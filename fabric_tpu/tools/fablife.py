"""fablife — resource-lifetime + wire-trust analyzer for fabric-tpu.

fablint pins per-file syntax invariants, fabdep the import graph and
lock discipline, fabflow value ranges and mask soundness, fabreg the
declarative metadata tables.  The failure class none of them models is
the one that kills long soaks: *lifetimes*.  The bug ledger since PR 8
is a lifetime ledger — a sidecar ``stop()`` that never woke its
``accept()`` thread (2s eaten per teardown, PR 10), conn threads and
``_conns`` bookkeeping leaked per reconnecting client, serve-socket
tempdirs never ``rmtree``'d, and QoS lane leak/double-free that PR 14
could only prove absent with *runtime* acquired/released counters.
fablife is the static twin of those counters: every acquire must reach
its release on every path, checked at parse time, before the fleet soak
scales to ≥8 peers for hours.

Like its siblings it is pure ``ast`` on the shared ``tools/toolkit.py``
chassis: it never imports analyzed code and runs without
numpy/jax/cryptography.

Rules
-----
Lifetime family (path-sensitive must-analysis, the fabflow
mask-fail-open mold):

thread-unjoined     a ``Thread.start()`` with no join reachable from
                    the owning scope: a started thread bound to a local
                    must be ``join()``-ed (or handed onward) in that
                    function; one stored on ``self.<attr>`` (directly
                    or via an ``append`` to a thread-list attr) must be
                    joined somewhere in the owning class — the
                    ``stop()``/``close()``/``__exit__`` teardown
                    family; an *unbound* ``Thread(...).start()`` can
                    never be joined and always fires.
fd-leak             a ``socket.socket``/``create_connection``/``open``/
                    ``tempfile.mkdtemp``/``TemporaryDirectory`` acquire
                    whose release (``close``/``rmtree``/``cleanup``) is
                    not guaranteed on exception edges: ``with``,
                    ``try/finally``, a registered cleanup
                    (``atexit.register``/``addCleanup``/
                    ``addfinalizer``/``ExitStack``), a generator
                    releasing after its ``yield`` (the pytest-fixture
                    idiom), or an ownership hand-off (returned, stored
                    on the owner, passed onward) all satisfy.  A
                    release that merely *exists* on the straight-line
                    path does not: the exception edge still leaks.
                    Tempdir paths are never ownership-transferred by
                    passing them to a call — a path string travels
                    freely; the creator still owes the ``rmtree``.
lock-leak           a bare ``X.acquire()`` whose ``X.release()`` is not
                    inside a ``finally`` in the same function (``with
                    lock:`` is the sanctioned shape).
pair-imbalance      driven by the declarative pair table
                    ``tools/pairs.toml`` (ClassLedger
                    ``try_acquire``→``release``, pool
                    ``submit``→``resolve``/teardown, CooldownGate
                    ``ready``→``record_*``, batcher
                    ``try_submit``/``submit``→resolver called): every
                    acquire site must discharge its obligation on every
                    success path — in a ``finally``, on all paths of
                    the success region, or (weakest tier, for
                    split-phase designs like the dispatcher's
                    ``on_dispatch`` release hook) somewhere else in the
                    owning class.

Wire-trust family (intraprocedural taint from wire-decoded integers —
the exact ``retry_after_ms`` class fixed by hand in PR 8, where a u32
off the wire bought a server-controlled unbounded client sleep):

wire-unclamped      an integer sourced from ``struct.unpack`` / the
                    protocol reader (``u8``/``u16``/``u32``/``u64``) /
                    a ``decode_*`` frame helper flowing into
                    ``sleep``/a ``timeout=`` argument/``deque(maxlen=)``
                    /``bytearray``/sequence-repeat allocation without
                    passing through ``min``/``clamp`` first.
blocking-unbudgeted a ``recv``/``join``/``get``/``wait``/``result``
                    with no timeout on the serve/router/batcher request
                    paths (``fabric_tpu/serve/*``,
                    ``parallel/batcher.py``) — every per-hop wait must
                    derive from a budget (the fabtail discipline as a
                    checked invariant).  ``recv`` is exempted when the
                    enclosing function also wields
                    ``settimeout``/``select`` (the bounded-demux
                    shape).

Suppression
-----------
Per line, toolkit grammar: ``# fablife: disable=rule-id  # <reason>``.
The reason must name the by-design release path (enforced by review +
the NOTES_BUILD triage ledger, like fabflow's computed-bound
discipline).

Usage
-----
    python -m fabric_tpu.tools.fablife [--json] [--list-rules]
        [--rules a,b] [--pairs FILE] PATH...

Exit status: 0 = clean, 1 = findings, 2 = usage/IO/pair-table error
(a half-read pair table checking nothing would be silent drift — parse
errors are loud by design).
"""

from __future__ import annotations

import ast
import json
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import (  # noqa: F401 - re-exported API
    DEFAULT_EXCLUDES,
    FileContext,
    Finding,
    iter_py_files,
)

__version__ = "1.0"

RULES: Dict[str, str] = {
    "thread-unjoined": (
        "Thread.start() with no join reachable from the owning scope "
        "(function-local join, or a join anywhere in the owning class "
        "for self-attr / thread-list threads)"
    ),
    "fd-leak": (
        "socket/open/mkdtemp/TemporaryDirectory acquired without a "
        "release guaranteed on exception edges (with, try/finally, "
        "registered cleanup, fixture-after-yield, or ownership "
        "hand-off)"
    ),
    "lock-leak": (
        "bare X.acquire() whose X.release() is not in a finally in the "
        "same function (use `with lock:`)"
    ),
    "pair-imbalance": (
        "a tools/pairs.toml acquire (ClassLedger try_acquire, pool "
        "submit, CooldownGate ready, batcher try_submit/submit) whose "
        "release is not reached on every success path"
    ),
    "wire-unclamped": (
        "wire-decoded integer (struct.unpack / reader u8-u64 / "
        "decode_*) flows into sleep/timeout/deque(maxlen)/allocation "
        "size without a min/clamp"
    ),
    "blocking-unbudgeted": (
        "recv/join/get/wait/result with no timeout on the "
        "serve/router/batcher request paths (every per-hop wait must "
        "derive from a budget)"
    ),
}

#: lifetime + wire rules pin the runtime package; the tempdir facet of
#: fd-leak additionally covers tests/ and bench.py — a leaked fd dies
#: with the test process, a leaked /tmp dir accumulates across every CI
#: run of an hours-long soak.
PKG_SCOPE = ("*fabric_tpu/*",)
REQUEST_SCOPE = ("*fabric_tpu/serve/*", "*fabric_tpu/parallel/batcher.py")

_WIRE_SOURCE_LEAVES = {"u8", "u16", "u32", "u64", "unpack", "unpack_from"}
_WIRE_SANITIZERS = {"min", "clamp"}
_TIMEOUT_KWARGS = {"timeout", "maxlen"}
#: leaves whose FIRST positional is a timeout; ``get`` is excluded (its
#: first positional is a dict key / block flag — its timeout is the
#: second positional, handled separately)
_TIMEOUT_POSITION_LEAVES = {"join", "wait"}
_ALLOC_LEAVES = {"bytearray", "deque"}

_BLOCKING_LEAVES = {"join", "wait", "get", "result"}
_RECV_LEAVES = {"recv", "recv_into"}
_RECV_BOUNDING_LEAVES = {"settimeout", "setblocking", "select", "poll"}

_CLEANUP_REG_LEAVES = {
    "register", "addCleanup", "addfinalizer", "finalize", "callback",
    "push", "enter_context",
}


# --------------------------------------------------------------------------
# pairs.toml
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class PairSpec:
    name: str
    acquire: str
    release: Tuple[str, ...]
    base_like: Tuple[str, ...]
    mode: str  # "base" | "result"
    conditional: bool
    doc: str = ""


def default_pairs_file() -> Path:
    return Path(__file__).resolve().parent / "pairs.toml"


_LIST_RE = re.compile(r"^\[(.*)\]$")


def _parse_toml_value(raw: str, where: str):
    raw = raw.strip()
    if raw in ("true", "false"):
        return raw == "true"
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    m = _LIST_RE.match(raw)
    if m:
        inner = m.group(1).strip()
        if not inner:
            return []
        items = []
        for part in inner.split(","):
            part = part.strip()
            if not (part.startswith('"') and part.endswith('"')):
                raise ValueError(f"{where}: list items must be \"quoted\"")
            items.append(part[1:-1])
        return items
    raise ValueError(f"{where}: expected \"string\", [list] or true/false")


def parse_pairs(text: str, path: str = "<pairs>") -> List[PairSpec]:
    """Parse the tiny TOML subset the analyzers already use for
    layers.toml, extended with ``[[pair]]`` array-of-tables.  LOUD on
    any malformed line: a half-read pair table silently checking
    nothing would be config drift."""
    entries: List[Dict[str, object]] = []
    current: Optional[Dict[str, object]] = None
    for n, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[pair]]":
            current = {}
            entries.append(current)
            continue
        if line.startswith("["):
            raise ValueError(f"{path}:{n}: unknown section {line!r}")
        if "=" not in line:
            raise ValueError(f"{path}:{n}: expected 'key = value'")
        if current is None:
            raise ValueError(f"{path}:{n}: key outside a [[pair]] entry")
        key, _, value = line.partition("=")
        key = key.strip()
        if "#" in value and not value.strip().startswith('"'):
            value = value.split("#", 1)[0]
        current[key] = _parse_toml_value(value, f"{path}:{n}")
    specs: List[PairSpec] = []
    seen: Set[str] = set()
    for i, e in enumerate(entries, start=1):
        where = f"{path}: [[pair]] #{i}"
        for req in ("name", "acquire", "release", "mode"):
            if req not in e:
                raise ValueError(f"{where}: missing required key {req!r}")
        name = e["name"]
        if not isinstance(name, str) or not name:
            raise ValueError(f"{where}: name must be a non-empty string")
        if name in seen:
            raise ValueError(f"{where}: duplicate pair name {name!r}")
        seen.add(name)
        mode = e["mode"]
        if mode not in ("base", "result"):
            raise ValueError(
                f"{where}: mode must be \"base\" or \"result\", got {mode!r}"
            )
        release = e["release"]
        if isinstance(release, str):
            release = [release]
        if not isinstance(release, list):
            raise ValueError(f"{where}: release must be a list of strings")
        if mode == "base" and not release:
            raise ValueError(
                f"{where}: mode \"base\" requires at least one release leaf"
            )
        base_like = e.get("base_like", [])
        if isinstance(base_like, str):
            base_like = [base_like]
        acquire = e["acquire"]
        if not isinstance(acquire, str) or not acquire:
            raise ValueError(f"{where}: acquire must be a non-empty string")
        specs.append(
            PairSpec(
                name=name,
                acquire=acquire,
                release=tuple(release),
                base_like=tuple(s.lower() for s in base_like),
                mode=str(mode),
                conditional=bool(e.get("conditional", False)),
                doc=str(e.get("doc", "")),
            )
        )
    return specs


def load_default_pairs() -> List[PairSpec]:
    f = default_pairs_file()
    return parse_pairs(f.read_text(encoding="utf-8"), str(f))


# --------------------------------------------------------------------------
# AST helpers
# --------------------------------------------------------------------------

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _leaf(dn: Optional[str]) -> str:
    return (dn or "").rsplit(".", 1)[-1]


def _call_base(node: ast.Call) -> Optional[str]:
    """For ``a.b.c(...)`` the receiver ``a.b``; None for bare names."""
    if isinstance(node.func, ast.Attribute):
        return _dotted(node.func.value)
    return None


def _own_nodes(fn: ast.AST):
    """Walk a scope's own body, not nested function/lambda bodies."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, _NESTED):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _all_nodes(fn: ast.AST):
    """Everything below ``fn`` including nested defs/lambdas (release
    evidence: a discharge inside a callback defined here still counts)."""
    yield from ast.walk(fn)


def _mentions_name(node: ast.AST, names: Set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(node)
    )


def _self_attr(node: ast.AST) -> Optional[str]:
    """'attr' for a ``self.attr`` expression."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _own_finally_bodies(fn: ast.AST):
    for n in _own_nodes(fn):
        if isinstance(n, ast.Try) and n.finalbody:
            yield n.finalbody
    if isinstance(fn, ast.Try) and fn.finalbody:  # pragma: no cover
        yield fn.finalbody


def _is_generator(fn: ast.AST) -> bool:
    return any(
        isinstance(n, (ast.Yield, ast.YieldFrom)) for n in _own_nodes(fn)
    )


# --------------------------------------------------------------------------
# Path engine: does every path through a region hit the predicate?
# --------------------------------------------------------------------------
# Three-valued sequence status:
#   "hit"  — every path through the sequence discharges the obligation
#   "miss" — some path EXITS (return/raise) without discharging
#   "fall" — some path falls off the end undischarged (keep scanning
#            the continuation)


def _stmt_status(s: ast.stmt, pred) -> str:
    # the statement NODE itself can discharge (a `for f in futures:`
    # loop consuming result handles) — predicates never match compound
    # containers like If/Try, so this cannot over-credit branches
    if pred(s):
        return "hit"
    if isinstance(s, ast.If):
        b = _seq_status(s.body, pred)
        o = _seq_status(s.orelse, pred)
        if "miss" in (b, o):
            return "miss"
        if b == "hit" and o == "hit":
            return "hit"
        return "fall"
    if isinstance(s, ast.Try):
        if _seq_status(s.finalbody, pred) == "hit":
            return "hit"  # finally dominates every exit
        body = _seq_status(list(s.body) + list(s.orelse), pred)
        hs = [_seq_status(h.body, pred) for h in s.handlers]
        if body == "miss" or "miss" in hs:
            return "miss"
        if body == "hit" and hs and all(h == "hit" for h in hs):
            return "hit"
        return "fall"
    if isinstance(s, (ast.With, ast.AsyncWith)):
        return _seq_status(s.body, pred)
    if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
        body = _seq_status(list(s.body) + list(s.orelse), pred)
        # the loop may run zero times: a body hit cannot promote to
        # "hit", but a body exit-without-release is still a miss
        return "miss" if body == "miss" else "fall"
    # simple statement: predicate anywhere inside discharges (covers
    # `return release(...)` and callback-carrying calls)
    for n in ast.walk(s):
        if pred(n):
            return "hit"
    if isinstance(s, (ast.Return, ast.Raise)):
        return "miss"
    return "fall"


def _seq_status(stmts: Sequence[ast.stmt], pred) -> str:
    for s in stmts:
        st = _stmt_status(s, pred)
        if st in ("hit", "miss"):
            return st
        if isinstance(s, (ast.Return, ast.Raise)):
            return "miss"  # terminal without a hit
    return "fall"


def _segments_hit(segments: Sequence[Sequence[ast.stmt]], pred) -> bool:
    """Fold continuation segments: True iff every path is discharged
    before the function falls off the end."""
    for seg in segments:
        st = _seq_status(seg, pred)
        if st == "hit":
            return True
        if st == "miss":
            return False
    return False  # fell off the function end undischarged


def _locate(
    stmts: Sequence[ast.stmt], target: ast.AST,
    conts: List[List[ast.stmt]],
) -> Optional[Tuple[ast.stmt, List[ast.stmt], List[List[ast.stmt]]]]:
    """Find the statement in (possibly nested) ``stmts`` whose subtree
    contains ``target``; returns (stmt, local tail, outer
    continuations)."""
    for i, s in enumerate(stmts):
        if any(n is target for n in ast.walk(s)):
            tail = list(stmts[i + 1:])
            # nested? descend into compound bodies first
            for fieldname in ("body", "orelse", "finalbody"):
                sub = getattr(s, fieldname, None)
                if isinstance(sub, list) and sub:
                    hit = _locate(sub, target, [tail] + conts)
                    if hit is not None:
                        # only descend when target is in the sub-body,
                        # not e.g. in an If test
                        if any(
                            any(n is target for n in ast.walk(x))
                            for x in sub
                        ):
                            return hit
            for h in getattr(s, "handlers", []) or []:
                if any(
                    any(n is target for n in ast.walk(x)) for x in h.body
                ):
                    hit = _locate(h.body, target, [tail] + conts)
                    if hit is not None:
                        return hit
            return s, tail, conts
    return None


def _success_segments(
    fn: ast.AST, acq: ast.Call, result_var: Optional[str],
    conditional: bool,
) -> Optional[List[List[ast.stmt]]]:
    """The statement segments a *successful* acquire flows through.
    None means the obligation is satisfied structurally (acquire inside
    a return/handed straight onward)."""
    loc = _locate(list(fn.body), acq, [])
    if loc is None:
        return None
    s, tail, conts = loc
    segs: List[List[ast.stmt]] = []
    if isinstance(s, (ast.Return, ast.Yield)) or (
        isinstance(s, ast.Expr)
        and isinstance(s.value, (ast.Yield, ast.YieldFrom))
    ):
        return None  # handed to the caller/consumer
    if (
        isinstance(s, (ast.If, ast.While))
        and any(n is acq for n in ast.walk(s.test))
        and conditional
    ):
        if isinstance(s.test, ast.UnaryOp) and isinstance(
            s.test.op, ast.Not
        ):
            segs = [tail]  # `if not acquire(): bail` — success is after
        else:
            segs = [list(s.body), tail]
    elif (
        conditional
        and result_var is not None
        and tail
        and isinstance(tail[0], ast.If)
        and _mentions_name(tail[0].test, {result_var})
    ):
        guard = tail[0]
        rest = tail[1:]
        test = guard.test
        negated = (
            isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not)
        ) or (
            isinstance(test, ast.Compare)
            and any(isinstance(op, ast.Is) for op in test.ops)
            and any(
                isinstance(c, ast.Constant) and c.value is None
                for c in test.comparators
            )
        )
        if negated:
            segs = [rest]  # `if r is None: bail` / `if not r: bail`
        else:
            segs = [list(guard.body), rest]
    else:
        segs = [[s], tail]
    return segs + conts


# --------------------------------------------------------------------------
# Per-class evidence (threads / resources stored on self)
# --------------------------------------------------------------------------


@dataclass
class ClassFacts:
    node: ast.ClassDef
    #: attrs with a direct ``self.A.join(`` anywhere in the class
    joined_attrs: Set[str] = field(default_factory=set)
    #: attrs iterated by a ``for v in <... self.A ...>: v.join()`` loop
    loop_joined_attrs: Set[str] = field(default_factory=set)
    #: attr -> release leaves seen on ``self.A.<leaf>(`` / rmtree args
    released_attrs: Dict[str, Set[str]] = field(default_factory=dict)
    #: release leaves seen anywhere in the class (pair weak tier)
    release_leaves: Set[str] = field(default_factory=set)


def _collect_class_facts(cls: ast.ClassDef) -> ClassFacts:
    facts = ClassFacts(cls)
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # local alias map: name -> self-attrs its RHS mentions
        aliases: Dict[str, Set[str]] = {}
        for n in _all_nodes(method):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and (
                isinstance(n.targets[0], ast.Name)
            ):
                attrs = {
                    a
                    for sub in ast.walk(n.value)
                    if (a := _self_attr(sub)) is not None
                }
                if attrs:
                    aliases[n.targets[0].id] = attrs
        for n in _all_nodes(method):
            if isinstance(n, ast.Call):
                leaf = _leaf(_dotted(n.func))
                base = _call_base(n)
                facts.release_leaves.add(leaf)
                if base is not None and base.startswith("self."):
                    attr = base[len("self."):].split(".", 1)[0]
                    if leaf == "join":
                        facts.joined_attrs.add(attr)
                    facts.released_attrs.setdefault(attr, set()).add(leaf)
                elif base is not None and "." not in base and (
                    base in aliases
                ):
                    # `t = self._thread; t.join()` — the alias carries
                    # the release to the attr it was read from
                    if leaf == "join":
                        facts.joined_attrs |= aliases[base]
                    for attr in aliases[base]:
                        facts.released_attrs.setdefault(attr, set()).add(
                            leaf
                        )
                if leaf == "rmtree":
                    for arg in n.args:
                        for sub in ast.walk(arg):
                            a = _self_attr(sub)
                            if a is not None:
                                facts.released_attrs.setdefault(
                                    a, set()
                                ).add("rmtree")
            if isinstance(n, (ast.For, ast.AsyncFor)) and isinstance(
                n.target, ast.Name
            ):
                v = n.target.id
                body_joins = any(
                    isinstance(c, ast.Call)
                    and _leaf(_dotted(c.func)) == "join"
                    and _call_base(c) == v
                    for b in n.body
                    for c in ast.walk(b)
                )
                if not body_joins:
                    continue
                iter_attrs: Set[str] = set()
                for sub in ast.walk(n.iter):
                    a = _self_attr(sub)
                    if a is not None:
                        iter_attrs.add(a)
                    if isinstance(sub, ast.Name) and sub.id in aliases:
                        iter_attrs |= aliases[sub.id]
                facts.loop_joined_attrs |= iter_attrs
    return facts


# --------------------------------------------------------------------------
# Per-file analysis
# --------------------------------------------------------------------------


class _FileAnalyzer:
    def __init__(
        self,
        path: str,
        tree: ast.Module,
        pairs: Sequence[PairSpec],
        active: Set[str],
    ) -> None:
        self.path = path
        self.tree = tree
        self.pairs = pairs
        self.active = active
        self.ctx = FileContext(path)
        self.findings: List[Finding] = []
        self.in_pkg = self.ctx.matches(PKG_SCOPE)
        self.on_request_path = self.ctx.matches(REQUEST_SCOPE)
        self._class_facts: Dict[ast.ClassDef, ClassFacts] = {}
        #: names bound at module level — a pair base rooted in one is
        #: owned by the MODULE, so a release anywhere in the file is
        #: its owning-scope evidence (the _POOL_GATE shape)
        self._module_globals: Set[str] = set()
        for stmt in tree.body:
            targets: List[ast.expr] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    self._module_globals.add(t.id)

    # -- orchestration ------------------------------------------------------

    def run(self) -> List[Finding]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._class_facts[node] = _collect_class_facts(node)
        scopes: List[Tuple[ast.AST, Optional[ast.ClassDef]]] = [
            (self.tree, None)
        ]
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        scopes.append((item, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not any(
                    node in getattr(c, "body", ())
                    for c in self._class_facts
                ):
                    scopes.append((node, None))
        for fn, cls in scopes:
            if self.in_pkg and "thread-unjoined" in self.active:
                self._check_threads(fn, cls)
            if "fd-leak" in self.active:
                self._check_fds(fn, cls)
            if self.in_pkg and "lock-leak" in self.active:
                self._check_locks(fn)
            if self.in_pkg and "pair-imbalance" in self.active:
                self._check_pairs(fn, cls)
            if self.in_pkg and "wire-unclamped" in self.active:
                self._check_wire(fn)
            if self.on_request_path and (
                "blocking-unbudgeted" in self.active
            ):
                self._check_blocking(fn)
        return self.findings

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                rule, self.path, getattr(node, "lineno", 1),
                getattr(node, "col_offset", 0), msg,
            )
        )

    # -- thread-unjoined ----------------------------------------------------

    def _check_threads(
        self, fn: ast.AST, cls: Optional[ast.ClassDef]
    ) -> None:
        facts = self._class_facts.get(cls) if cls is not None else None
        thread_locals: Set[str] = set()
        attr_threads: Dict[str, ast.AST] = {}
        starts: List[Tuple[ast.Call, Optional[str], Optional[str]]] = []
        # (start call, local name or None, attr name or None)
        for n in _own_nodes(fn):
            if isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Call
            ) and _leaf(_dotted(n.value.func)) == "Thread":
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        thread_locals.add(t.id)
                    a = _self_attr(t)
                    if a is not None:
                        attr_threads[a] = n
        for n in _own_nodes(fn):
            if not (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "start"
            ):
                continue
            recv = n.func.value
            if isinstance(recv, ast.Call) and (
                _leaf(_dotted(recv.func)) == "Thread"
            ):
                starts.append((n, None, None))  # unbound: never joinable
            elif isinstance(recv, ast.Name) and recv.id in thread_locals:
                starts.append((n, recv.id, None))
            else:
                a = _self_attr(recv)
                if a is not None and a in attr_threads:
                    starts.append((n, None, a))
        if not starts:
            return

        for site, local, attr in starts:
            if local is not None:
                verdict = self._local_thread_ok(fn, cls, local)
            elif attr is not None:
                verdict = self._attr_thread_ok(facts, attr)
            else:
                verdict = (
                    "an unbound Thread(...).start() can never be joined: "
                    "bind it and join it from the owner's teardown, or "
                    "register it on the owner's thread list"
                )
            if verdict is not None:
                self._emit(
                    "thread-unjoined", site,
                    f"started thread has no reachable join: {verdict}",
                )

    def _local_thread_ok(
        self, fn: ast.AST, cls: Optional[ast.ClassDef], name: str
    ) -> Optional[str]:
        facts = self._class_facts.get(cls) if cls is not None else None
        # alias chain: t = _thread; t.join(...) joins the same thread
        aliases: Set[str] = {name}
        grew = True
        while grew:
            grew = False
            for n in _all_nodes(fn):
                if isinstance(n, ast.Assign) and isinstance(
                    n.value, ast.Name
                ) and n.value.id in aliases:
                    for t in n.targets:
                        if isinstance(t, ast.Name) and t.id not in aliases:
                            aliases.add(t.id)
                            grew = True
        joined_local_containers: Set[str] = set()
        for n in _all_nodes(fn):
            if isinstance(n, (ast.For, ast.AsyncFor)) and isinstance(
                n.target, ast.Name
            ):
                v = n.target.id
                if any(
                    isinstance(c, ast.Call)
                    and _leaf(_dotted(c.func)) == "join"
                    and _call_base(c) == v
                    for b in n.body
                    for c in ast.walk(b)
                ):
                    for sub in ast.walk(n.iter):
                        if isinstance(sub, ast.Name):
                            joined_local_containers.add(sub.id)
        for n in _all_nodes(fn):
            if isinstance(n, ast.Call):
                leaf = _leaf(_dotted(n.func))
                base = _call_base(n)
                if leaf == "join" and base in aliases:
                    return None
                if leaf in ("append", "add", "put") and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in n.args
                ):
                    # registered on a thread list: the list's join loop
                    # is the join
                    if isinstance(n.func, ast.Attribute):
                        recv = n.func.value
                        a = _self_attr(recv)
                        if a is not None:
                            if facts is not None and (
                                a in facts.loop_joined_attrs
                                or a in facts.joined_attrs
                            ):
                                return None
                            return (
                                f"registered on self.{a} but no method "
                                f"of the owning class joins self.{a}'s "
                                f"elements (stop()/close() must drain "
                                f"the list)"
                            )
                        if (
                            isinstance(recv, ast.Name)
                            and recv.id in joined_local_containers
                        ):
                            return None
                        return (
                            "registered on a container that is never "
                            "join-drained in this function"
                        )
                elif any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in list(n.args)
                    + [k.value for k in n.keywords]
                ) and leaf not in ("start", "Thread"):
                    return None  # handed onward: ownership transferred
            if isinstance(n, (ast.Return, ast.Yield)) and (
                n.value is not None
                and _mentions_name(n.value, {name})
            ):
                return None  # returned/yielded to the caller
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if _self_attr(t) is not None and isinstance(
                        n.value, ast.Name
                    ) and n.value.id == name:
                        a = _self_attr(t)
                        if facts is not None and a is not None and (
                            a in facts.joined_attrs
                            or a in facts.loop_joined_attrs
                        ):
                            return None
                        return (
                            f"stored on self.{a} but no method of the "
                            f"owning class joins it"
                        )
                    if (
                        isinstance(t, ast.Attribute)
                        and _self_attr(t) is None
                        and isinstance(n.value, ast.Name)
                        and n.value.id == name
                    ):
                        return None  # stored on another owner object
                    if isinstance(t, ast.Subscript) and isinstance(
                        n.value, ast.Name
                    ) and n.value.id == name:
                        return None
        return (
            f"local thread {name!r} is neither joined, registered on a "
            f"joined thread list, nor handed onward in this function"
        )

    def _attr_thread_ok(
        self, facts: Optional[ClassFacts], attr: str
    ) -> Optional[str]:
        if facts is not None and (
            attr in facts.joined_attrs or attr in facts.loop_joined_attrs
        ):
            return None
        return (
            f"self.{attr} is started but no method of the owning class "
            f"joins it (the stop()/close()/__exit__ family must)"
        )

    # -- fd-leak ------------------------------------------------------------

    def _acquire_kind(self, call: ast.Call) -> Optional[str]:
        dn = _dotted(call.func)
        leaf = _leaf(dn)
        if dn in ("socket.socket", "socket.create_connection"):
            return "socket"
        if dn in ("open", "io.open"):
            return "file"
        if leaf == "mkdtemp":
            return "tempdir"
        if leaf == "TemporaryDirectory":
            return "tempdirobj"
        return None

    def _check_fds(self, fn: ast.AST, cls: Optional[ast.ClassDef]) -> None:
        acquires: List[Tuple[ast.Call, str]] = []
        for n in _own_nodes(fn):
            if isinstance(n, ast.Call):
                kind = self._acquire_kind(n)
                if kind is None:
                    continue
                if kind in ("socket", "file") and not self.in_pkg:
                    continue  # fd facets pin the package only
                acquires.append((n, kind))
        if not acquires:
            return
        with_items: List[ast.AST] = []
        for n in _own_nodes(fn):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    with_items.extend(ast.walk(item.context_expr))
        generator = _is_generator(fn)
        facts = self._class_facts.get(cls) if cls is not None else None

        for call, kind in acquires:
            if any(call is w for w in with_items):
                continue
            verdict = self._fd_verdict(fn, cls, facts, call, kind, generator)
            if verdict is not None:
                self._emit("fd-leak", call, verdict)

    def _fd_verdict(
        self,
        fn: ast.AST,
        cls: Optional[ast.ClassDef],
        facts: Optional[ClassFacts],
        call: ast.Call,
        kind: str,
        generator: bool,
    ) -> Optional[str]:
        # find the binding statement
        bound: Set[str] = set()
        attr_target: Optional[str] = None
        for n in _own_nodes(fn):
            if isinstance(n, ast.Assign) and any(
                x is call for x in ast.walk(n.value)
            ):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
                    a = _self_attr(t)
                    if a is not None:
                        attr_target = a
            if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) and (
                getattr(n, "value", None) is not None
                and any(x is call for x in ast.walk(n.value))
            ):
                return None  # handed straight to the caller/consumer
        noun = {
            "socket": "socket", "file": "file handle",
            "tempdir": "tempdir", "tempdirobj": "TemporaryDirectory",
        }[kind]
        if attr_target is not None:
            rel = (
                facts.released_attrs.get(attr_target, set())
                if facts is not None
                else set()
            )
            ok = {
                "socket": {"close", "shutdown"},
                "file": {"close"},
                "tempdir": {"rmtree"},
                "tempdirobj": {"cleanup"},
            }[kind]
            if rel & ok:
                return None
            return (
                f"{noun} stored on self.{attr_target} but no method of "
                f"the owning class releases it "
                f"({'/'.join(sorted(ok))}) — the teardown family must"
            )
        if not bound:
            if kind in ("socket", "file"):
                return None  # consumed by another call: handed onward
            return (
                f"{noun} created and its path immediately dropped: "
                f"nothing can ever rmtree it — bind the path and "
                f"release it in a finally"
            )
        names = set(bound)
        # alias chains: s2 = s
        for n in _own_nodes(fn):
            if isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Name
            ) and n.value.id in names:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)

        def released(node: ast.AST) -> bool:
            if not isinstance(node, ast.Call):
                return False
            leaf = _leaf(_dotted(node.func))
            base = _call_base(node)
            if kind in ("socket", "file"):
                return leaf in ("close", "shutdown") and base in names
            if kind == "tempdirobj":
                return leaf == "cleanup" and base in names
            return leaf == "rmtree" and any(
                _mentions_name(a, names) for a in node.args
            )

        for body in _own_finally_bodies(fn):
            if any(released(x) for s in body for x in ast.walk(s)):
                return None
        release_anywhere = any(released(n) for n in _all_nodes(fn))
        if generator and release_anywhere:
            # pytest-fixture idiom: teardown after yield runs on test
            # failure too
            return None
        for n in _all_nodes(fn):
            if isinstance(n, ast.Call):
                leaf = _leaf(_dotted(n.func))
                args = list(n.args) + [k.value for k in n.keywords]
                if leaf in _CLEANUP_REG_LEAVES and any(
                    _mentions_name(a, names) for a in args
                ):
                    return None  # registered cleanup
                if kind in ("socket", "file") and not released(n):
                    if leaf not in ("close", "shutdown") and any(
                        isinstance(a, ast.Name) and a.id in names
                        for a in args
                    ):
                        return None  # fd handed onward: new owner
            if isinstance(n, (ast.Return, ast.Yield)) and (
                n.value is not None and _mentions_name(n.value, names)
            ):
                return None  # ownership to the caller/consumer
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if (
                        _self_attr(t) is not None
                        or isinstance(t, ast.Subscript)
                    ) and _mentions_name(n.value, names):
                        return None  # stored on an owner
        if release_anywhere:
            return (
                f"{noun} is released on the straight-line path only — "
                f"an exception between acquire and release leaks it; "
                f"move the release into a finally (or use with)"
            )
        rel_name = {
            "socket": "close()", "file": "close()",
            "tempdir": "shutil.rmtree(...)", "tempdirobj": "cleanup()",
        }[kind]
        return (
            f"{noun} acquired but never released in this function: "
            f"{rel_name} in a finally, a with block, a registered "
            f"cleanup, or an ownership hand-off is required"
        )

    # -- lock-leak ----------------------------------------------------------

    def _check_locks(self, fn: ast.AST) -> None:
        for n in _own_nodes(fn):
            if not (
                isinstance(n, ast.Call)
                and _leaf(_dotted(n.func)) == "acquire"
                and isinstance(n.func, ast.Attribute)
            ):
                continue
            base = _call_base(n)
            if base is None:
                continue

            def release_pred(x: ast.AST, b=base) -> bool:
                return (
                    isinstance(x, ast.Call)
                    and _leaf(_dotted(x.func)) == "release"
                    and _call_base(x) == b
                )

            in_finally = any(
                any(release_pred(x) for s in body for x in ast.walk(s))
                for body in _own_finally_bodies(fn)
            )
            if not in_finally:
                self._emit(
                    "lock-leak", n,
                    f"bare {base}.acquire() without {base}.release() in "
                    f"a finally in this function — an exception between "
                    f"them wedges every later acquirer (use `with "
                    f"{base}:`)",
                )

    # -- pair-imbalance -----------------------------------------------------

    def _check_pairs(
        self, fn: ast.AST, cls: Optional[ast.ClassDef]
    ) -> None:
        facts = self._class_facts.get(cls) if cls is not None else None
        for n in _own_nodes(fn):
            if not (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
            ):
                continue
            leaf = _leaf(_dotted(n.func))
            base = _call_base(n)
            if base is None:
                continue
            for spec in self.pairs:
                if leaf != spec.acquire:
                    continue
                if spec.base_like and not any(
                    s in base.lower() for s in spec.base_like
                ):
                    continue
                verdict = self._pair_verdict(fn, facts, n, base, spec)
                if verdict is not None:
                    self._emit(
                        "pair-imbalance", n,
                        f"[{spec.name}] {base}.{spec.acquire}(...) "
                        f"{verdict}",
                    )

    def _pair_verdict(
        self,
        fn: ast.AST,
        facts: Optional[ClassFacts],
        acq: ast.Call,
        base: str,
        spec: PairSpec,
    ) -> Optional[str]:
        if spec.mode == "base":
            def pred(x: ast.AST) -> bool:
                return (
                    isinstance(x, ast.Call)
                    and _leaf(_dotted(x.func)) in spec.release
                    and _call_base(x) == base
                )

            for body in _own_finally_bodies(fn):
                if any(pred(x) for s in body for x in ast.walk(s)):
                    return None
            segs = _success_segments(fn, acq, None, spec.conditional)
            if segs is None or _segments_hit(segs, pred):
                return None
            # weakest tier: a split-phase release elsewhere in the
            # owning class (dispatcher hooks, drain paths)
            if any(pred(x) for x in _all_nodes(fn)):
                leak = "a success path misses the release"
            else:
                leak = "no release in this function"
            if facts is not None and (
                set(spec.release) & facts.release_leaves
            ):
                return None
            if base.split(".", 1)[0] in self._module_globals and any(
                pred(x) for x in ast.walk(self.tree)
            ):
                return None  # module-owned base, released in this file
            return (
                f"{leak} and no {'/'.join(spec.release)} anywhere in "
                f"the owning scope: every success path must discharge "
                f"the obligation ({spec.doc})"
            )

        # mode == "result": the returned obligation must be called or
        # handed onward
        result_var: Optional[str] = None
        for n in _own_nodes(fn):
            if isinstance(n, ast.Assign) and any(
                x is acq for x in ast.walk(n.value)
            ):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        result_var = t.id
                if any(_self_attr(t) is not None for t in n.targets):
                    return None  # stored on the owner: split-phase
            if isinstance(n, (ast.Return, ast.Yield)) and (
                getattr(n, "value", None) is not None
                and any(x is acq for x in ast.walk(n.value))
            ):
                return None  # handed straight to the caller
            if (
                isinstance(n, ast.Call)
                and n is not acq
                and any(x is acq for x in ast.walk(n))
            ):
                return None  # consumed by another call
        if result_var is not None:
            for n in ast.walk(fn):
                if isinstance(n, _NESTED) and n is not fn and (
                    _mentions_name(n, {result_var})
                ):
                    # captured by a closure defined here (the
                    # futures-resolved-by-returned-resolve shape):
                    # the closure is the new owner
                    return None
        if result_var is None:
            return (
                f"drops its result: the obligation (resolver/handle) is "
                f"lost the moment it is created ({spec.doc})"
            )

        rv = result_var

        def pred(x: ast.AST) -> bool:
            if isinstance(x, ast.Call):
                if (
                    isinstance(x.func, ast.Name) and x.func.id == rv
                ):
                    return True  # resolver()
                if _leaf(_dotted(x.func)) in spec.release:
                    # a declared release leaf discharges whatever is
                    # outstanding, receiver or bare teardown helper
                    # (shutdown_pool(broken=True) on the failure edge)
                    return True
                if any(
                    isinstance(a, ast.Name) and a.id == rv
                    for a in list(x.args)
                    + [k.value for k in x.keywords]
                ):
                    return True  # handed onward
            if isinstance(x, (ast.Return, ast.Yield)) and (
                getattr(x, "value", None) is not None
                and _mentions_name(x.value, {rv})
            ):
                return True
            if isinstance(x, ast.Assign) and (
                any(
                    _self_attr(t) is not None
                    or isinstance(t, ast.Subscript)
                    for t in x.targets
                )
                and _mentions_name(x.value, {rv})
            ):
                return True
            if isinstance(x, (ast.For, ast.AsyncFor)) and _mentions_name(
                x.iter, {rv}
            ):
                return True  # `for f in futures:` consumes the handles
            if isinstance(x, ast.comprehension) and _mentions_name(
                x.iter, {rv}
            ):
                return True
            return False

        for body in _own_finally_bodies(fn):
            if any(pred(x) for s in body for x in ast.walk(s)):
                return None
        segs = _success_segments(fn, acq, rv, spec.conditional)
        if segs is None or _segments_hit(segs, pred):
            return None
        return (
            f"has a success path where the result is neither called "
            f"nor handed onward ({spec.doc})"
        )

    # -- wire-unclamped -----------------------------------------------------

    def _check_wire(self, fn: ast.AST) -> None:
        tainted: Set[str] = set()

        def is_source(call: ast.Call) -> bool:
            leaf = _leaf(_dotted(call.func))
            return leaf in _WIRE_SOURCE_LEAVES or leaf.startswith(
                "decode_"
            )

        def expr_taint(e: Optional[ast.AST]) -> bool:
            if e is None:
                return False
            if isinstance(e, ast.Call):
                leaf = _leaf(_dotted(e.func))
                if leaf in _WIRE_SANITIZERS:
                    return False  # clamped
                if is_source(e):
                    return True
                return any(expr_taint(a) for a in e.args) or any(
                    expr_taint(k.value) for k in e.keywords
                )
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Lambda):
                return False
            return any(expr_taint(c) for c in ast.iter_child_nodes(e))

        def flag(node: ast.AST, what: str) -> None:
            self._emit(
                "wire-unclamped", node,
                f"wire-decoded integer flows into {what} without a "
                f"min/clamp: a u32 off the wire must never buy an "
                f"unbounded {what} (the PR 8 retry_after_ms class)",
            )

        for node in _walk_in_order(fn):
            if isinstance(node, ast.Assign):
                t0 = node.targets[0] if len(node.targets) == 1 else None
                if (
                    isinstance(t0, (ast.Tuple, ast.List))
                    and isinstance(node.value, (ast.Tuple, ast.List))
                    and len(t0.elts) == len(node.value.elts)
                ):
                    for t_el, v_el in zip(t0.elts, node.value.elts):
                        if isinstance(t_el, ast.Name):
                            if expr_taint(v_el):
                                tainted.add(t_el.id)
                            else:
                                tainted.discard(t_el.id)
                    continue
                is_t = expr_taint(node.value)
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Store
                        ):
                            if is_t:
                                tainted.add(sub.id)
                            else:
                                tainted.discard(sub.id)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name) and expr_taint(
                    node.value
                ):
                    tainted.add(node.target.id)
            elif isinstance(node, ast.Call):
                leaf = _leaf(_dotted(node.func))
                if leaf == "sleep" and node.args and expr_taint(
                    node.args[0]
                ):
                    flag(node, "sleep")
                if leaf in _TIMEOUT_POSITION_LEAVES and node.args and (
                    expr_taint(node.args[0])
                ):
                    flag(node, f"{leaf}() timeout")
                if leaf == "get" and len(node.args) >= 2 and expr_taint(
                    node.args[1]
                ):
                    flag(node, "get() timeout")
                if leaf in _ALLOC_LEAVES and any(
                    expr_taint(a) for a in node.args
                ):
                    flag(node, f"{leaf}() allocation size")
                for kw in node.keywords:
                    if kw.arg in _TIMEOUT_KWARGS and expr_taint(kw.value):
                        flag(node, f"{kw.arg}=")
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.Mult
            ):
                l, r = node.left, node.right
                for const, var in ((l, r), (r, l)):
                    if isinstance(
                        const, (ast.List, ast.Constant)
                    ) and (
                        not isinstance(const, ast.Constant)
                        or isinstance(const.value, (str, bytes))
                    ) and expr_taint(var):
                        flag(node, "sequence-repeat allocation size")
                        break

    # -- blocking-unbudgeted ------------------------------------------------

    def _check_blocking(self, fn: ast.AST) -> None:
        has_bounding = any(
            isinstance(n, ast.Call)
            and _leaf(_dotted(n.func)) in _RECV_BOUNDING_LEAVES
            for n in _all_nodes(fn)
        )
        for n in _own_nodes(fn):
            if not (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
            ):
                continue
            leaf = _leaf(_dotted(n.func))
            if leaf in _RECV_LEAVES:
                if not has_bounding:
                    self._emit(
                        "blocking-unbudgeted", n,
                        f"{leaf}() on a request path with no "
                        f"settimeout/select in the enclosing function: "
                        f"a silent peer stalls this hop forever — "
                        f"every wait must derive from the budget",
                    )
                continue
            if leaf not in _BLOCKING_LEAVES:
                continue
            has_timeout_kw = any(
                kw.arg == "timeout" for kw in n.keywords
            )
            if has_timeout_kw:
                continue
            if not n.args:
                self._emit(
                    "blocking-unbudgeted", n,
                    f"{leaf}() with no timeout on a request path: a "
                    f"wedged peer blocks this hop forever — pass a "
                    f"budget-derived timeout",
                )
            elif (
                len(n.args) == 1
                and isinstance(n.args[0], ast.Constant)
                and n.args[0].value is True
            ):
                self._emit(
                    "blocking-unbudgeted", n,
                    f"{leaf}(True) blocks without a timeout on a "
                    f"request path — pass a budget-derived timeout",
                )


def _walk_in_order(node: ast.AST):
    """Depth-first pre-order (source order) over a scope's OWN body —
    the taint pass needs source order (``ast.walk`` is breadth-first)
    and must not leak taint across nested function boundaries (each
    nested def is its own scope, analyzed separately)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, _NESTED):
            continue
        yield child
        yield from _walk_in_order(child)


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------


def analyze_sources(
    sources: Dict[str, str],
    rule_ids: Optional[Iterable[str]] = None,
    pairs: Optional[Sequence[PairSpec]] = None,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Analyze {path: source}.  ``pairs`` defaults to the packaged
    ``tools/pairs.toml`` (loud ValueError when missing/malformed)."""
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    for rid in active:
        if rid not in RULES:
            raise ValueError(f"unknown rule id {rid!r}")
    if pairs is None and "pair-imbalance" in active:
        pairs = load_default_pairs()
    pairs = pairs or []

    findings: List[Finding] = []
    n_suppressed = 0
    for path, source in sorted(sources.items()):
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(
                Finding(
                    "syntax-error", path, exc.lineno or 1,
                    exc.offset or 0, f"cannot parse: {exc.msg}",
                )
            )
            continue
        raw = _FileAnalyzer(path, tree, pairs, active).run()
        supp = toolkit.suppressed_rules(source, "fablife")
        kept, suppressed = toolkit.apply_suppressions(raw, supp)
        findings.extend(kept)
        n_suppressed += len(suppressed)
        if collect_suppressed is not None:
            collect_suppressed.extend(suppressed)
    findings.sort(key=Finding.key)
    stats = {"files": len(sources), "suppressed": n_suppressed}
    return findings, stats


def analyze_source(
    source: str,
    path: str,
    rule_ids: Optional[Iterable[str]] = None,
    pairs: Optional[Sequence[PairSpec]] = None,
) -> Tuple[List[Finding], int]:
    """Single-blob convenience (fixtures/tests)."""
    findings, stats = analyze_sources({path: source}, rule_ids, pairs)
    return findings, stats["suppressed"]


def analyze_paths(
    paths: Sequence[str],
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    pairs: Optional[Sequence[PairSpec]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    files = iter_py_files(paths, excludes)
    sources, io_findings = toolkit.read_sources(files)
    findings, stats = analyze_sources(sources, rule_ids, pairs)
    findings.extend(io_findings)
    findings.sort(key=Finding.key)
    stats["files"] = len(files)
    return findings, stats


def live_suppression_keys(
    sources: Dict[str, str], rules: Set[str]
) -> Set[Tuple[str, int, str]]:
    """The toolkit analyzer-registry staleness protocol (consumed by
    fabreg's suppression-stale): (normalized path, line, rule) for
    every fablife suppression that still absorbs a finding."""
    needed = set(RULES) if "all" in rules else (rules & set(RULES))
    if not needed:
        return set()
    suppressed: List[Finding] = []
    analyze_sources(sources, needed, collect_suppressed=suppressed)
    return {
        (toolkit.normalize_path(f.path), f.line, f.rule)
        for f in suppressed
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fablife",
        "resource-lifetime + wire-trust analyzer for fabric-tpu "
        "(dependency-free; never imports the analyzed code)",
    )
    parser.add_argument(
        "--pairs",
        metavar="FILE",
        help="acquire/release pair table (default: tools/pairs.toml "
        "next to this module)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(RULES, width=20)
        return 0

    rc = toolkit.check_paths_exist(args.paths, "fablife", parser)
    if rc:
        return rc
    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fablife")
    if rc:
        return rc

    pairs: Optional[List[PairSpec]] = None
    try:
        if args.pairs is not None:
            pairs = parse_pairs(
                Path(args.pairs).read_text(encoding="utf-8"), args.pairs
            )
        else:
            pairs = load_default_pairs()
    except (OSError, ValueError) as exc:
        print(f"fablife: error: pair table: {exc}", file=sys.stderr)
        return 2

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)
    findings, stats = analyze_paths(args.paths, rule_ids, excludes, pairs)

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "files": stats["files"],
                    "suppressed": stats["suppressed"],
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fablife: {len(findings)} finding(s) in {stats['files']} "
            f"file(s) ({stats['suppressed']} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
