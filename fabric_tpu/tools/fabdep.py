"""fabdep — whole-program import-layering + concurrency analyzer.

fablint (the sibling tool) checks invariants one file at a time; fabdep
parses the WHOLE package tree into one symbol table and checks the
properties that only exist between files: the shape of the import graph,
and which threads touch which state.  Both matter for the same reason —
the pipeline's correctness contract is bit-exactness of the
VALID/INVALID mask, and parallel validation (thread-pipelined block
commit, sharded hostec, async TPU dispatch) is exactly where that
contract dies silently when dependency structure or locking drifts.

Like fablint, fabdep is dependency-free and import-free: it never
imports the analyzed code (pure ``ast`` + a symbol table), so it runs
identically in minimal environments without ``cryptography``/``jax``.

Passes / rules
--------------
Layering (pass 1):

import-cycle     a cycle in the package import graph (any import depth,
                 deferred imports included — an architectural cycle is a
                 cycle even when hidden inside a function), or a cycle
                 between MODULES at import time (module-scope imports
                 only).  Reported with the full cycle path and the
                 contributing import sites.
layer-skip       an upward import: a package imports from a package the
                 declared layer map places ABOVE it.  Downward imports
                 may skip any number of layers; upward is never allowed.
layer-unknown    a package missing from the declared layer map (keeps
                 the map from silently rotting as packages are added).

Concurrency (pass 2):

unguarded-shared-write  module-global or ``self.*`` mutable state
                 written from two different execution contexts (two
                 distinct thread entry points, or a thread and
                 non-thread code) with no common ``with <lock>:`` guard
                 across the write sites.  Thread entry points are
                 ``threading.Thread(target=...)``/``Timer``/
                 ``executor.submit(...)``/``apply_async`` call sites,
                 resolved through the symbol table and closed over the
                 call graph.  Heuristic by design — suppress confirmed
                 benign sites with a reason.
lock-order-cycle a cycle in the lock-acquisition-order graph (lock B
                 taken while holding A, and A while holding B —
                 potential deadlock).  Nested ``with`` blocks plus one
                 level of call resolution.
blocking-under-lock  a blocking call — ``.join()``, ``.result()``,
                 ``.recv()``, ``time.sleep()``, ``Event.wait()`` — made
                 while holding a lock: stalls every competing acquirer
                 (``Condition.wait`` is fine: it releases the lock).

API surface (pass 3):

dead-export      a name a module declares in ``__all__`` that nothing
                 outside its package (including the reference roots:
                 ``tests/``, the repo-root scripts) ever references.

Layer map
---------
Declared in ``tools/layers.toml`` next to the analyzed package (or
``--layers FILE``): a ``[layers]`` table of ``package = level`` (higher
level may import lower or equal), and an optional ``[allow]`` table of
``"src -> dst" = "reason"`` edge suppressions that exempt a package edge
from both the cycle and the layer checks.  A tiny TOML subset is parsed
in-process — no tomllib dependency, works on any Python.

Suppression
-----------
Per line: ``# fabdep: disable=rule-id[,rule-id...]  # <reason>`` on the
reported line, same idiom as fablint.  ``disable=all`` silences every
rule for that line.  Per edge: the ``[allow]`` table above.

Usage
-----
    python -m fabric_tpu.tools.fabdep [--json] [--dot] [--graph-json]
        [--layers FILE] [--refs PATH] [--rules a,b] [--list-rules] PATH

Exit status: 0 = clean, 1 = findings, 2 = usage/IO error.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import (  # noqa: F401 - re-exported API
    DEFAULT_EXCLUDES,
    Finding,
)

__version__ = "1.0"

# --------------------------------------------------------------------------
# Configuration
# --------------------------------------------------------------------------

# Generated-artifact exclusions live in tools.toolkit.DEFAULT_EXCLUDES
# (re-exported above), shared with fablint/fabflow/fabreg.

#: rule-id -> one-line doc (the registry; passes emit by id).
RULES: Dict[str, str] = {
    "import-cycle": "cycle in the package import graph, or an "
    "import-time cycle between modules",
    "layer-skip": "upward import: a package imports from a higher "
    "declared layer",
    "layer-unknown": "package missing from the declared layer map",
    "unguarded-shared-write": "shared mutable state written from two "
    "execution contexts with no common lock",
    "lock-order-cycle": "cyclic lock acquisition order (potential "
    "deadlock)",
    "blocking-under-lock": "blocking call (.join/.result/.recv/sleep) "
    "while holding a lock",
    "dead-export": "__all__ name never referenced outside its package",
}

#: Constructors whose instances are thread-safe to CALL METHODS ON —
#: mutations through them are synchronization, not shared-state writes.
THREADSAFE_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "local", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "deque",
}

#: Constructors that mark an executor attribute as PROCESS-based: its
#: submitted callables run in another process and share no memory.
PROCESS_CTORS = {"ProcessPoolExecutor", "Pool", "get_context"}

#: Builtin container constructors: a mutator-method call on a receiver
#: of this type (or of unknown type) is a raw shared-state write.  A
#: receiver hinted as a USER class is not — that class's own methods
#: are analyzed for its own state, with its own locks.
CONTAINER_CTORS = {
    "dict", "list", "set", "defaultdict", "OrderedDict", "Counter",
    "frozenset", "bytearray",
}

#: Method names that mutate their receiver in place.
MUTATOR_METHODS = {
    "append", "add", "update", "clear", "pop", "popitem", "remove",
    "discard", "extend", "insert", "setdefault", "sort", "reverse",
    "appendleft", "popleft", "extendleft",
}

#: Identifier tokens that mark a ``with`` context manager as a lock.
LOCKISH_TOKENS = {
    "lock", "rlock", "mutex", "mu", "sem", "semaphore", "cv", "cond",
    "condition",
}

#: Methods treated as constructor-like: writes there are object setup,
#: ordered before any thread can see the instance.
INIT_METHODS = {"__init__", "__post_init__", "__new__", "__set_name__"}



# --------------------------------------------------------------------------
# Core data model
# --------------------------------------------------------------------------


@dataclass
class ImportSite:
    line: int
    col: int
    target: str  # dotted module-ish name as written (maybe module.attr)
    deferred: bool  # not at module scope


@dataclass
class WriteSite:
    key: str  # canonical state key ("mod:GLOBAL" / "mod:Class.attr")
    line: int
    col: int
    locks: FrozenSet[str]
    desc: str  # human description of the write


@dataclass
class FuncInfo:
    qualname: str  # "mod:func" or "mod:Class.meth"
    module: str
    cls: Optional[str]
    name: str
    line: int
    calls: List[Tuple[str, int, FrozenSet[str]]] = field(
        default_factory=list
    )  # (callee qualname-ish, line, locks held at the call site)
    unresolved_methods: List[Tuple[str, int, FrozenSet[str]]] = field(
        default_factory=list
    )  # (.method name, line, locks held)
    thread_targets: List[Tuple[str, int, int]] = field(default_factory=list)
    writes: List[WriteSite] = field(default_factory=list)
    acquires: List[Tuple[str, int, int]] = field(default_factory=list)
    lock_pairs: List[Tuple[str, str, int, int]] = field(default_factory=list)
    calls_under_lock: List[Tuple[str, FrozenSet[str], int]] = field(
        default_factory=list
    )
    blocking: List[Tuple[str, str, int, int]] = field(default_factory=list)


@dataclass
class ModuleInfo:
    path: str
    modname: str  # dotted, e.g. fabric_tpu.crypto.bccsp
    package: str  # first component below the root package
    imports: List[ImportSite] = field(default_factory=list)
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    classes: Dict[str, List[str]] = field(default_factory=dict)  # cls -> bases
    global_types: Dict[str, str] = field(default_factory=dict)
    attr_types: Dict[Tuple[str, str], str] = field(default_factory=dict)
    return_hints: Dict[str, str] = field(default_factory=dict)  # fn -> ctor
    all_names: List[Tuple[str, int, int]] = field(default_factory=list)
    defined: Set[str] = field(default_factory=set)  # top-level def/class/assign
    refs: Set[Tuple[str, str]] = field(default_factory=set)  # (module, name)
    star_imports: Set[str] = field(default_factory=set)
    strings: Set[str] = field(default_factory=set)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    return toolkit.suppressed_rules(source, "fabdep")


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _tokens(name: str) -> Set[str]:
    return {t for t in name.lower().replace(".", "_").split("_") if t}


def _unwrap_value(value: ast.AST) -> ast.AST:
    """Peel ``X if cond else None`` / ``X or Y`` to the lead candidate."""
    if isinstance(value, ast.IfExp):
        return _unwrap_value(value.body)
    if isinstance(value, ast.BoolOp) and value.values:
        return _unwrap_value(value.values[0])
    return value


def _ctor_hint(value: ast.AST) -> Optional[str]:
    """'Lock' for ``threading.Lock()``, 'deque' for ``deque()``, etc."""
    value = _unwrap_value(value)
    if isinstance(value, ast.Call):
        name = _dotted(value.func)
        if name:
            return name.rsplit(".", 1)[-1]
    return None


# --------------------------------------------------------------------------
# Layer map (mini-TOML)
# --------------------------------------------------------------------------


class LayerMap:
    def __init__(
        self,
        layers: Optional[Dict[str, int]] = None,
        allow: Optional[Dict[Tuple[str, str], str]] = None,
    ):
        self.layers = layers or {}
        self.allow = allow or {}

    def allowed(self, src: str, dst: str) -> bool:
        return (src, dst) in self.allow

    @classmethod
    def parse(cls, text: str, path: str = "<layers>") -> "LayerMap":
        """Parse the tiny TOML subset fabdep uses: ``[section]`` headers,
        ``key = value`` lines, ``#`` comments, quoted keys/values."""
        layers: Dict[str, int] = {}
        allow: Dict[Tuple[str, str], str] = {}
        section = ""
        for n, raw in enumerate(text.splitlines(), start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("[") and line.endswith("]"):
                section = line[1:-1].strip()
                continue
            if "=" not in line:
                raise ValueError(f"{path}:{n}: expected 'key = value'")
            key, _, value = line.partition("=")
            key = key.strip().strip('"').strip("'")
            value = value.strip()
            if "#" in value and not (
                value.startswith('"') or value.startswith("'")
            ):
                value = value.split("#", 1)[0].strip()
            if section == "layers":
                try:
                    layers[key] = int(value)
                except ValueError as exc:
                    raise ValueError(
                        f"{path}:{n}: layer level must be an int"
                    ) from exc
            elif section == "allow":
                m = re.match(r"^(\S+)\s*->\s*(\S+)$", key)
                if not m:
                    raise ValueError(
                        f"{path}:{n}: allow key must be 'src -> dst'"
                    )
                allow[(m.group(1), m.group(2))] = value.strip('"').strip("'")
            # unknown sections are ignored (forward compatibility)
        return cls(layers, allow)


# --------------------------------------------------------------------------
# Per-module collection
# --------------------------------------------------------------------------


class _ModuleCollector(ast.NodeVisitor):
    """One pass over a module AST filling a ModuleInfo: imports, the
    function/class symbol table, write sites with held-lock sets, thread
    spawn sites, lock nesting, and name references."""

    def __init__(self, info: ModuleInfo):
        self.info = info
        self.cls_stack: List[str] = []
        self.fn_stack: List[FuncInfo] = []
        self.lock_stack: List[str] = []
        # import alias -> dotted module (or module.attr for from-imports)
        self.aliases: Dict[str, str] = {}
        # per-function local alias: name -> ("attr", cls, attr) | ("global", g)
        self.local_alias: Dict[str, Tuple[str, ...]] = {}
        # per-function local var -> constructor hint
        self.local_types: Dict[str, str] = {}
        # per-function locally-defined (nested) functions: name -> qualname
        self.local_funcs: Dict[str, str] = {}
        self.module_globals: Set[str] = set()
        self.declared_global: Set[str] = set()

    def prescan(self, tree: ast.Module) -> None:
        """Fill global/return type hints BEFORE the main walk, so e.g.
        ``pool = _pool()`` resolves to the ProcessPoolExecutor the
        function returns even when ``_pool`` is defined later."""
        global_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                global_names.update(node.names)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) >= 1:
                hint = _ctor_hint(node.value)
                if not hint:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in global_names:
                        self.info.global_types.setdefault(t.id, hint)
        for node in tree.body:
            if isinstance(node, (ast.Assign,)) and len(node.targets) == 1:
                t = node.targets[0]
                hint = _ctor_hint(node.value)
                if isinstance(t, ast.Name) and hint:
                    self.info.global_types.setdefault(t.id, hint)
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for ret in ast.walk(node):
                if not isinstance(ret, ast.Return) or ret.value is None:
                    continue
                val = ret.value
                if isinstance(val, ast.BoolOp) and val.values:
                    val = val.values[0]
                hint = _ctor_hint(val)
                if hint is None and isinstance(val, ast.Name):
                    hint = self.info.global_types.get(val.id)
                if hint:
                    self.info.return_hints.setdefault(node.name, hint)
                    break

    # -- helpers ----------------------------------------------------------

    def _fn(self) -> Optional[FuncInfo]:
        return self.fn_stack[-1] if self.fn_stack else None

    def _held(self) -> FrozenSet[str]:
        return frozenset(self.lock_stack)

    def _self_attr_type(self, attr: str) -> Optional[str]:
        cls = self.cls_stack[-1] if self.cls_stack else None
        if cls is None:
            return None
        return self.info.attr_types.get((cls, attr))

    def _canon_lock(self, node: ast.AST) -> Optional[str]:
        """Canonical name for a lock-ish with-context, else None."""
        hint = None
        name = None
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            if node.value.id == "self":
                name = f"{self.info.modname}:{self.cls_stack[-1] if self.cls_stack else '?'}.{node.attr}"
                hint = self._self_attr_type(node.attr)
            else:
                name = f"{self.info.modname}:<{node.value.id}>.{node.attr}"
            leaf = node.attr
        elif isinstance(node, ast.Name):
            if node.id in self.module_globals:
                name = f"{self.info.modname}:{node.id}"
                hint = self.info.global_types.get(node.id)
            else:
                fn = self._fn()
                scope = fn.name if fn else "?"
                name = f"{self.info.modname}:{scope}.<local>.{node.id}"
            leaf = node.id
        else:
            return None
        if hint in ("Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore"):
            return name
        if _tokens(leaf) & LOCKISH_TOKENS:
            return name
        return None

    def _state_key(self, node: ast.AST) -> Optional[Tuple[str, str]]:
        """(key, desc) when `node` is shared mutable state: a module
        global or a self attribute (directly or through a local alias)."""
        if isinstance(node, ast.Name):
            alias = self.local_alias.get(node.id)
            if alias is not None:
                if alias[0] == "attr":
                    return (
                        f"{self.info.modname}:{alias[1]}.{alias[2]}",
                        f"self.{alias[2]} (via local alias {node.id!r})",
                    )
                if alias[0] == "global":
                    return (
                        f"{self.info.modname}:{alias[1]}",
                        f"module global {alias[1]!r} (via alias {node.id!r})",
                    )
            if node.id in self.declared_global or (
                not self.fn_stack and node.id in self.module_globals
            ) or (node.id in self.module_globals and self.fn_stack):
                return (
                    f"{self.info.modname}:{node.id}",
                    f"module global {node.id!r}",
                )
            return None
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            if node.value.id == "self" and self.cls_stack:
                return (
                    f"{self.info.modname}:{self.cls_stack[-1]}.{node.attr}",
                    f"self.{node.attr}",
                )
        return None

    def _recv_hint(self, node: ast.AST) -> Optional[str]:
        """Best-effort type hint for a method-call receiver."""
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ) and node.value.id == "self":
            return self._self_attr_type(node.attr)
        if isinstance(node, ast.Name):
            alias = self.local_alias.get(node.id)
            if alias is not None and alias[0] == "attr":
                return self.info.attr_types.get((alias[1], alias[2]))
            if alias is not None and alias[0] == "global":
                return self.info.global_types.get(alias[1])
            return self.local_types.get(node.id) or self.info.global_types.get(
                node.id
            )
        return None

    def _exempt_state(self, node: ast.AST) -> bool:
        """Thread-safe-typed receivers are synchronization, not state."""
        return self._recv_hint(node) in THREADSAFE_CTORS

    def _record_write(
        self, target: ast.AST, line: int, col: int, mutator: bool = False
    ) -> None:
        fn = self._fn()
        if fn is None or fn.name in INIT_METHODS:
            return
        if self._exempt_state(target):
            return
        if mutator:
            # a mutator-method call on a USER-class receiver is that
            # class's business: its own methods (and locks) are analyzed
            hint = self._recv_hint(target)
            if hint is not None and hint not in CONTAINER_CTORS:
                return
        keyed = self._state_key(target)
        if keyed is None:
            return
        key, desc = keyed
        fn.writes.append(WriteSite(key, line, col, self._held(), desc))

    # -- imports ----------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        deferred = bool(self.fn_stack)
        for a in node.names:
            self.info.imports.append(
                ImportSite(node.lineno, node.col_offset, a.name, deferred)
            )
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        deferred = bool(self.fn_stack)
        base = node.module or ""
        if node.level > 0:
            parts = self.info.modname.split(".")
            # from . import x at level 1 inside pkg.mod -> base pkg
            anchor = parts[: len(parts) - node.level]
            base = ".".join(anchor + ([node.module] if node.module else []))
        for a in node.names:
            if a.name == "*":
                self.info.star_imports.add(base)
                self.info.imports.append(
                    ImportSite(node.lineno, node.col_offset, base, deferred)
                )
                continue
            self.info.imports.append(
                ImportSite(
                    node.lineno, node.col_offset, f"{base}.{a.name}", deferred
                )
            )
            self.info.refs.add((base, a.name))
            self.aliases[a.asname or a.name] = f"{base}.{a.name}"
        self.generic_visit(node)

    # -- scopes -----------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if not self.fn_stack and not self.cls_stack:
            self.info.defined.add(node.name)
            self.info.classes[node.name] = [
                _dotted(b) or "" for b in node.bases
            ]
            self.module_globals.add(node.name)
        self.cls_stack.append(node.name)
        # collect self.<attr> = CTOR() hints from every method first, so
        # methods earlier in the file see hints from __init__ anywhere
        for meth in node.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(meth):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        t = sub.targets[0]
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            hint = _ctor_hint(sub.value)
                            if hint:
                                self.info.attr_types.setdefault(
                                    (node.name, t.attr), hint
                                )
        self.generic_visit(node)
        self.cls_stack.pop()

    def _visit_func(self, node) -> None:
        cls = self.cls_stack[-1] if self.cls_stack else None
        if self.fn_stack:  # nested function: own FuncInfo, local name
            outer = self.fn_stack[-1]
            qual = f"{outer.qualname}.<locals>.{node.name}"
            self.local_funcs[node.name] = qual
        elif cls and len(self.cls_stack) == 1:
            qual = f"{self.info.modname}:{cls}.{node.name}"
        elif not cls:
            qual = f"{self.info.modname}:{node.name}"
            self.info.defined.add(node.name)
            self.module_globals.add(node.name)
        else:  # class nested in class: rare, attribute to inner class
            qual = f"{self.info.modname}:{'.'.join(self.cls_stack)}.{node.name}"
        fn = FuncInfo(
            qualname=qual,
            module=self.info.modname,
            cls=cls,
            name=node.name,
            line=node.lineno,
        )
        self.info.functions[qual] = fn
        self.fn_stack.append(fn)
        saved_alias, self.local_alias = self.local_alias, {}
        saved_types, self.local_types = self.local_types, {}
        saved_funcs, self.local_funcs = self.local_funcs, dict(self.local_funcs)
        saved_global, self.declared_global = self.declared_global, set()
        saved_locks, self.lock_stack = self.lock_stack, []
        for stmt in node.body:
            self.visit(stmt)
        self.fn_stack.pop()
        self.local_alias = saved_alias
        self.local_types = saved_types
        self.local_funcs = saved_funcs
        self.declared_global = saved_global
        self.lock_stack = saved_locks

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.declared_global.update(node.names)
        self.module_globals.update(node.names)

    # -- with / locks ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        fn = self._fn()
        acquired: List[str] = []
        for item in node.items:
            expr = item.context_expr
            # `with lock:` or `with lock.acquire_timeout(..)`-ish
            lock = self._canon_lock(expr)
            if lock is None and isinstance(expr, ast.Call):
                lock = self._canon_lock(expr.func) if isinstance(
                    expr.func, (ast.Name, ast.Attribute)
                ) else None
            if lock is not None and fn is not None:
                for outer in self.lock_stack:
                    if outer != lock:
                        fn.lock_pairs.append(
                            (outer, lock, node.lineno, node.col_offset)
                        )
                fn.acquires.append((lock, node.lineno, node.col_offset))
                acquired.append(lock)
            self.visit(expr)
        self.lock_stack.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.lock_stack.pop()

    # -- assignments / writes ---------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if not self.fn_stack and not self.cls_stack:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.info.defined.add(t.id)
                    self.module_globals.add(t.id)
                    hint = _ctor_hint(node.value)
                    if hint:
                        self.info.global_types[t.id] = hint
                    if t.id == "__all__" and isinstance(
                        node.value, (ast.List, ast.Tuple)
                    ):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, str
                            ):
                                self.info.all_names.append(
                                    (elt.value, node.lineno, node.col_offset)
                                )
        fn = self._fn()
        if fn is not None:
            hint = _ctor_hint(node.value)
            # `x = f()` where f is a module function with a return hint
            call = _unwrap_value(node.value)
            if (
                hint is not None
                and isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id in self.info.return_hints
            ):
                hint = self.info.return_hints[call.func.id]
            for t in node.targets:
                # track `x = self._attr` / `x = GLOBAL` aliases
                if isinstance(t, ast.Name):
                    if hint is not None:
                        self.local_types[t.id] = hint
                    elif t.id in self.local_types:
                        del self.local_types[t.id]
                    if (
                        isinstance(node.value, ast.Attribute)
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == "self"
                        and self.cls_stack
                    ):
                        self.local_alias[t.id] = (
                            "attr", self.cls_stack[-1], node.value.attr,
                        )
                    elif (
                        isinstance(node.value, ast.Name)
                        and node.value.id in self.module_globals
                    ):
                        self.local_alias[t.id] = ("global", node.value.id)
                    elif t.id in self.local_alias:
                        del self.local_alias[t.id]
                # writes: global rebinds, self.attr rebinds, subscripts
                if isinstance(t, ast.Name):
                    if t.id in self.declared_global:
                        if hint in THREADSAFE_CTORS:
                            continue
                        self._record_write(t, node.lineno, node.col_offset)
                elif isinstance(t, ast.Attribute):
                    if hint in THREADSAFE_CTORS:
                        continue
                    self._record_write(t, node.lineno, node.col_offset)
                elif isinstance(t, ast.Subscript):
                    self._record_write(
                        t.value, node.lineno, node.col_offset
                    )
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        fn = self._fn()
        if fn is not None:
            t = node.target
            if isinstance(t, ast.Name) and t.id in self.declared_global:
                self._record_write(t, node.lineno, node.col_offset)
            elif isinstance(t, ast.Attribute):
                self._record_write(t, node.lineno, node.col_offset)
            elif isinstance(t, ast.Subscript):
                self._record_write(t.value, node.lineno, node.col_offset)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._record_write(t.value, node.lineno, node.col_offset)
        self.generic_visit(node)

    # -- calls ------------------------------------------------------------

    def _resolve_callable(self, node: ast.AST) -> Optional[str]:
        """Best-effort: AST callable reference -> qualified name key."""
        if isinstance(node, ast.Name):
            if node.id in self.local_funcs:
                return self.local_funcs[node.id]
            alias = self.aliases.get(node.id)
            if alias:
                return f"@{alias}"  # imported name, resolved program-wide
            if node.id in self.module_globals:
                return f"{self.info.modname}:{node.id}"
            return None
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            base = node.value.id
            if base == "self" and self.cls_stack:
                return f"{self.info.modname}:{self.cls_stack[-1]}.{node.attr}"
            alias = self.aliases.get(base)
            if alias:
                return f"@{alias}.{node.attr}"
            # typed receiver: self.attr hint / local var ctor hint
            return None
        return None

    def visit_Call(self, node: ast.Call) -> None:
        fn = self._fn()
        callee = _dotted(node.func) or ""
        leaf = callee.rsplit(".", 1)[-1]

        # --- thread spawn sites ---
        target_expr: Optional[ast.AST] = None
        if leaf in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    target_expr = kw.value
            if target_expr is None and leaf == "Timer" and len(node.args) >= 2:
                target_expr = node.args[1]
        elif leaf in ("submit", "apply_async") and isinstance(
            node.func, ast.Attribute
        ):
            recv_hint = self._recv_hint(node.func.value)
            if recv_hint not in PROCESS_CTORS and node.args:
                target_expr = node.args[0]
        elif callee in ("start_new_thread", "_thread.start_new_thread"):
            if node.args:
                target_expr = node.args[0]
        if target_expr is not None and fn is not None:
            ref = self._resolve_callable(target_expr)
            if ref:
                fn.thread_targets.append((ref, node.lineno, node.col_offset))

        # --- call graph edges ---
        if fn is not None:
            ref = self._resolve_callable(node.func)
            if ref:
                fn.calls.append((ref, node.lineno, self._held()))
            elif isinstance(node.func, ast.Attribute):
                hint = self._recv_hint(node.func.value)
                if hint:
                    fn.calls.append(
                        (f"#{hint}.{node.func.attr}", node.lineno,
                         self._held())
                    )
                elif node.func.attr not in MUTATOR_METHODS:
                    fn.unresolved_methods.append(
                        (node.func.attr, node.lineno, self._held())
                    )

        # --- mutation method calls on shared state ---
        if (
            fn is not None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in MUTATOR_METHODS
        ):
            self._record_write(
                node.func.value, node.lineno, node.col_offset, mutator=True
            )

        # --- blocking calls under a held lock ---
        if fn is not None and self.lock_stack and isinstance(
            node.func, ast.Attribute
        ):
            self._check_blocking(node, fn)

        self.generic_visit(node)

    def _check_blocking(self, node: ast.Call, fn: FuncInfo) -> None:
        attr = node.func.attr
        recv = node.func.value
        recv_dotted = _dotted(recv) or ""
        if attr == "sleep" and recv_dotted == "time":
            fn.blocking.append(
                ("time.sleep", self.lock_stack[-1], node.lineno,
                 node.col_offset)
            )
            return
        if attr not in ("join", "result", "recv", "wait"):
            return
        # the held lock's own .wait/.acquire is Condition discipline
        canon = self._canon_lock(recv)
        if canon is not None and canon in self.lock_stack:
            return
        if attr == "wait":
            # only flag Event-typed receivers: lock.wait/cond.wait differ
            hint = None
            if isinstance(recv, ast.Attribute) and isinstance(
                recv.value, ast.Name
            ) and recv.value.id == "self":
                hint = self._self_attr_type(recv.attr)
            elif isinstance(recv, ast.Name):
                hint = self.info.global_types.get(recv.id)
            if hint != "Event":
                return
        if attr == "join":
            # exclude the overwhelming str.join / os.path.join shapes
            if isinstance(recv, ast.Constant):
                return
            if "path" in recv_dotted.lower().split("."):
                return
            if any(
                isinstance(
                    a,
                    (ast.List, ast.Tuple, ast.GeneratorExp, ast.ListComp,
                     ast.Call, ast.JoinedStr, ast.BinOp),
                )
                or (isinstance(a, ast.Constant) and isinstance(a.value, str))
                for a in node.args
            ):
                return
        fn.blocking.append(
            (f".{attr}()", self.lock_stack[-1], node.lineno, node.col_offset)
        )

    # -- references (dead-export pass) ------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name):
            alias = self.aliases.get(node.value.id)
            if alias:
                self.info.refs.add((alias, node.attr))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        alias = self.aliases.get(node.id)
        if alias and "." in alias:
            mod, _, name = alias.rpartition(".")
            self.info.refs.add((mod, name))

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str) and node.value.isidentifier():
            self.info.strings.add(node.value)


# --------------------------------------------------------------------------
# Program-level analysis
# --------------------------------------------------------------------------


class Program:
    def __init__(self, root: Path, excludes: Sequence[str]):
        self.root = root
        self.root_pkg = root.name
        self.excludes = tuple(excludes)
        self.modules: Dict[str, ModuleInfo] = {}
        self.findings: List[Finding] = []
        self.suppressed = 0
        #: the findings per-line suppressions absorbed (fabreg's
        #: suppression-stale rule reads these to prove each comment
        #: still covers a live finding)
        self.suppressed_findings: List[Finding] = []
        # program-wide symbol tables (built in link())
        self.functions: Dict[str, FuncInfo] = {}
        self.class_methods: Dict[str, Dict[str, str]] = {}
        self.method_owner_count: Dict[str, int] = {}
        self.thread_classes: Set[str] = set()

    # -- loading ----------------------------------------------------------

    def load(self) -> None:
        files = sorted(self.root.rglob("*.py"))
        for f in files:
            posix = f.as_posix()
            if any(fnmatch.fnmatch(posix, pat) for pat in self.excludes):
                continue
            rel = f.relative_to(self.root.parent)
            modname = ".".join(rel.with_suffix("").parts)
            if modname.endswith(".__init__"):
                modname = modname[: -len(".__init__")]
            parts = modname.split(".")
            package = parts[1] if len(parts) > 1 else ""
            try:
                source = f.read_text(encoding="utf-8")
                tree = ast.parse(source, filename=str(f))
            except (OSError, UnicodeDecodeError, SyntaxError) as exc:
                self.findings.append(
                    Finding("io-error", str(f), 1, 0, f"cannot parse: {exc}")
                )
                continue
            info = ModuleInfo(path=str(f), modname=modname, package=package)
            info.suppressions = parse_suppressions(source)
            collector = _ModuleCollector(info)
            collector.prescan(tree)
            collector.visit(tree)
            self.modules[modname] = info

    def link(self) -> None:
        """Build the program-wide symbol tables used for resolution."""
        for info in self.modules.values():
            for qual, fn in info.functions.items():
                self.functions[qual] = fn
            for cls, bases in info.classes.items():
                methods = self.class_methods.setdefault(cls, {})
                for qual, fn in info.functions.items():
                    if fn.cls == cls:
                        methods[fn.name] = qual
                if any(
                    b.rsplit(".", 1)[-1] == "Thread" for b in bases if b
                ):
                    self.thread_classes.add(cls)
        for cls, methods in self.class_methods.items():
            for name in methods:
                self.method_owner_count[name] = (
                    self.method_owner_count.get(name, 0) + 1
                )

    # -- shared helpers ----------------------------------------------------

    def _emit(
        self, rule: str, info: ModuleInfo, line: int, col: int, msg: str
    ) -> None:
        disabled = info.suppressions.get(line, set())
        if rule in disabled or "all" in disabled:
            self.suppressed += 1
            self.suppressed_findings.append(
                Finding(rule, info.path, line, col, msg)
            )
            return
        self.findings.append(Finding(rule, info.path, line, col, msg))

    def resolve_module(self, target: str) -> Optional[str]:
        """Dotted import target -> analyzed module name (or None)."""
        if target in self.modules:
            return target
        head, _, _ = target.rpartition(".")
        if head in self.modules:
            return head
        return None

    def resolve_func(self, ref: str) -> List[str]:
        """Call/target reference -> candidate FuncInfo qualnames."""
        if ref.startswith("#"):  # typed receiver: ClassName.method
            cls_meth = ref[1:]
            cls, _, meth = cls_meth.partition(".")
            qual = self.class_methods.get(cls, {}).get(meth)
            return [qual] if qual else []
        if ref.startswith("@"):  # imported dotted name
            dotted = ref[1:]
            mod, _, name = dotted.rpartition(".")
            if mod in self.modules:
                qual = f"{mod}:{name}"
                if qual in self.functions:
                    return [qual]
            # imported class / deeper attribute chain: not a call edge
            return []
        if ref in self.functions:
            return [ref]
        return []

    # -- pass 1: layering --------------------------------------------------

    def layering_pass(self, layer_map: LayerMap) -> Dict[str, object]:
        pkg_edges: Dict[Tuple[str, str], List[Tuple[ModuleInfo, ImportSite]]] = {}
        mod_edges: Dict[Tuple[str, str], Tuple[ModuleInfo, ImportSite]] = {}
        for info in self.modules.values():
            for site in info.imports:
                if not site.target.startswith(self.root_pkg):
                    continue
                target_mod = self.resolve_module(site.target)
                if target_mod is None or target_mod == info.modname:
                    continue
                tparts = target_mod.split(".")
                tpkg = tparts[1] if len(tparts) > 1 else ""
                if tpkg and info.package and tpkg != info.package:
                    if not layer_map.allowed(info.package, tpkg):
                        pkg_edges.setdefault(
                            (info.package, tpkg), []
                        ).append((info, site))
                if not site.deferred:
                    key = (info.modname, target_mod)
                    if key not in mod_edges:
                        mod_edges[key] = (info, site)

        # package cycles (all imports, deferred included)
        pkg_graph: Dict[str, Set[str]] = {}
        for (src, dst) in pkg_edges:
            pkg_graph.setdefault(src, set()).add(dst)
            pkg_graph.setdefault(dst, set())
        for cycle in _find_cycles(pkg_graph):
            path = " -> ".join(cycle + [cycle[0]])
            sites: List[str] = []
            for a, b in zip(cycle, cycle[1:] + [cycle[0]]):
                # consecutive pairs follow real edges, but in an SCC that
                # is not one simple cycle the CLOSING pair may not be an
                # import edge — report the sites that exist
                hit = pkg_edges.get((a, b))
                if hit:
                    info, site = hit[0]
                    sites.append(f"{info.path}:{site.line}")
            info, site = pkg_edges[(cycle[0], cycle[1])][0]
            self._emit(
                "import-cycle", info, site.line, site.col,
                f"package import cycle: {path} (edge sites: "
                f"{', '.join(sites)}); break it by moving the shared "
                f"leaf types into the lower layer",
            )

        # module-level import-time cycles (module-scope imports only)
        mod_graph: Dict[str, Set[str]] = {}
        for (src, dst) in mod_edges:
            mod_graph.setdefault(src, set()).add(dst)
            mod_graph.setdefault(dst, set())
        for cycle in _find_cycles(mod_graph):
            path = " -> ".join(cycle + [cycle[0]])
            info, site = mod_edges[(cycle[0], cycle[1])]
            self._emit(
                "import-cycle", info, site.line, site.col,
                f"import-time module cycle: {path} (these imports run "
                f"at module scope; one direction must become deferred "
                f"or the shared names must move down)",
            )

        # layer-skip + layer-unknown
        if layer_map.layers:
            unknown_seen: Set[str] = set()
            for (src, dst), sites in sorted(pkg_edges.items()):
                src_l = layer_map.layers.get(src)
                dst_l = layer_map.layers.get(dst)
                for pkg, lvl in ((src, src_l), (dst, dst_l)):
                    if lvl is None and pkg not in unknown_seen:
                        unknown_seen.add(pkg)
                        info, site = sites[0]
                        self._emit(
                            "layer-unknown", info, site.line, site.col,
                            f"package {pkg!r} is not in the declared "
                            f"layer map (tools/layers.toml) — add it at "
                            f"the right level",
                        )
                if src_l is None or dst_l is None:
                    continue
                if src_l < dst_l:
                    for info, site in sites:
                        self._emit(
                            "layer-skip", info, site.line, site.col,
                            f"upward import: {src} (layer {src_l}) "
                            f"imports {dst} (layer {dst_l}); only same "
                            f"or lower layers may be imported",
                        )

        return {
            "packages": sorted(
                {m.package for m in self.modules.values() if m.package}
            ),
            "edges": sorted(
                {
                    (s, d): len(v) for (s, d), v in pkg_edges.items()
                }.items()
            ),
        }

    # -- pass 2: concurrency ----------------------------------------------

    def _call_edges(
        self,
    ) -> Dict[str, List[Tuple[str, FrozenSet[str]]]]:
        """callee qualname -> [(caller qualname, locks held at site)] over
        every resolvable call (typed, imported, local, unique-method)."""
        incoming: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for qual, fn in self.functions.items():
            resolved: List[Tuple[str, FrozenSet[str]]] = []
            for ref, _line, locks in fn.calls:
                for callee in self.resolve_func(ref):
                    resolved.append((callee, locks))
            for meth, _line, locks in fn.unresolved_methods:
                # unique-method fallback: only when exactly one class in
                # the whole program defines this method name
                if self.method_owner_count.get(meth) == 1:
                    for methods in self.class_methods.values():
                        if meth in methods:
                            resolved.append((methods[meth], locks))
            for callee, locks in resolved:
                incoming.setdefault(callee, []).append((qual, locks))
        return incoming

    def concurrency_pass(self) -> None:
        # 1. thread entries: explicit targets + Thread-subclass run()
        entries: Dict[str, str] = {}  # entry qualname -> spawn description
        for qual, fn in self.functions.items():
            for ref, line, _col in fn.thread_targets:
                for target in self.resolve_func(ref):
                    entries.setdefault(
                        target, f"{fn.qualname} line {line}"
                    )
        for cls in self.thread_classes:
            run_qual = self.class_methods.get(cls, {}).get("run")
            if run_qual:
                entries.setdefault(run_qual, f"{cls}.run (Thread subclass)")

        incoming = self._call_edges()
        outgoing: Dict[str, List[str]] = {}
        for callee, callers in incoming.items():
            for caller, _locks in callers:
                outgoing.setdefault(caller, []).append(callee)

        # 2. closure per entry over the resolved call graph
        def closure(start: str) -> Set[str]:
            seen = {start}
            work = [start]
            while work:
                cur = work.pop()
                for c in outgoing.get(cur, ()):
                    if c not in seen:
                        seen.add(c)
                        work.append(c)
            return seen

        context_of: Dict[str, Set[str]] = {q: set() for q in self.functions}
        for entry in entries:
            for q in closure(entry):
                if q in context_of:
                    context_of[q].add(entry)

        # main context: reachable from any function no resolved call
        # feeds into (API roots / CLI mains / module-level code)
        roots = [
            q for q in self.functions
            if q not in incoming and q not in entries
        ]
        main_reach: Set[str] = set()
        for r in roots:
            main_reach |= closure(r)
        for q in main_reach:
            if q in context_of:
                context_of[q].add("<main>")

        # 2b. caller-held lock inheritance: a write lexically outside a
        # ``with lock:`` is still guarded when EVERY call path into its
        # function holds the lock (``_expire_locked`` style helpers).
        # Must-analysis fixpoint: inherited(f) = intersection over call
        # sites of (locks at site | inherited(caller)); thread entries
        # and call-graph roots inherit nothing (spawn drops locks).
        TOP = None  # lattice top: no call path seen yet
        inherited: Dict[str, Optional[FrozenSet[str]]] = {
            q: TOP for q in self.functions
        }
        for q in self.functions:
            if q in entries or q not in incoming:
                inherited[q] = frozenset()
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for q, callers in incoming.items():
                if q in entries:
                    continue
                acc: Optional[FrozenSet[str]] = TOP
                for caller, locks in callers:
                    up = inherited.get(caller, TOP)
                    if up is TOP:
                        continue  # optimistic: unresolved caller path
                    contrib = locks | up
                    acc = contrib if acc is TOP else (acc & contrib)
                if acc is not TOP and acc != inherited.get(q):
                    inherited[q] = acc
                    changed = True

        def effective(fn_qual: str, locks: FrozenSet[str]) -> FrozenSet[str]:
            extra = inherited.get(fn_qual)
            return locks if extra in (None, frozenset()) else (locks | extra)

        # 3. group write sites by state key with their context sets
        by_key: Dict[str, List[Tuple[FuncInfo, WriteSite, Set[str], FrozenSet[str]]]] = {}
        for qual, fn in self.functions.items():
            ctxs = context_of.get(qual) or {"<main>"}
            for w in fn.writes:
                by_key.setdefault(w.key, []).append(
                    (fn, w, ctxs, effective(qual, w.locks))
                )

        for key, sites in sorted(by_key.items()):
            all_ctxs: Set[str] = set()
            for _fn, _w, ctxs, _locks in sites:
                all_ctxs |= ctxs
            thread_ctxs = all_ctxs - {"<main>"}
            if not thread_ctxs:
                continue  # never written from a thread
            if len(all_ctxs) < 2:
                continue  # single context: no concurrent writers
            common = None
            for _fn, _w, _ctxs, locks in sites:
                common = locks if common is None else (common & locks)
            if common:
                continue  # a shared lock guards every write site
            # report at each UNLOCKED write site (usually 1-2)
            entry_desc = sorted(
                entries.get(c, c) for c in thread_ctxs
            )[0]
            others = {
                f"{Path(f.module.replace('.', '/')).name}.py:{w.line}"
                for f, w, _c, _l in sites
            }
            reported = False
            for fn, w, _ctxs, locks in sites:
                if locks:
                    continue
                info = self.modules.get(fn.module)
                if info is None:
                    continue
                self._emit(
                    "unguarded-shared-write", info, w.line, w.col,
                    f"{w.desc} is written here without a lock, and the "
                    f"same state is written from a thread context "
                    f"(spawned at {entry_desc}; write sites: "
                    f"{', '.join(sorted(others))}); guard every write "
                    f"with one lock or make the state thread-local",
                )
                reported = True
            if not reported:
                # every site locked, but by DIFFERENT locks
                fn, w, _ctxs, _locks = sites[0]
                info = self.modules.get(fn.module)
                if info is not None:
                    held = sorted(set().union(*(s[3] for s in sites)))
                    self._emit(
                        "unguarded-shared-write", info, w.line, w.col,
                        f"{w.desc} write sites are guarded by DIFFERENT "
                        f"locks ({', '.join(held)}) — they do not "
                        f"exclude each other",
                    )

        # 4. lock-order graph + cycles: lexical nesting plus inherited
        # caller-held locks over callee acquisitions
        order_edges: Dict[Tuple[str, str], Tuple[FuncInfo, int, int]] = {}
        for qual, fn in self.functions.items():
            for outer, inner, line, col in fn.lock_pairs:
                order_edges.setdefault((outer, inner), (fn, line, col))
            for inner, line, col in fn.acquires:
                extra = inherited.get(qual)
                if extra:
                    for outer in extra:
                        if outer != inner:
                            order_edges.setdefault(
                                (outer, inner), (fn, line, col)
                            )
        lock_graph: Dict[str, Set[str]] = {}
        for (a, b) in order_edges:
            lock_graph.setdefault(a, set()).add(b)
            lock_graph.setdefault(b, set())
        for cycle in _find_cycles(lock_graph):
            path = " -> ".join(cycle + [cycle[0]])
            fn, line, col = order_edges[(cycle[0], cycle[1])]
            info = self.modules.get(fn.module)
            if info is not None:
                self._emit(
                    "lock-order-cycle", info, line, col,
                    f"lock acquisition order cycle: {path} — two "
                    f"threads taking these locks in opposite order "
                    f"deadlock; pick one global order",
                )

        # 5. blocking calls under a lock
        for qual, fn in self.functions.items():
            info = self.modules.get(fn.module)
            if info is None:
                continue
            for desc, lock, line, col in fn.blocking:
                self._emit(
                    "blocking-under-lock", info, line, col,
                    f"blocking call {desc} while holding {lock}: every "
                    f"competing acquirer stalls (and a cycle through "
                    f"the blocked resource deadlocks); move the wait "
                    f"outside the lock",
                )

    # -- pass 3: dead exports ---------------------------------------------

    def export_pass(self, ref_infos: Sequence[ModuleInfo]) -> None:
        # build the program-wide reference index
        refs: Set[Tuple[str, str]] = set()
        star: Set[str] = set()
        strings: Set[str] = set()
        for info in list(self.modules.values()) + list(ref_infos):
            refs |= info.refs
            star |= info.star_imports
            strings |= info.strings
        for modname, info in sorted(self.modules.items()):
            if not info.all_names:
                continue
            pkg_init = f"{self.root_pkg}.{info.package}"
            is_init = modname == pkg_init
            # a package __init__ re-exports names that live in its
            # submodules; external imports of the SAME name straight
            # from the submodule keep the API surface live
            accept_mods = {modname}
            if is_init:
                accept_mods |= {
                    m for m in self.modules
                    if m.startswith(pkg_init + ".")
                }
            for name, line, col in info.all_names:
                live = False
                for other in list(self.modules.values()) + list(ref_infos):
                    if other is info:
                        continue
                    same_pkg = (
                        other.package == info.package
                        and other.modname.startswith(self.root_pkg + ".")
                    )
                    if same_pkg and other.modname != pkg_init:
                        continue  # intra-package use doesn't count
                    if any((m, name) in other.refs for m in accept_mods):
                        live = True
                        break
                    if accept_mods & other.star_imports:
                        live = True
                        break
                    if name in other.strings:
                        live = True
                        break
                if not live:
                    self._emit(
                        "dead-export", info, line, col,
                        f"{name!r} is exported in __all__ but never "
                        f"referenced outside package {info.package!r} "
                        f"(reference roots included) — drop it from the "
                        f"public API or add the missing consumer",
                    )


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Cycles in a digraph: one representative cycle per SCC (Tarjan),
    as a node path [a, b, ..] meaning a -> b -> .. -> a."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        # iterative Tarjan (analyzed trees can nest deeply)
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(list(reversed(comp)))
                elif node in graph.get(node, ()):
                    sccs.append([node])

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    # order each SCC as an actual cycle path via DFS inside the SCC
    cycles: List[List[str]] = []
    for comp in sccs:
        if len(comp) == 1:
            cycles.append(comp)
            continue
        comp_set = set(comp)
        start = comp[0]
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxt = next(
                (n for n in sorted(graph.get(cur, ())) if n in comp_set
                 and n not in seen), None,
            )
            if nxt is None:
                break
            path.append(nxt)
            seen.add(nxt)
            cur = nxt
        cycles.append(path)
    return cycles


# --------------------------------------------------------------------------
# Reference roots (dead-export consumers outside the analyzed tree)
# --------------------------------------------------------------------------


def load_ref_roots(paths: Sequence[Path], excludes: Sequence[str]) -> List[ModuleInfo]:
    out: List[ModuleInfo] = []
    for p in paths:
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            posix = f.as_posix()
            if any(fnmatch.fnmatch(posix, pat) for pat in excludes):
                continue
            try:
                tree = ast.parse(f.read_text(encoding="utf-8"))
            except (OSError, UnicodeDecodeError, SyntaxError):
                continue
            info = ModuleInfo(path=str(f), modname=f"<ref>{f}", package="<ref>")
            _ModuleCollector(info).visit(tree)
            out.append(info)
    return out


# --------------------------------------------------------------------------
# Graph output
# --------------------------------------------------------------------------


def graph_dict(program: Program, layer_map: LayerMap) -> Dict[str, object]:
    edges: Dict[Tuple[str, str], int] = {}
    deferred: Dict[Tuple[str, str], int] = {}
    for info in program.modules.values():
        for site in info.imports:
            if not site.target.startswith(program.root_pkg):
                continue
            target = program.resolve_module(site.target)
            if target is None:
                continue
            tparts = target.split(".")
            tpkg = tparts[1] if len(tparts) > 1 else ""
            if not tpkg or not info.package or tpkg == info.package:
                continue
            key = (info.package, tpkg)
            edges[key] = edges.get(key, 0) + 1
            if site.deferred:
                deferred[key] = deferred.get(key, 0) + 1
    packages = sorted({m.package for m in program.modules.values() if m.package})
    return {
        "root": program.root_pkg,
        "packages": [
            {"name": p, "layer": layer_map.layers.get(p)} for p in packages
        ],
        "edges": [
            {
                "src": s,
                "dst": d,
                "imports": n,
                "deferred": deferred.get((s, d), 0),
            }
            for (s, d), n in sorted(edges.items())
        ],
    }


def graph_dot(program: Program, layer_map: LayerMap) -> str:
    g = graph_dict(program, layer_map)
    lines = [
        "digraph fabric_tpu_imports {",
        "  rankdir=BT;",
        '  node [shape=box, fontname="Helvetica"];',
    ]
    by_layer: Dict[object, List[str]] = {}
    for pkg in g["packages"]:  # type: ignore[index]
        by_layer.setdefault(pkg["layer"], []).append(pkg["name"])
    for layer, pkgs in sorted(
        by_layer.items(), key=lambda kv: (kv[0] is None, kv[0])
    ):
        lines.append(f"  {{ rank=same; // layer {layer}")
        for p in pkgs:
            label = f"{p}\\n[layer {layer}]" if layer is not None else p
            lines.append(f'    "{p}" [label="{label}"];')
        lines.append("  }")
    for e in g["edges"]:  # type: ignore[index]
        lines.append(
            f'  "{e["src"]}" -> "{e["dst"]}" [label="{e["imports"]}"];'
        )
    lines.append("}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------


#: rule -> the analysis pass that can emit it (for skip_unneeded_passes)
_LAYERING_RULES = {"import-cycle", "layer-skip", "layer-unknown"}
_CONCURRENCY_RULES = {
    "unguarded-shared-write", "lock-order-cycle", "blocking-under-lock"
}
_EXPORT_RULES = {"dead-export"}


def analyze(
    root: Path,
    layer_map: Optional[LayerMap] = None,
    ref_paths: Sequence[Path] = (),
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    skip_unneeded_passes: bool = False,
) -> Tuple[Program, List[Finding]]:
    """Run all passes over the package at `root`.  Returns the Program
    (for graph output / tests) and the unsuppressed findings.

    ``skip_unneeded_passes`` (opt-in: fabreg's suppression-stale rule
    uses it) skips whole analysis passes when no active rule can come
    from them — same unsuppressed findings, but ``program.suppressed``
    then only counts the passes that ran, so the default keeps the
    historical full-run accounting."""
    program = Program(root, excludes)
    program.load()
    program.link()
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    lm = layer_map or LayerMap()
    if not skip_unneeded_passes or active & _LAYERING_RULES:
        program.layering_pass(lm)
    if not skip_unneeded_passes or active & _CONCURRENCY_RULES:
        program.concurrency_pass()
    if not skip_unneeded_passes or active & _EXPORT_RULES:
        refs = load_ref_roots(ref_paths, excludes)
        program.export_pass(refs)
    findings = [
        f for f in program.findings
        if f.rule in active or f.rule == "io-error"
    ]
    findings.sort(key=Finding.key)
    return program, findings


def default_layer_file(root: Path) -> Optional[Path]:
    cand = root / "tools" / "layers.toml"
    return cand if cand.is_file() else None


def default_ref_paths(root: Path) -> List[Path]:
    out: List[Path] = []
    parent = root.resolve().parent
    tests = parent / "tests"
    if tests.is_dir():
        out.append(tests)
    for f in sorted(parent.glob("*.py")):
        out.append(f)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fabdep",
        "whole-program import-layering + concurrency analyzer "
        "for fabric-tpu (dependency-free; never imports the analyzed code)",
        paths_help="package root to analyze",
    )
    parser.add_argument("--dot", action="store_true", help="print the package import graph as DOT and exit")
    parser.add_argument("--graph-json", action="store_true", help="print the package import graph as JSON and exit")
    parser.add_argument("--layers", metavar="FILE", help="layer map file (default: <root>/tools/layers.toml)")
    parser.add_argument("--refs", action="append", default=[], metavar="PATH", help="extra reference roots for the dead-export pass (default: sibling tests/ + repo-root *.py)")
    parser.add_argument("--no-default-refs", action="store_true", help="do not auto-add sibling tests/ and repo-root *.py as reference roots")
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(RULES, width=24)
        return 0

    if len(args.paths) != 1:
        parser.print_usage(sys.stderr)
        print("fabdep: error: exactly one package root required", file=sys.stderr)
        return 2
    root = Path(args.paths[0]).resolve()
    if not root.is_dir():
        print(f"fabdep: error: not a directory: {root}", file=sys.stderr)
        return 2

    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fabdep")
    if rc:
        return rc

    layer_map = LayerMap()
    layer_file = Path(args.layers) if args.layers else default_layer_file(root)
    if layer_file is not None:
        try:
            layer_map = LayerMap.parse(
                layer_file.read_text(encoding="utf-8"), str(layer_file)
            )
        except (OSError, ValueError) as exc:
            print(f"fabdep: error: bad layer map: {exc}", file=sys.stderr)
            return 2

    ref_paths = [Path(p) for p in args.refs]
    if not args.no_default_refs:
        ref_paths.extend(default_ref_paths(root))

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)

    if args.dot or args.graph_json:
        # graph dumps only need the parsed import table — skip the
        # concurrency fixpoint and export scan
        program = Program(root, excludes)
        program.load()
        program.link()
        if args.dot:
            print(graph_dot(program, layer_map))
        if args.graph_json:
            print(json.dumps(graph_dict(program, layer_map), indent=2))
        return 0

    program, findings = analyze(
        root, layer_map, ref_paths, rule_ids, excludes
    )

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "findings": [f.to_dict() for f in findings],
                    "stats": {
                        "modules": len(program.modules),
                        "suppressed": program.suppressed,
                    },
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fabdep: {len(findings)} finding(s), "
            f"{program.suppressed} suppressed, "
            f"{len(program.modules)} modules",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
