"""fabchaos — deterministic fault-injection + adversarial traffic harness.

The bench suite measures clean, uniform batches; production variance
comes from faults (BENCH_r04/r05: backend init hangs, pool breakage,
device loss) and from adversarial traffic (skewed channels, invalid
endorsements, MVCC storms, CRL rotation, malformed blocks).  fabchaos
drives the REAL runtime objects — VerifyBatcher, SoftwareProvider,
CommitPipeline, BlockValidator, the MVCC validator, BlockDeliverer —
through seeded scenarios with faults injected at the
``fabric_tpu.common.faults`` seams, and asserts two invariants on every
scenario:

1. **mask bit-exactness**: the VALID/INVALID verdicts equal the
   by-construction ground truth (spot-checked against the p256 oracle),
   and
2. **fail-closed**: an injected fault may slow or fail a request, but it
   may never flip a verdict toward VALID, wedge a queue, or strand a
   resolver.

This is the empirical twin of fabflow's mask fail-closed proof — and the
``corrupt_detect`` scenario proves the gate has teeth by injecting a
verdict corruption and requiring the mask assertion to CATCH it.

Determinism contract: ``python -m fabric_tpu.tools.fabchaos --seed N
--scenario all`` prints a scorecard JSON on stdout that is byte-identical
across runs (same tree, same flags).  Wall-clock latencies and
thread-order-dependent counters (fault fires, retries observed) are
inherently non-deterministic, so they live in the scorecard's
``observed`` section, which goes to ``--out``/stderr — never stdout.

Usage::

    python -m fabric_tpu.tools.fabchaos --seed 7 --scenario all
    python -m fabric_tpu.tools.fabchaos --seed 7 --scenario smoke --out card.json
    python -m fabric_tpu.tools.fabchaos --list-scenarios
    python -m fabric_tpu.tools.fabchaos --seed 3 --scenario soak --soak-seconds 60
"""

from __future__ import annotations

import argparse
import hashlib
import json
import random
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.common import p256
from fabric_tpu.common.faults import (
    FaultPlan,
    InjectedFault,
    plan_installed,
)
from fabric_tpu.common.retry import RetryPolicy
from fabric_tpu.common.txflags import TxValidationCode
from fabric_tpu.crypto import der, hostec
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider
from fabric_tpu.protos import ab_pb2, common_pb2, protoutil

VALID = TxValidationCode.VALID
NOT_VALIDATED = TxValidationCode.NOT_VALIDATED


class ChaosAssertionError(AssertionError):
    """A scenario invariant failed.  Messages must be deterministic
    (no timings, no ids from memory addresses) — they land in the
    deterministic scorecard."""


def check(cond: bool, msg: str) -> None:
    if not cond:
        raise ChaosAssertionError(msg)


# ---------------------------------------------------------------------------
# Per-stage latency scorecard
# ---------------------------------------------------------------------------


class StageClock:
    """Thread-safe per-stage latency samples -> p50/p99 summary."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: Dict[str, List[float]] = {}

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._samples.setdefault(stage, []).append(seconds)

    def timed(self, stage: str, fn: Callable, *args):
        t0 = time.perf_counter()
        out = fn(*args)
        self.record(stage, time.perf_counter() - t0)
        return out

    @staticmethod
    def _pct(sorted_s: List[float], q: float) -> float:
        # nearest-rank percentile: deterministic given the sample set
        i = min(len(sorted_s) - 1, max(0, int(round(q * (len(sorted_s) - 1)))))
        return sorted_s[i]

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for stage, samples in self._samples.items():
                s = sorted(samples)
                out[stage] = {
                    "n": len(s),
                    "p50_ms": round(self._pct(s, 0.50) * 1e3, 3),
                    "p99_ms": round(self._pct(s, 0.99) * 1e3, 3),
                    "max_ms": round(s[-1] * 1e3, 3),
                }
        return out


# ---------------------------------------------------------------------------
# Seeded workload material
# ---------------------------------------------------------------------------

#: lane corruption kinds with their by-construction expected verdicts
LANE_KINDS = (
    "good",          # True
    "bad_sig",       # flipped signature byte -> False
    "bad_digest",    # verify against a different digest -> False
    "wrong_key",     # someone else's key -> False
    "garbage_der",   # unparseable DER -> False (VerifyError path)
    "high_s",        # S > N/2 -> False (low-S precheck path)
)


class LanePool:
    """A seeded pool of signed messages plus corruption recipes; lanes
    sampled from it carry exact expected verdicts."""

    def __init__(self, rng: random.Random, n_keys: int = 4, n_msgs: int = 24):
        self.keys = []
        for _ in range(n_keys):
            d = rng.randrange(1, p256.N)
            q = hostec.scalar_base_mult(d)
            self.keys.append((d, ECDSAPublicKey(q[0], q[1])))
        self.base = []  # (key_idx, digest, der_sig)
        for i in range(n_msgs):
            ki = rng.randrange(n_keys)
            digest = hashlib.sha256(
                b"fabchaos msg %d %d" % (i, rng.getrandbits(32))
            ).digest()
            r, s = hostec.sign_digest(self.keys[ki][0], digest)
            self.base.append((ki, digest, der.marshal_signature(r, s)))

    def lane(self, rng: random.Random) -> Tuple[ECDSAPublicKey, bytes, bytes, bool, str]:
        """(pub, sig, digest, expected, kind) — expected is exact."""
        ki, digest, sig = self.base[rng.randrange(len(self.base))]
        kind = LANE_KINDS[rng.randrange(len(LANE_KINDS))]
        pub = self.keys[ki][1]
        if kind == "good":
            return pub, sig, digest, True, kind
        if kind == "bad_sig":
            # flip a byte of the S integer (the tail of the DER blob)
            bad = bytearray(sig)
            bad[-1] ^= 0x5A
            return pub, bytes(bad), digest, False, kind
        if kind == "bad_digest":
            return pub, sig, hashlib.sha256(digest).digest(), False, kind
        if kind == "wrong_key":
            other = self.keys[(ki + 1) % len(self.keys)][1]
            return other, sig, digest, False, kind
        if kind == "garbage_der":
            return pub, b"\x00\x01garbage", digest, False, kind
        # high_s: re-encode with S' = N - S (valid curve math, violates
        # the low-S rule -> VerifyError -> False on the batch path)
        r, s = der.unmarshal_signature(sig)
        return (
            pub,
            der.marshal_signature(r, p256.N - s),
            digest,
            False,
            kind,
        )

    def lanes(self, rng: random.Random, n: int):
        keys, sigs, digests, expected, kinds = [], [], [], [], []
        for _ in range(n):
            k, s, d, e, kind = self.lane(rng)
            keys.append(k)
            sigs.append(s)
            digests.append(d)
            expected.append(e)
            kinds.append(kind)
        return keys, sigs, digests, expected, kinds


def mask_hash(mask: Sequence[bool]) -> str:
    return hashlib.sha256(
        bytes(1 if b else 0 for b in mask)
    ).hexdigest()[:16]


def oracle_spot_check(
    rng: random.Random, keys, sigs, digests, expected, n_samples: int = 4
) -> int:
    """Re-derive a seeded sample of expected verdicts with the p256
    oracle (parse + low-S + clarity-first curve math) — the harness's
    ground truth is itself checked against the slowest, clearest tier."""
    n = len(keys)
    for _ in range(min(n_samples, n)):
        i = rng.randrange(n)
        try:
            r, s = der.unmarshal_signature(sigs[i])
            ok = p256.is_low_s(s) and p256.verify_digest(
                keys[i].point, digests[i], r, s
            )
        except der.DerError:
            ok = False
        check(
            ok == expected[i],
            f"oracle disagrees with ground truth at lane {i}: "
            f"oracle={ok} expected={expected[i]}",
        )
    return min(n_samples, n)


# ---------------------------------------------------------------------------
# Scenarios.  Each returns (det, observed): det must be identical for
# identical (seed, scale); observed may carry timings and racy counters.
# ---------------------------------------------------------------------------

SCENARIOS: Dict[str, Callable] = {}


def scenario(name: str):
    def deco(fn):
        SCENARIOS[name] = fn
        return fn

    return deco


def _skewed_channel_lanes(rng: random.Random, n_channels: int, total: int):
    """Zipf-ish per-channel lane counts (channel 0 hottest), min 4."""
    weights = [1.0 / (i + 1) for i in range(n_channels)]
    wsum = sum(weights)
    counts = [max(4, int(total * w / wsum)) for w in weights]
    return counts


@scenario("verify_storm")
def run_verify_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """Multi-channel skewed verify traffic (no faults): N channels with
    zipf-skewed rates submit mixed valid/invalid lanes through ONE
    VerifyBatcher from concurrent threads; every request's verdicts must
    equal ground truth bit-exactly."""
    rng = random.Random(seed * 1000003 + 1)
    pool = LanePool(rng)
    n_channels = 4
    counts = _skewed_channel_lanes(rng, n_channels, int(192 * scale))
    # per-channel deterministic workloads (generated before threading)
    chans = []
    for c in range(n_channels):
        crng = random.Random(seed * 7919 + c)
        reqs = []
        remaining = counts[c]
        while remaining > 0:
            n = min(remaining, 1 + crng.randrange(12))
            remaining -= n
            reqs.append(pool.lanes(crng, n))
        chans.append(reqs)

    provider = SoftwareProvider()
    from fabric_tpu.parallel.batcher import VerifyBatcher

    b = VerifyBatcher(provider, linger_s=0.001)
    mismatches: List[str] = []
    lock = threading.Lock()

    def drive(c: int):
        for keys, sigs, digests, expected, _kinds in chans[c]:
            t0 = time.perf_counter()
            out = b.submit(keys, sigs, digests)()
            clock.record("verify.request", time.perf_counter() - t0)
            if list(out) != expected:
                with lock:
                    mismatches.append(
                        f"ch{c}: got {mask_hash(out)} want {mask_hash(expected)}"
                    )

    threads = [
        threading.Thread(target=drive, args=(c,), daemon=True)
        for c in range(n_channels)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        wedged = sum(t.is_alive() for t in threads)
    finally:
        b.stop()
    check(
        wedged == 0,
        f"{wedged} channel thread(s) still blocked after 120s — wedged "
        "batcher (mask assertions below would be vacuous)",
    )
    check(not mismatches, f"verify mask mismatches: {sorted(mismatches)}")

    flat_expected = [
        e for reqs in chans for (_k, _s, _d, exp, _ki) in reqs for e in exp
    ]
    ksample, ssample, dsample, esample = [], [], [], []
    for reqs in chans:
        for keys, sigs, digests, expected, _kinds in reqs:
            ksample.extend(keys)
            ssample.extend(sigs)
            dsample.extend(digests)
            esample.extend(expected)
    n_oracle = oracle_spot_check(
        random.Random(seed + 17), ksample, ssample, dsample, esample
    )
    det = {
        "channels": n_channels,
        "lanes_per_channel": counts,
        "lanes_total": sum(counts),
        "expected_mask_sha": mask_hash(flat_expected),
        "mask_ok": True,
        "oracle_samples": n_oracle,
    }
    obs = {"launches": b.launches, "lanes": b.lanes}
    return det, obs


@scenario("verify_faults")
def run_verify_faults(seed: int, clock: StageClock, scale: float = 1.0):
    """The same storm under injected dispatch faults (backend flaps at
    the batcher and EC-ladder seams).  Fail-closed contract: every
    request either resolves with EXACTLY the expected verdicts or raises
    InjectedFault — a wrong verdict is a scenario failure, and so is a
    wedged resolver.  The batcher's bounded dispatch retry absorbs most
    flaps (each attempt re-keys the fault decision)."""
    rng = random.Random(seed * 1000003 + 2)
    pool = LanePool(rng)
    reqs = []
    total = int(160 * scale)
    while total > 0:
        n = min(total, 1 + rng.randrange(10))
        total -= n
        reqs.append(pool.lanes(rng, n))

    plan = FaultPlan.parse(
        "batcher.submit=raise:0.2:max=6;"
        "batcher.dispatch=raise:0.35;bccsp.dispatch=raise:0.15:max=6",
        seed=seed,
    )
    provider = SoftwareProvider()
    from fabric_tpu.parallel.batcher import VerifyBatcher

    outcomes = {"ok": 0, "injected": 0, "submit_rejected": 0}
    mismatches: List[str] = []
    with plan_installed(plan):
        b = VerifyBatcher(
            provider,
            linger_s=0.001,
            # deterministic-friendly: no wall-clock deadline pressure,
            # a fixed number of quick attempts
            dispatch_retry=RetryPolicy(
                base_s=0.001, multiplier=2.0, cap_s=0.01,
                deadline_s=10.0, max_attempts=3,
            ),
        )
        try:
            resolvers = []
            for keys, sigs, digests, expected, _kinds in reqs:
                t0 = time.perf_counter()
                try:
                    # the submit seam fires BEFORE lane admission: a
                    # rejected submit must leak nothing into pending
                    resolver = b.submit(keys, sigs, digests)
                except InjectedFault:
                    outcomes["submit_rejected"] += 1
                    continue
                resolvers.append((resolver, expected, t0))
            for resolve, expected, t0 in resolvers:
                try:
                    out = resolve()
                    clock.record("verify.request", time.perf_counter() - t0)
                    if list(out) != expected:
                        mismatches.append(
                            f"got {mask_hash(out)} want {mask_hash(expected)}"
                        )
                    outcomes["ok"] += 1
                except InjectedFault:
                    clock.record(
                        "verify.fault_settle", time.perf_counter() - t0
                    )
                    outcomes["injected"] += 1
        finally:
            b.stop()
    check(not mismatches, f"faulted verify flipped a verdict: {mismatches}")
    check(
        outcomes["ok"] + outcomes["injected"] == len(resolvers),
        "some resolvers neither settled nor raised (wedged batcher)",
    )
    check(
        len(resolvers) + outcomes["submit_rejected"] == len(reqs),
        "a submit neither returned a resolver nor raised InjectedFault",
    )
    det = {
        "requests": len(reqs),
        "lanes_total": sum(len(r[3]) for r in reqs),
        "mask_ok": True,
        "all_settled": True,
    }
    obs = {"outcomes": outcomes, "faults_fired": plan.fired()}
    return det, obs


@scenario("pool_chaos")
def run_pool_chaos(seed: int, clock: StageClock, scale: float = 1.0):
    """Pool-worker kills: a big batch big enough to shard across the
    hostec process pool, with injected submit/resolve failures — the
    degrade path must recompute inline and keep the mask exact, and the
    broken pool's rebuild must respect the cooldown gate."""
    rng = random.Random(seed * 1000003 + 3)
    pool = LanePool(rng)
    n = max(hostec.MIN_POOL_LANES, int(hostec.MIN_POOL_LANES * scale))
    keys, sigs, digests, expected, _kinds = pool.lanes(rng, n)
    provider = SoftwareProvider()

    plan = FaultPlan.parse(
        "hostec.pool.submit=raise:1.0:max=1;"
        "hostec_np.pool.submit=raise:1.0:max=1;"
        "hostec.pool.resolve=raise:1.0:max=1;"
        "hostec_np.pool.resolve=raise:1.0:max=1",
        seed=seed,
    )
    with plan_installed(plan):
        out1 = clock.timed(
            "pool.degraded_batch", provider.batch_verify, keys, sigs, digests
        )
    out2 = clock.timed(
        "pool.clean_batch", provider.batch_verify, keys, sigs, digests
    )
    check(
        list(out1) == expected,
        f"degraded pool flipped the mask: got {mask_hash(out1)} "
        f"want {mask_hash(expected)}",
    )
    check(
        list(out2) == expected,
        f"post-degrade batch wrong: got {mask_hash(out2)} "
        f"want {mask_hash(expected)}",
    )
    det = {
        "lanes": n,
        "expected_mask_sha": mask_hash(expected),
        "mask_ok": True,
        "degrade_inline_ok": True,
    }
    obs = {"faults_fired": plan.fired(), "backend": provider.describe_backend()}
    return det, obs


class _ChaosChannel:
    """Synthetic channel for CommitPipeline scenarios: store applies
    writes to a dict; ordering and write effects are fully observable."""

    def __init__(self, channel_id: str, store_delay_s: float = 0.0):
        self.channel_id = channel_id
        self.state: Dict[str, int] = {}
        self.committed: List[int] = []
        self.store_delay_s = store_delay_s

    def prepare_block(self, block):
        return {"writes": {f"k{block.header.number % 7}": block.header.number}}

    def store_block(self, block, prepared=None):
        if self.store_delay_s:
            time.sleep(self.store_delay_s)
        self.state.update(prepared["writes"])
        self.committed.append(block.header.number)
        return prepared["writes"]


@scenario("commit_storm")
def run_commit_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """Commit-stage faults: a seeded subset of block commits raises
    inside the commit loop.  The pipeline must keep draining (slow, not
    dead), route every failure to on_error exactly once, record
    last_error, and commit every non-faulted block in order."""
    n_blocks = max(8, int(24 * scale))
    # pipeline.commit decisions key on the block number: precompute the
    # exact fault set the seeded plan will choose
    from fabric_tpu.common.faults import _keyed_hit

    prob = 0.3
    expect_fail = {
        num for num in range(n_blocks)
        if _keyed_hit(seed, "pipeline.commit", num, prob)
    }
    plan = FaultPlan.parse(f"pipeline.commit=raise:{prob}", seed=seed)

    from fabric_tpu.peer.pipeline import CommitPipeline

    ch = _ChaosChannel("chaos")
    errors: List[int] = []
    with plan_installed(plan):
        pipe = CommitPipeline(
            ch,
            on_error=lambda b, exc: errors.append(b.header.number),
        )
        try:
            for num in range(n_blocks):
                block = protoutil.new_block(num, b"")
                t0 = time.perf_counter()
                pipe.submit(block)
                clock.record("commit.submit", time.perf_counter() - t0)
            drained = pipe.drain(timeout=60)
            # sample liveness BEFORE the cleanup stop(): the un-latched
            # half of `dead` is defined against a not-yet-stopped pipe
            died = pipe.dead
        finally:
            pipe.stop()
    check(drained, "pipeline failed to drain under injected commit faults")
    check(not died, "committer thread died (dead, not slow)")
    check(
        sorted(errors) == sorted(expect_fail),
        f"on_error set {sorted(errors)} != injected set {sorted(expect_fail)}",
    )
    check(
        ch.committed == [n for n in range(n_blocks) if n not in expect_fail],
        f"commit order/coverage wrong: {ch.committed}",
    )
    check(
        (pipe.last_error is not None) == bool(expect_fail),
        "last_error not recorded for a failed commit",
    )
    if expect_fail:
        check(
            isinstance(pipe.last_error, InjectedFault),
            f"last_error is {type(pipe.last_error).__name__}, "
            "expected InjectedFault",
        )
    det = {
        "blocks": n_blocks,
        "injected_commit_failures": sorted(expect_fail),
        "committed": ch.committed,
        "drained": True,
        "last_error_recorded": bool(expect_fail),
    }
    obs = {"faults_fired": plan.fired()}
    return det, obs


@scenario("mvcc_storm")
def run_mvcc_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """MVCC conflict storm: zipf-skewed key traffic with stale reads and
    intra-block write-write collisions, validated block by block by the
    real MVCC validator and replayed against an independent sequential
    model; codes must match exactly."""
    from fabric_tpu.ledger.mvcc import Validator
    from fabric_tpu.ledger.rwset import (
        KVRead,
        KVWrite,
        NsRwSet,
        TxRwSet,
        Version,
    )
    from fabric_tpu.ledger.statedb import VersionedDB

    rng = random.Random(seed * 1000003 + 4)
    n_blocks = max(4, int(8 * scale))
    txs_per_block = 24
    keys = [f"k{i}" for i in range(12)]

    db = VersionedDB()
    validator = Validator(db)
    model: Dict[str, Tuple[int, int]] = {}  # key -> committed version
    codes_all: List[int] = []
    expected_all: List[int] = []

    for bn in range(1, n_blocks + 1):
        rwsets = []
        reads_list = []
        for _ in range(txs_per_block):
            # zipf-ish: low-index keys far hotter -> conflict storms
            k = keys[min(int(rng.paretovariate(1.2)) - 1, len(keys) - 1)]
            stale = rng.random() < 0.25
            committed = model.get(k)
            if stale and committed is not None:
                read_ver = Version(committed[0], committed[1] + 1)
            else:
                read_ver = (
                    Version(*committed) if committed is not None else None
                )
            reads_list.append((k, read_ver, stale and committed is not None))
            rwsets.append(
                TxRwSet(
                    (
                        NsRwSet(
                            "cc",
                            (KVRead(k, read_ver),),
                            (KVWrite(k, False, b"v%d" % bn),),
                        ),
                    )
                )
            )
        incoming = [VALID] * txs_per_block
        t0 = time.perf_counter()
        codes, updates, hashed = validator.validate_and_prepare_batch(
            bn, rwsets, incoming
        )
        clock.record("mvcc.block", time.perf_counter() - t0)
        db.apply_updates(updates, hashed)

        # independent sequential model of the same semantics
        block_writes: Dict[str, int] = {}
        expected = []
        for tx_num, (k, read_ver, _stale) in enumerate(reads_list):
            committed = model.get(k)
            committed_ver = Version(*committed) if committed else None
            ok = (
                k not in block_writes
                and (
                    (read_ver is None and committed_ver is None)
                    or (
                        read_ver is not None
                        and committed_ver is not None
                        and read_ver == committed_ver
                    )
                )
            )
            if ok:
                block_writes[k] = tx_num
                expected.append(int(VALID))
            else:
                expected.append(int(TxValidationCode.MVCC_READ_CONFLICT))
        for k, tx_num in block_writes.items():
            model[k] = (bn, tx_num)
        codes_all.extend(int(c) for c in codes)
        expected_all.extend(expected)

    check(
        codes_all == expected_all,
        "MVCC codes diverged from the sequential model at indexes "
        f"{[i for i, (a, b) in enumerate(zip(codes_all, expected_all)) if a != b][:8]}",
    )
    n_conflicts = sum(
        1 for c in codes_all if c == int(TxValidationCode.MVCC_READ_CONFLICT)
    )
    det = {
        "blocks": n_blocks,
        "txs": len(codes_all),
        "mvcc_conflicts": n_conflicts,
        "codes_sha": hashlib.sha256(bytes(codes_all)).hexdigest()[:16],
        "model_match": True,
    }
    check(n_conflicts > 0, "storm produced no conflicts — not a storm")
    return det, {}


# -- full-block validation plane (fake MSP, real BlockValidator) -----------


class _FakeIdentity:
    """Duck-typed msp.identity.Identity: raw P-256 point as the 'cert'."""

    def __init__(self, msp_id: str, serialized: bytes, pub: ECDSAPublicKey):
        self.msp_id = msp_id
        self._serialized = serialized
        self.public_key = pub
        self.ou_values: List[str] = []

    def serialize(self) -> bytes:
        return self._serialized

    def fingerprint(self) -> bytes:
        return hashlib.sha256(self._serialized).digest()


class _FakeMSP:
    """MSPManager+MSP in one: identities are SerializedIdentity protos
    whose id_bytes are 'raw:' + uncompressed point; validate() honors a
    mutable revocation set — CRL rotation is one set-add away."""

    def __init__(self, msp_id: str):
        self.msp_id = msp_id
        self.revoked: set = set()  # fingerprints
        self._lock = threading.Lock()

    # MSPManager surface
    def deserialize_identity(self, serialized: bytes):
        from fabric_tpu.msp.identity import MSPError
        from fabric_tpu.protos import identities_pb2

        sid = protoutil.unmarshal(
            identities_pb2.SerializedIdentity, serialized
        )
        raw = sid.id_bytes
        if not raw.startswith(b"raw:") or len(raw) != 4 + 65:
            raise MSPError("unparseable fake identity")
        x = int.from_bytes(raw[5:37], "big")
        y = int.from_bytes(raw[37:69], "big")
        return _FakeIdentity(sid.mspid, serialized, ECDSAPublicKey(x, y)), self

    def get_msp(self, msp_id: str):
        from fabric_tpu.msp.identity import MSPError

        if msp_id != self.msp_id:
            raise MSPError(f"MSP {msp_id} is unknown")
        return self

    # MSP surface
    def validate(self, ident: _FakeIdentity) -> None:
        from fabric_tpu.msp.identity import MSPError

        with self._lock:
            if ident.fingerprint() in self.revoked:
                raise MSPError("identity revoked (fake CRL)")

    def satisfies_principal(self, ident, principal) -> None:
        from fabric_tpu.msp.identity import MSPError
        from fabric_tpu.protos import msp_principal_pb2

        P = msp_principal_pb2.MSPPrincipal
        if principal.principal_classification != P.ROLE:
            raise MSPError("fake MSP supports ROLE principals only")
        role = protoutil.unmarshal(
            msp_principal_pb2.MSPRole, principal.principal
        )
        if role.msp_identifier != self.msp_id:
            raise MSPError("different MSP")
        self.validate(ident)

    def revoke(self, signer: "_ChaosSigner") -> None:
        with self._lock:
            self.revoked.add(hashlib.sha256(signer.serialize()).digest())


class _ChaosSigner:
    """SigningIdentity stand-in with seeded nonces (deterministic
    tx_ids) and a raw-point 'certificate' the fake MSP can parse."""

    def __init__(self, msp_id: str, rng: random.Random):
        self.msp_id = msp_id
        self.d = rng.randrange(1, p256.N)
        q = hostec.scalar_base_mult(self.d)
        self.pub = ECDSAPublicKey(q[0], q[1])
        raw = (
            b"raw:\x04"
            + q[0].to_bytes(32, "big")
            + q[1].to_bytes(32, "big")
        )
        self._serialized = protoutil.serialize_identity(msp_id, raw)
        self._rng = rng
        self.corrupt_next = False  # one-shot: emit an invalid signature

    def serialize(self) -> bytes:
        return self._serialized

    def new_nonce(self) -> bytes:
        return self._rng.getrandbits(192).to_bytes(24, "big")

    def sign(self, msg: bytes) -> bytes:
        digest = hashlib.sha256(msg).digest()
        r, s = hostec.sign_digest(self.d, digest)
        sig = der.marshal_signature(r, s)
        if self.corrupt_next:
            self.corrupt_next = False
            bad = bytearray(sig)
            bad[-1] ^= 0x5A
            sig = bytes(bad)
        return sig


def _make_validation_world(seed: int):
    from fabric_tpu.policy.ast import from_dsl
    from fabric_tpu.validation.validator import (
        BlockValidator,
        ChaincodeDefinition,
        ChaincodeRegistry,
    )

    rng = random.Random(seed * 1000003 + 5)
    msp = _FakeMSP("ChaosMSP")
    client = _ChaosSigner("ChaosMSP", rng)
    endorser = _ChaosSigner("ChaosMSP", rng)
    registry = ChaincodeRegistry(
        [ChaincodeDefinition("cc", from_dsl("OR('ChaosMSP.member')"))]
    )
    validator = BlockValidator("chaoschan", msp, SoftwareProvider(), registry)
    return rng, msp, client, endorser, validator


def _endorsed_tx(
    client: _ChaosSigner, endorser: _ChaosSigner, key: str
) -> common_pb2.Envelope:
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger import rwset as rw
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset

    bundle = create_proposal(client, "chaoschan", "cc", [b"put", key.encode()])
    results = serialize_tx_rwset(
        rw.TxRwSet((rw.NsRwSet("cc", (), (rw.KVWrite(key, False, b"v"),)),))
    )
    responses = [endorse_proposal(bundle, endorser, results)]
    return create_signed_tx(bundle, client, responses)


def _build_block(num: int, prev: bytes, envs: Sequence[bytes]):
    block = protoutil.new_block(num, prev)
    for raw in envs:
        block.data.data.append(raw)
    protoutil.seal_block(block)
    return block


@scenario("crl_rotation")
def run_crl_rotation(seed: int, clock: StageClock, scale: float = 1.0):
    """CRL rotation mid-stream against the REAL BlockValidator: blocks
    validated before the rotation accept the endorser; after the fake
    CRL revokes it, its endorsements must flip to
    ENDORSEMENT_POLICY_FAILURE and a revoked creator to
    BAD_CREATOR_SIGNATURE — with the identity cache's generation
    discipline keeping stale pre-rotation entries out."""
    rng, msp, client, endorser, validator = _make_validation_world(seed)
    n_pre = max(2, int(3 * scale))
    n_post = n_pre
    txs_per_block = 4
    flags_seq: List[List[int]] = []
    prev = b""

    def validate_block(num: int, corrupt_lane: Optional[int] = None):
        nonlocal prev
        envs = []
        for i in range(txs_per_block):
            if corrupt_lane == i:
                endorser.corrupt_next = True
            envs.append(
                _endorsed_tx(client, endorser, f"b{num}k{i}").SerializeToString()
            )
        block = _build_block(num, prev, envs)
        prev = protoutil.block_header_hash(block.header)
        t0 = time.perf_counter()
        flags = validator.validate(block)
        clock.record("validator.block", time.perf_counter() - t0)
        return [int(flags.flag(i)) for i in range(txs_per_block)]

    for num in range(n_pre):
        # one corrupted endorsement per pre-rotation block: the mixed
        # valid/invalid mask proves lanes are independent
        flags_seq.append(validate_block(num, corrupt_lane=txs_per_block - 1))
    for row in flags_seq:
        check(
            row[:-1] == [int(VALID)] * (txs_per_block - 1)
            and row[-1] == int(TxValidationCode.ENDORSEMENT_POLICY_FAILURE),
            f"pre-rotation flags wrong: {row}",
        )

    msp.revoke(endorser)  # CRL rotation mid-stream
    # the validator's ident cache may still hold the endorser validated
    # against the pre-rotation CRL: invalidate through the same public
    # seam the config-tx path uses (generation bump + cache drop)
    validator.invalidate_identity_caches()

    post_rows = [validate_block(n_pre + k) for k in range(n_post)]
    for row in post_rows:
        check(
            row == [int(TxValidationCode.ENDORSEMENT_POLICY_FAILURE)]
            * txs_per_block,
            f"post-rotation flags must all fail policy: {row}",
        )
    flags_seq.extend(post_rows)

    # revoked CREATOR: every lane dies at the creator signature
    msp.revoke(client)
    validator.invalidate_identity_caches()
    creator_row = validate_block(n_pre + n_post)
    check(
        creator_row
        == [int(TxValidationCode.BAD_CREATOR_SIGNATURE)] * txs_per_block,
        f"revoked creator flags wrong: {creator_row}",
    )
    flags_seq.append(creator_row)

    det = {
        "blocks": len(flags_seq),
        "txs_per_block": txs_per_block,
        "flags": flags_seq,
        "rotation_honored": True,
    }
    return det, {"backend": validator.last_sig_backend}


@scenario("malformed_blocks")
def run_malformed_blocks(seed: int, clock: StageClock, scale: float = 1.0):
    """Malformed + oversized envelopes through the real BlockValidator:
    garbage bytes, truncated protos, an empty envelope, and an oversized
    (256 KiB arg) tx mixed with good txs.  Every malformed lane must
    carry an INVALID-family code (never VALID, never NOT_VALIDATED —
    fail closed), good lanes stay VALID, and nothing raises."""
    rng, msp, client, endorser, validator = _make_validation_world(seed + 1)
    good = _endorsed_tx(client, endorser, "good").SerializeToString()
    oversized = _oversized_tx(client, endorser)
    envs = [
        good,
        b"\x00\x01\x02 garbage",
        good[: len(good) // 3],  # truncated
        b"",
        oversized,
        good[:-7] + b"\x00" * 7,  # corrupted tail
    ]
    block = _build_block(0, b"", envs)
    t0 = time.perf_counter()
    flags = validator.validate(block)
    clock.record("validator.malformed_block", time.perf_counter() - t0)
    codes = [int(flags.flag(i)) for i in range(len(envs))]
    check(codes[0] == int(VALID), f"good lane not VALID: {codes[0]}")
    check(codes[4] == int(VALID), f"oversized lane not VALID: {codes[4]}")
    for i in (1, 2, 3, 5):
        check(
            codes[i] not in (int(VALID), int(NOT_VALIDATED)),
            f"malformed lane {i} fails open: code {codes[i]}",
        )
    # KiB bucket: the exact byte count varies with DER signature length
    # (leading-zero padding of r/s under a random nonce)
    det = {
        "codes": codes,
        "oversized_kib": len(oversized) // 1024,
        "fail_closed": True,
    }
    return det, {}


def _oversized_tx(client: _ChaosSigner, endorser: _ChaosSigner) -> bytes:
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger import rwset as rw
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset

    bundle = create_proposal(
        client, "chaoschan", "cc", [b"put", b"big", b"\xab" * (256 * 1024)]
    )
    results = serialize_tx_rwset(
        rw.TxRwSet((rw.NsRwSet("cc", (), (rw.KVWrite("big", False, b"v"),)),))
    )
    responses = [endorse_proposal(bundle, endorser, results)]
    return create_signed_tx(bundle, client, responses).SerializeToString()


@scenario("deliver_flap")
def run_deliver_flap(seed: int, clock: StageClock, scale: float = 1.0):
    """Endpoint failover under a seeded flap plan: the primary endpoint
    fails the first N connection attempts (injected), the deliverer's
    shared retry policy paces bounded backoff, delivery resumes on the
    secondary, and the total-delay deadline is honored when EVERY
    endpoint is dead."""
    from fabric_tpu.deliver.client import BlockDeliverer

    n_blocks = max(6, int(10 * scale))
    blocks = [protoutil.new_block(i, b"") for i in range(n_blocks)]
    flap_n = 3

    calls: List[str] = []

    def endpoint(name: str):
        def serve(env):
            calls.append(name)
            start = _seek_start(env)
            for b in blocks[start:]:
                resp = ab_pb2.DeliverResponse()
                resp.block.CopyFrom(b)
                yield resp

        return serve

    got: List[int] = []
    sleeps: List[float] = []
    # deliver.pull is keyed on connect_attempts (1-based): fail 1..flap_n
    plan = FaultPlan.parse(
        f"deliver.pull=raise:1.0:max={flap_n}", seed=seed
    )
    d = BlockDeliverer(
        "chaoschan",
        [endpoint("primary"), endpoint("secondary")],
        on_block=lambda b: got.append(b.header.number),
        next_block=lambda: len(got),
        sleeper=lambda s: sleeps.append(round(s, 6)),
        retry_policy=RetryPolicy(
            base_s=0.05, multiplier=2.0, cap_s=0.4, deadline_s=30.0
        ),
    )
    with plan_installed(plan):
        t0 = time.perf_counter()
        received = d.run(max_blocks=n_blocks)
        clock.record("deliver.session", time.perf_counter() - t0)
    check(received == n_blocks, f"delivered {received}/{n_blocks}")
    check(got == list(range(n_blocks)), f"block order wrong: {got}")
    check(
        len(sleeps) == flap_n,
        f"retries not bounded by the flap count: {len(sleeps)} sleeps",
    )
    expected_backoff = [
        round(min(0.05 * 2.0**i, 0.4), 6) for i in range(flap_n)
    ]
    check(
        sleeps == expected_backoff,
        f"backoff ramp {sleeps} != policy {expected_backoff}",
    )
    # attempts 1..flap_n flapped; failover advanced the index each time,
    # so the serving attempt lands deterministically
    serving_endpoint = ("primary", "secondary")[flap_n % 2]
    check(
        calls and calls[-1] == serving_endpoint,
        f"served by {calls[-1] if calls else None}, want {serving_endpoint}",
    )

    # phase 2: all endpoints dead -> the deadline stops the session
    dead_sleeps: List[float] = []
    plan2 = FaultPlan.parse("deliver.pull=raise:1.0", seed=seed)
    d2 = BlockDeliverer(
        "chaoschan",
        [endpoint("primary")],
        on_block=lambda b: None,
        next_block=lambda: 0,
        sleeper=lambda s: dead_sleeps.append(s),
        retry_policy=RetryPolicy(
            base_s=0.05, multiplier=2.0, cap_s=0.4, deadline_s=1.0
        ),
    )
    with plan_installed(plan2):
        received2 = d2.run(max_blocks=1)
    check(received2 == 0, "dead fabric somehow delivered")
    check(
        sum(dead_sleeps) <= 1.0 + 1e-9,
        f"deadline violated: slept {sum(dead_sleeps)}s nominal > 1.0s budget",
    )
    det = {
        "blocks": n_blocks,
        "flaps": flap_n,
        "backoff_ramp": expected_backoff,
        "served_by": serving_endpoint,
        "deadline_honored": True,
        "dead_session_sleep_s": round(sum(dead_sleeps), 6),
    }
    return det, {"endpoint_calls": len(calls)}


def _seek_start(env: common_pb2.Envelope) -> int:
    payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
    seek = protoutil.unmarshal(ab_pb2.SeekInfo, payload.data)
    return seek.start.specified.number


@scenario("corrupt_detect")
def run_corrupt_detect(seed: int, clock: StageClock, scale: float = 1.0):
    """Self-test of the oracle gate: inject a verdict corruption at the
    bccsp.verdict seam and require the bit-exact mask assertion to CATCH
    it.  If the harness would accept a corrupted mask, this scenario
    fails — fabchaos proving fabchaos, the runtime analog of fabflow's
    pinned firing fixture."""
    rng = random.Random(seed * 1000003 + 6)
    pool = LanePool(rng)
    keys, sigs, digests, expected, _kinds = pool.lanes(rng, 24)
    provider = SoftwareProvider()
    plan = FaultPlan.parse("bccsp.verdict=corrupt:1.0:lanes=3", seed=seed)
    with plan_installed(plan):
        out = clock.timed(
            "verify.corrupted_batch", provider.batch_verify, keys, sigs, digests
        )
    detected = list(out) != expected
    check(
        detected,
        "verdict corruption went UNDETECTED — the mask oracle gate is blind",
    )
    # and the corruption is bounded to what the plan asked for
    n_flipped = sum(1 for a, b in zip(out, expected) if a != b)
    check(n_flipped == 3, f"corrupt width {n_flipped} != plan lanes=3")
    clean = provider.batch_verify(keys, sigs, digests)
    check(list(clean) == expected, "mask corrupt AFTER the plan was removed")
    det = {
        "lanes": len(keys),
        "corruption_detected": True,
        "flipped_lanes": n_flipped,
        "clean_after_uninstall": True,
    }
    return det, {"faults_fired": plan.fired()}


# ---------------------------------------------------------------------------
# idemix_storm: adversarial Idemix traffic through the batch rung
# ---------------------------------------------------------------------------

#: per-seed deterministic Idemix worlds (issuer keys cost seconds of
#: host bignum; same seed -> same world, so caching preserves the
#: determinism contract while the reproducibility test reruns scenarios)
_IDEMIX_WORLDS: Dict[int, Dict] = {}


def _idemix_world(seed: int) -> Dict:
    """Issuer + credential + the adversarial signature flavor set, all
    seeded; oracle (scheme rung) verdicts per flavor are the ground
    truth the batch rung's mask is asserted against bit-exactly."""
    world = _IDEMIX_WORLDS.get(seed)
    if world is not None:
        return world
    import random as _random

    from fabric_tpu import idemix
    from fabric_tpu.crypto import fp256bn as bncurve
    from fabric_tpu.idemix.batch import verify_signatures_batch
    from fabric_tpu.protos import idemix_pb2

    rng = _random.Random(seed * 1000003 + 11)
    attrs = ["OU", "Role"]
    rh_index = 1
    ik = idemix.new_issuer_key(attrs, rng)
    sk = bncurve.rand_mod_order(rng)
    nonce = bncurve.big_to_bytes(bncurve.rand_mod_order(rng))
    req = idemix.new_cred_request(sk, nonce, ik.ipk, rng)
    cred = idemix.new_credential(ik, req, [21, 42], rng)
    cri = idemix_pb2.CredentialRevocationInformation()
    cri.revocation_alg = idemix.ALG_NO_REVOCATION

    def sign(disclosure, msg):
        nym, r_nym = idemix.make_nym(sk, ik.ipk, rng)
        return idemix.new_signature(
            cred, sk, nym, r_nym, ik.ipk, disclosure, msg, rh_index, cri, rng
        )

    hid, dis = [0, 0], [0, 1]
    s_hid = sign(hid, b"storm m0")
    s_dis = sign(dis, b"storm m1")
    s_tmp = sign(hid, b"storm m2")

    def variant(base, mutate):
        sig = idemix_pb2.Signature()
        sig.CopyFrom(base)
        mutate(sig)
        return sig

    def bump_scalar(field):
        def mutate(sig):
            v = bncurve.big_from_bytes(getattr(sig, field))
            setattr(sig, field, bncurve.big_to_bytes((v + 1) % bncurve.R))
        return mutate

    def off_curve(sig):
        sig.a_bar.x = bncurve.big_to_bytes(12345)
        sig.a_bar.y = bncurve.big_to_bytes(67890)

    def identity_abar(sig):
        sig.a_bar.x = bncurve.big_to_bytes(0)
        sig.a_bar.y = bncurve.big_to_bytes(0)

    def identity_aprime(sig):
        sig.a_prime.x = bncurve.big_to_bytes(0)
        sig.a_prime.y = bncurve.big_to_bytes(0)

    # (flavor, sig, disclosure, msg, values)
    flavors = [
        ("valid_hidden", s_hid, hid, b"storm m0", [None, None]),
        ("valid_disclosed", s_dis, dis, b"storm m1", [None, 42]),
        ("wrong_message", s_tmp, hid, b"WRONG", [None, None]),
        (
            "corrupted_proof_scalar",
            variant(s_hid, bump_scalar("proof_s_sk")),
            hid, b"storm m0", [None, None],
        ),
        (
            "bad_challenge",
            variant(s_tmp, bump_scalar("proof_c")),
            hid, b"storm m2", [None, None],
        ),
        (
            "wrong_attribute_commitment",
            s_dis, dis, b"storm m1", [None, 999],
        ),
        (
            "off_group_point",
            variant(s_hid, off_curve), hid, b"storm m0", [None, None],
        ),
        (
            "identity_abar",
            variant(s_tmp, identity_abar), hid, b"storm m2", [None, None],
        ),
        (
            "identity_aprime",
            variant(s_dis, identity_aprime), dis, b"storm m1", [None, 42],
        ),
    ]
    expected = []
    for _name, sig, disclosure, msg, values in flavors:
        expected.extend(
            verify_signatures_batch(
                [sig], [disclosure], ik.ipk, [msg], [values], rh_index,
                backend="scheme",
            )
        )
    world = {
        "ipk": ik.ipk,
        "rh_index": rh_index,
        "flavors": flavors,
        "expected": expected,
    }
    if len(_IDEMIX_WORLDS) >= 4:
        _IDEMIX_WORLDS.pop(next(iter(_IDEMIX_WORLDS)))
    _IDEMIX_WORLDS[seed] = world
    return world


@scenario("idemix_storm")
def run_idemix_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """Mixed valid/invalid Idemix signatures (bad challenge, wrong
    attribute commitment, corrupted proof scalar, off-group point,
    identity A'/ABar) through the ACTIVE batch rung (hostbn numpy lanes
    when numpy is present, else the scheme oracle), mask asserted
    bit-exact against the scheme.verify_signature ground truth — then
    the ``idemix.verdict`` corrupt seam is armed and the SAME assertion
    must catch the injected verdict flips (the idemix slice of
    corrupt_detect).  Excluded from the CI smoke: the issuer/signature
    setup costs seconds of host bignum."""
    from fabric_tpu.crypto.bccsp import idemix_backend_name
    from fabric_tpu.idemix.batch import verify_signatures_batch

    rng = random.Random(seed * 1000003 + 12)
    world = clock.timed("idemix.world", _idemix_world, seed)
    flavors = world["flavors"]
    expected_by_flavor = world["expected"]

    # tile the flavor set to the lane count and shuffle, seeded
    n_lanes = max(len(flavors), int(round(len(flavors) * 2 * scale)))
    order = [i % len(flavors) for i in range(n_lanes)]
    rng.shuffle(order)
    sigs = [flavors[i][1] for i in order]
    disclosures = [flavors[i][2] for i in order]
    msgs = [flavors[i][3] for i in order]
    values = [flavors[i][4] for i in order]
    expected = [expected_by_flavor[i] for i in order]
    check(
        any(expected) and not all(expected),
        "flavor set must mix valid and invalid lanes",
    )

    t0 = time.perf_counter()
    out = verify_signatures_batch(
        sigs, disclosures, world["ipk"], msgs, values, world["rh_index"]
    )
    clock.record("idemix.batch_verify", time.perf_counter() - t0)
    check(
        list(out) == expected,
        f"idemix batch mask mismatch: got {mask_hash(out)} "
        f"want {mask_hash(expected)}",
    )

    # the mask gate must CATCH an injected verdict corruption on the rung
    plan = FaultPlan.parse("idemix.verdict=corrupt:1.0:lanes=2", seed=seed)
    with plan_installed(plan):
        corrupted = clock.timed(
            "idemix.corrupted_batch",
            verify_signatures_batch,
            sigs, disclosures, world["ipk"], msgs, values, world["rh_index"],
        )
    check(
        list(corrupted) != expected,
        "idemix verdict corruption went UNDETECTED — the mask gate is blind",
    )
    n_flipped = sum(1 for a, b in zip(corrupted, expected) if a != b)
    check(n_flipped == 2, f"corrupt width {n_flipped} != plan lanes=2")
    clean = verify_signatures_batch(
        sigs, disclosures, world["ipk"], msgs, values, world["rh_index"]
    )
    check(list(clean) == expected, "mask corrupt AFTER the plan was removed")

    # the hostbn pool seams: an injected submit failure AND a mid-batch
    # resolve failure must each degrade to inline verification with the
    # SAME mask (a pool death can never cost a verdict).  Env-scoped so
    # the storm batch actually routes through the pool machinery
    # (MIN_POOL default 64 >> the storm's lane count).
    pool_faults: Dict[str, int] = {}
    pool_degrade_ok = False
    if idemix_backend_name() == "hostbn":
        import os

        from fabric_tpu.idemix import batch as idemix_batch

        knobs = {
            "FABRIC_TPU_HOSTBN_MIN_POOL": "4",
            "FABRIC_TPU_HOSTBN_MIN_SHARD": "2",
            "FABRIC_TPU_HOSTBN_PROCS": "2",
        }
        saved = {k: os.environ.get(k) for k in knobs}
        os.environ.update(knobs)
        try:
            # an earlier batch in this process may have cached a pool
            # built under the pre-knob worker count (or _POOL = False on
            # a 1-CPU box); tear it down so _pool() re-reads the knobs
            # and the fault seams are actually reached
            idemix_batch.shutdown_pool()
            idemix_batch.reset_pool_cooldown()
            plan_pool = FaultPlan.parse(
                "hostbn.pool.submit=raise:1.0:max=1;"
                "hostbn.pool.resolve=raise:1.0:max=1",
                seed=seed,
            )
            with plan_installed(plan_pool):
                # leg A: submit fails before any future exists ->
                # broken-pool teardown + inline recompute
                out_a = clock.timed(
                    "idemix.pool_submit_degrade",
                    verify_signatures_batch,
                    sigs, disclosures, world["ipk"], msgs, values,
                    world["rh_index"],
                )
                check(
                    list(out_a) == expected,
                    f"hostbn pool submit-degrade flipped the mask: got "
                    f"{mask_hash(out_a)} want {mask_hash(expected)}",
                )
                # leg B: close the cooldown the broken teardown armed,
                # rebuild, and die mid-batch at the resolve seam
                idemix_batch.reset_pool_cooldown()
                out_b = clock.timed(
                    "idemix.pool_resolve_degrade",
                    verify_signatures_batch,
                    sigs, disclosures, world["ipk"], msgs, values,
                    world["rh_index"],
                )
                check(
                    list(out_b) == expected,
                    f"hostbn pool resolve-degrade flipped the mask: got "
                    f"{mask_hash(out_b)} want {mask_hash(expected)}",
                )
            pool_faults = plan_pool.fired()
            check(
                pool_faults.get("hostbn.pool.submit", 0) == 1
                and pool_faults.get("hostbn.pool.resolve", 0) == 1,
                f"hostbn pool faults never armed: {pool_faults}",
            )
            pool_degrade_ok = True
        finally:
            idemix_batch.shutdown_pool()
            idemix_batch.reset_pool_cooldown()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    det = {
        "backend": idemix_backend_name(),
        "lanes": n_lanes,
        "flavors": [name for name, *_ in flavors],
        "mask": mask_hash(expected),
        "valid_lanes": sum(expected),
        "corruption_detected": True,
        "flipped_lanes": n_flipped,
        "clean_after_uninstall": True,
        "pool_degrade_ok": pool_degrade_ok,
    }
    return det, {"faults_fired": plan.fired(), "pool_faults": pool_faults}


# ---------------------------------------------------------------------------
# serve_flap: the resident sidecar killed/restarted mid-stream
# ---------------------------------------------------------------------------


@scenario("serve_flap")
def run_serve_flap(seed: int, clock: StageClock, scale: float = 1.0):
    """Resident-sidecar chaos: mixed batches through the serve rung with
    (1) injected serve.dispatch faults, (2) an admission-control squeeze
    that must produce explicit ST_BUSY rejects, (3) the sidecar KILLED
    mid-batch (async dispatch in flight), and (4) a restart on the same
    address.  Every phase's masks must equal ground truth bit-exactly —
    a dead sidecar degrades the client to in-process verification, it
    never costs a verdict (fail-closed, never fail-open)."""
    import os
    import shutil
    import tempfile

    from fabric_tpu.serve.client import SidecarProvider
    from fabric_tpu.serve.server import SidecarServer

    rng = random.Random(seed * 1000003 + 11)
    pool = LanePool(rng)
    addr = os.path.join(tempfile.mkdtemp(prefix="fabchaos-serve-"), "s.sock")
    det: Dict[str, object] = {}
    obs: Dict[str, object] = {}
    server = SidecarServer(
        addr, engine="host", warm_ladder="off", buckets=(64, 256, 1024)
    )
    server.warm()
    server.start()
    provider = SidecarProvider(address=addr, sleeper=lambda s: None)
    server2 = None
    provider2 = None
    try:
        # -- phase 1: clean mixed traffic through the warm sidecar
        keys, sigs, digests, expected, _ = pool.lanes(rng, int(96 * scale))
        out = clock.timed(
            "serve.clean", provider.batch_verify, keys, sigs, digests
        )
        check(list(out) == expected, "clean sidecar mask != ground truth")
        oracle_spot_check(rng, keys, sigs, digests, expected)
        det["clean_mask"] = mask_hash(out)
        det["clean_lanes"] = len(out)
        check(not provider.degraded, "clean phase degraded the provider")

        # -- phase 2: injected serve.dispatch faults; the client's
        # bounded retry (or its in-process degrade) keeps masks exact
        plan = FaultPlan.parse("serve.dispatch=raise:0.5", seed=seed)
        k2, s2, d2, e2, _ = pool.lanes(rng, 64)
        with plan_installed(plan):
            out2 = clock.timed(
                "serve.dispatch_faults", provider.batch_verify, k2, s2, d2
            )
        check(list(out2) == e2, "mask wrong under serve.dispatch faults")
        det["fault_mask"] = mask_hash(out2)
        obs["dispatch_faults_fired"] = plan.fired().get("serve.dispatch", 0)

        # -- phase 3: admission squeeze — a sidecar whose lane budget is
        # full must REJECT with ST_BUSY (explicit admission control),
        # and the squeezed client must still produce exact masks
        adm = _serve_admission_squeeze(seed, clock, pool, rng)
        # the ST_BUSY replies land on the squeeze's own client, not the
        # outer provider — report the counter from where it counted
        obs["busy_rejects"] = adm.pop("busy_rejects")
        det["admission"] = adm

        # -- phase 4: kill mid-batch.  The async dispatch is in flight
        # when the server dies; the resolver must re-verify in-process.
        # A deterministic kill window: stall the sidecar's dispatch so
        # stop() ALWAYS lands before the worker can settle — without
        # the delay, a fast 48-lane verify could win the race on a
        # loaded box and reply a genuine ST_OK (degraded stays False
        # and the smoke's check() fails spuriously).
        k3, s3, d3, e3, _ = pool.lanes(rng, 48)
        plan4 = FaultPlan.parse("serve.dispatch=delay:1.0:ms=700", seed=seed)
        with plan_installed(plan4):
            resolver = provider.batch_verify_async(k3, s3, d3)
            server.stop()
        out3 = clock.timed("serve.kill_midbatch", resolver)
        check(list(out3) == e3, "mask wrong after sidecar kill mid-batch")
        check(provider.degraded, "kill did not degrade the provider")
        det["kill_mask"] = mask_hash(out3)
        det["degraded_after_kill"] = provider.degraded

        # -- phase 5: restart on the same address; a fresh client rides
        # the sidecar again (no lingering degrade in the new provider)
        server2 = SidecarServer(
            addr, engine="host", warm_ladder="off", buckets=(64, 256, 1024)
        )
        server2.warm()
        server2.start()
        provider2 = SidecarProvider(address=addr, sleeper=lambda s: None)
        k4, s4, d4, e4, _ = pool.lanes(rng, 64)
        out4 = clock.timed(
            "serve.after_restart", provider2.batch_verify, k4, s4, d4
        )
        check(list(out4) == e4, "mask wrong after sidecar restart")
        check(
            not provider2.degraded,
            "restarted sidecar did not serve the fresh client",
        )
        det["restart_mask"] = mask_hash(out4)
        det["served_after_restart"] = server2.stats.summary()["requests"] >= 1
    finally:
        provider.stop()
        if provider2 is not None:
            provider2.stop()
        server.stop()
        if server2 is not None:
            server2.stop()
        shutil.rmtree(os.path.dirname(addr), ignore_errors=True)
    return det, obs


def _serve_admission_squeeze(
    seed: int, clock: StageClock, pool: LanePool, rng: random.Random
) -> Dict:
    """Dedicated tiny-budget sidecar: stall the dispatcher behind a
    gated provider, fill the lane budget, and require the NEXT request
    to be rejected ST_BUSY — then release the gate and require every
    squeezed request's mask to be exact."""
    import os
    import shutil
    import tempfile

    from fabric_tpu.crypto.bccsp import SoftwareProvider
    from fabric_tpu.serve.client import SidecarProvider
    from fabric_tpu.serve.server import SidecarServer

    gate = threading.Event()
    entered = threading.Event()

    class GatedProvider(SoftwareProvider):
        """Computes eagerly, but holds the dispatcher until released —
        admitted-but-undispatched lanes pile up behind it."""

        def batch_verify_async(self, keys, sigs, digests):
            out = SoftwareProvider.batch_verify(self, keys, sigs, digests)
            entered.set()
            gate.wait(10.0)
            return lambda: out

    addr = os.path.join(tempfile.mkdtemp(prefix="fabchaos-busy-"), "b.sock")
    server = SidecarServer(
        addr,
        engine="host",
        provider=GatedProvider(),
        warm_ladder="off",
        buckets=(64,),
        max_pending_lanes=96,
        linger_s=0.0,
    )
    # no warm(): the gated provider would stall the warm batch
    server.start()
    first = SidecarProvider(address=addr, sleeper=lambda s: None)
    second = SidecarProvider(address=addr, sleeper=lambda s: None)
    third = SidecarProvider(address=addr, sleeper=lambda s: None)
    try:
        k1, s1, d1, e1, _ = pool.lanes(rng, 64)
        r1 = first.batch_verify_async(k1, s1, d1)
        check(entered.wait(5.0), "dispatcher never reached the gate")
        k2, s2, d2, e2, _ = pool.lanes(rng, 64)
        r2 = second.batch_verify_async(k2, s2, d2)
        deadline = time.monotonic() + 5.0
        while server.batcher.pending_lanes < 64 and time.monotonic() < deadline:
            time.sleep(0.01)
        check(
            server.batcher.pending_lanes >= 64,
            "second request never occupied the lane budget",
        )
        # budget: 96 total, 64 held by request 2 -> a 64-lane request
        # does not fit and must be REJECTED (not queued, not blocked)
        k3, s3, d3, e3, _ = pool.lanes(rng, 64)
        out3 = clock.timed("serve.busy_squeeze", third.batch_verify, k3, s3, d3)
        check(
            third.busy_rejects >= 1,
            "full sidecar never answered ST_BUSY (admission control dead)",
        )
        # the third client's retry budget (fake sleeper) expired against
        # a still-gated sidecar, so it degraded in-process: mask exact
        check(list(out3) == e3, "squeezed request mask != ground truth")
        gate.set()
        check(list(r1()) == e1, "gated request 1 mask != ground truth")
        check(list(r2()) == e2, "gated request 2 mask != ground truth")
        return {
            "busy_rejected": True,
            "squeezed_mask": mask_hash(out3),
            "gated_masks_exact": True,
            # observed count, popped into the obs section by the caller
            # (retry pacing makes the exact number timing-dependent)
            "busy_rejects": third.busy_rejects,
        }
    finally:
        gate.set()
        first.stop()
        second.stop()
        third.stop()
        server.stop()
        shutil.rmtree(os.path.dirname(addr), ignore_errors=True)


# ---------------------------------------------------------------------------
# qos_storm: per-class admission — spam cannot starve a paying channel
# ---------------------------------------------------------------------------


class _RearmableGatedProvider:
    """SoftwareProvider whose dispatcher stalls behind a re-armable
    gate: compute happens eagerly (masks stay exact), the resolver is
    withheld until release — pending-lane state becomes a deterministic
    construction instead of a timing race."""

    def __init__(self):
        from fabric_tpu.crypto.bccsp import SoftwareProvider

        self._sw = SoftwareProvider()
        self.gate = threading.Event()
        self.entered = threading.Event()

    def batch_verify(self, keys, sigs, digests):
        return self._sw.batch_verify(keys, sigs, digests)

    def batch_verify_async(self, keys, sigs, digests):
        out = self._sw.batch_verify(keys, sigs, digests)
        self.entered.set()
        self.gate.wait(20.0)
        return lambda: out

    def rearm(self):
        self.gate.clear()
        self.entered.clear()

    def release(self):
        self.gate.set()


@scenario("qos_storm")
def run_qos_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """Per-channel QoS admission under a 10:1 zipf spam skew: a bulk
    spam channel floods a shared sidecar past capacity while a paying
    high-priority channel submits.  Asserts (1) work-conserving
    borrowing — with the paying channel idle, spam may fill the WHOLE
    lane budget; (2) reservation protection — after one paying
    rejection, spam can no longer borrow the paying quota and the
    paying retry is admitted in full; (3) the paying channel's served
    fraction stays >= 0.9 under sustained overload; (4) every shed is a
    protocol-level ST_BUSY reply (observed per request — never a silent
    drop), cross-checked against the server's ledger counters; and (5)
    every served mask is bit-exact."""
    import os
    import shutil
    import tempfile

    from fabric_tpu.serve import protocol as sproto
    from fabric_tpu.serve.client import SidecarClient, encode_lanes
    from fabric_tpu.serve.server import SidecarServer

    rng = random.Random(seed * 1000003 + 13)
    pool = LanePool(rng)
    provider = _RearmableGatedProvider()
    addr = os.path.join(tempfile.mkdtemp(prefix="fabchaos-qos-"), "q.sock")
    # 128-lane budget, paying reserves half: quotas high=64/normal=32/bulk=32
    server = SidecarServer(
        addr, engine="host", provider=provider, warm_ladder="off",
        buckets=(64, 256), max_pending_lanes=128, linger_s=0.0,
        qos_shares={"high": 0.5, "normal": 0.25, "bulk": 0.25},
    )
    server.start()  # no warm(): the gate would stall the warm batch
    spam = SidecarClient(addr)
    paying = SidecarClient(addr)
    det: Dict[str, object] = {}
    obs: Dict[str, object] = {}

    spam_lanes = 16
    pay_lanes = 64
    spam_reqs = [pool.lanes(rng, spam_lanes) for _ in range(16)]
    pay_req = pool.lanes(rng, pay_lanes)

    def send_spam(i: int):
        k, s, d, _e, _ = spam_reqs[i]
        payload = encode_lanes(
            k, s, d, qos_class=sproto.QOS_BULK, channel="spamchan",
            version=spam.version,
        )
        return spam.submit(sproto.OP_VERIFY, payload)

    def send_paying():
        k, s, d, _e, _ = pay_req
        payload = encode_lanes(
            k, s, d, qos_class=sproto.QOS_HIGH, channel="paychan",
            version=paying.version,
        )
        return paying.submit(sproto.OP_VERIFY, payload)

    def outcome(client: SidecarClient, token: int) -> Tuple[str, Optional[List[bool]]]:
        status, retry_ms, mask, _msg = sproto.decode_verify_response(
            client.await_reply(token)
        )
        if status == sproto.ST_OK:
            return "ok", mask
        check(
            status == sproto.ST_BUSY,
            f"shed with status {status}, not a protocol ST_BUSY",
        )
        check(retry_ms >= 5, f"ST_BUSY without a retry_after hint ({retry_ms})")
        return "busy", None

    def settle_pending(tokens_expected) -> None:
        provider.release()
        for client, token, expected in tokens_expected:
            kind, mask = outcome(client, token)
            check(kind == "ok", "gated request did not settle OK")
            check(
                list(mask) == expected,
                f"mask wrong under QoS storm: got {mask_hash(mask)} "
                f"want {mask_hash(expected)}",
            )

    processed = [0]

    def wait_processed() -> None:
        """Serialize admission decisions: worker threads race to the
        ledger, so each submit waits for ITS decision to land before
        the next goes out — the outcome sequence becomes deterministic
        instead of thread-scheduling-dependent."""
        processed[0] += 1
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            snap = server.qos.snapshot()
            done = sum(
                snap[c]["admitted"] + snap[c]["rejected"] for c in snap
            )
            if done >= processed[0]:
                return
            time.sleep(0.002)
        raise ChaosAssertionError("admission pipeline stalled")

    try:
        # -- phase A: paying idle -> spam is work-conserving: 16-lane
        # spam requests fill the entire 128-lane budget (first request
        # dispatches and stalls at the gate; the next 8 occupy pending)
        t0 = time.perf_counter()
        pending: List = []
        tok0 = send_spam(0)
        wait_processed()
        check(provider.entered.wait(5.0), "dispatcher never reached the gate")
        pending.append((spam, tok0, spam_reqs[0][3]))
        phase_a: List[str] = []
        for i in range(1, 10):
            token = send_spam(i)
            wait_processed()
            # requests 1..8 fit the budget (8 * 16 = 128 pending lanes);
            # request 9 must shed: await only the one that can reject
            if i <= 8:
                pending.append((spam, token, spam_reqs[i][3]))
                phase_a.append("admitted")
            else:
                kind, _ = outcome(spam, token)
                phase_a.append(kind)
        check(
            phase_a == ["admitted"] * 8 + ["busy"],
            f"work-conserving admission broke: {phase_a}",
        )
        # paying arrives against a spam-full sidecar: exactly one
        # explicit ST_BUSY (the demand latch arms its reservation)
        pay_tok = send_paying()
        wait_processed()
        pay_kind, _ = outcome(paying, pay_tok)
        check(pay_kind == "busy", "paying request against full budget "
              "must shed explicitly (got served?)")
        settle_pending(pending)
        clock.record("qos.phase_a", time.perf_counter() - t0)

        # -- phase B: the paying reservation is now protected — spam may
        # refill only up to total - high_quota, the paying retry admits
        # in full, and the mask is exact
        t0 = time.perf_counter()
        provider.rearm()
        pending = []
        tok_b0 = send_spam(10)
        wait_processed()
        check(provider.entered.wait(5.0), "dispatcher never re-entered the gate")
        pending.append((spam, tok_b0, spam_reqs[10][3]))
        phase_b: List[str] = []
        for i in range(11, 16):
            token = send_spam(i)
            wait_processed()
            # 4 * 16 = 64 pending spam lanes fit beside the 64-lane
            # paying reservation; the 5th spam request must shed
            if i <= 14:
                pending.append((spam, token, spam_reqs[i][3]))
                phase_b.append("admitted")
            else:
                kind, _ = outcome(spam, token)
                phase_b.append(kind)
        check(
            phase_b == ["admitted"] * 4 + ["busy"],
            f"paying reservation not protected from borrowing: {phase_b}",
        )
        pay_tok2 = send_paying()
        wait_processed()
        pending.append((paying, pay_tok2, pay_req[3]))
        settle_pending(pending)
        clock.record("qos.phase_b", time.perf_counter() - t0)

        # -- accounting: served fractions + no silent drops.  The
        # paying channel was shed once and served once -> fraction 0.5
        # per ATTEMPT, 1.0 per request after one bounded retry; the
        # acceptance bound is on requests ultimately served.
        qos_snap = server.qos.snapshot()
        stats = server.stats.summary()
        check(
            qos_snap["high"]["admitted"] == 1
            and qos_snap["high"]["rejected"] == 1,
            f"paying ledger counts wrong: {qos_snap['high']}",
        )
        check(
            qos_snap["bulk"]["admitted"] == 14
            and qos_snap["bulk"]["rejected"] == 2,
            f"spam ledger counts wrong: {qos_snap['bulk']}",
        )
        # every ledger rejection was observed by a client as ST_BUSY
        observed_busy = 3  # phase_a spam + paying + phase_b spam
        ledger_rejected = sum(
            qos_snap[c]["rejected"] for c in ("high", "normal", "bulk")
        )
        check(
            ledger_rejected == observed_busy
            and stats["rejects"] == observed_busy,
            f"sheds not all protocol-visible: ledger {ledger_rejected}, "
            f"stats {stats['rejects']}, observed {observed_busy}",
        )
        served_fraction_paying = 1.0  # 1 request, served after 1 retry
        check(served_fraction_paying >= 0.9, "paying served fraction < 0.9")
        det.update(
            {
                "budget_lanes": 128,
                "quotas": {
                    c: qos_snap[c]["quota"] for c in ("high", "normal", "bulk")
                },
                "spam_skew": "10:1",
                "phase_a": phase_a,
                "paying_first_outcome": "busy",
                "phase_b": phase_b,
                "paying_retry_outcome": "ok",
                "paying_served_fraction": served_fraction_paying,
                "spam_admitted": qos_snap["bulk"]["admitted"],
                "spam_rejected": qos_snap["bulk"]["rejected"],
                "all_sheds_protocol_busy": True,
                "paying_mask": mask_hash(pay_req[3]),
            }
        )
        obs["per_class"] = stats["per_class"]
    finally:
        provider.release()
        spam.close()
        paying.close()
        server.stop()
        shutil.rmtree(os.path.dirname(addr), ignore_errors=True)
    return det, obs


# ---------------------------------------------------------------------------
# router_flap: multi-sidecar failover + rolling restart under load
# ---------------------------------------------------------------------------


@scenario("router_flap")
def run_router_flap(seed: int, clock: StageClock, scale: float = 1.0):
    """The fleet serving plane under endpoint churn: three sidecars
    behind a SidecarRouter, then (1) mixed batches spread across the
    fleet — every mask bit-exact; (2) the preferred endpoint for an
    in-flight batch is KILLED mid-dispatch (a delay fault pins the
    race) — the router re-verifies on another endpoint, mask exact,
    never degrading to in-process while peers are healthy; (3) a
    ROLLING RESTART of every sidecar (OP_DRAIN -> stop -> fresh server
    on the same address) under a sustained batch stream — every mask
    bit-exact through the whole roll (byte-identical to what a
    no-fault run computes: the ground truth), and every endpoint is
    healthy again at the end."""
    import os
    import shutil
    import tempfile

    from fabric_tpu.common.retry import RetryPolicy as _RP
    from fabric_tpu.serve.router import SidecarRouter
    from fabric_tpu.serve.server import SidecarServer

    rng = random.Random(seed * 1000003 + 14)
    pool = LanePool(rng)
    base = tempfile.mkdtemp(prefix="fabchaos-router-")
    addrs = [os.path.join(base, f"s{i}.sock") for i in range(3)]

    def start_server(addr: str) -> SidecarServer:
        srv = SidecarServer(
            addr, engine="host", warm_ladder="off", buckets=(64, 256, 1024)
        )
        srv.warm()
        srv.start()
        return srv

    servers = {addr: start_server(addr) for addr in addrs}
    # fast eviction ramp so the rolling restart finishes inside the
    # smoke budget; recovery correctness is gate-policy-independent
    router = SidecarRouter(
        endpoints=addrs,
        sleeper=lambda s: None,
        gate_policy=_RP(base_s=0.05, multiplier=2.0, cap_s=0.5,
                        deadline_s=float("inf")),
    )
    det: Dict[str, object] = {}
    obs: Dict[str, object] = {}
    try:
        # -- phase 1: clean spread across the fleet
        t0 = time.perf_counter()
        sizes = [48, 200, 800, 64, 300]
        masks_ok = 0
        for i, n in enumerate(sizes):
            k, s, d, e, _ = pool.lanes(rng, n)
            out = router.batch_verify(k, s, d)
            check(
                list(out) == e,
                f"router batch {i} mask wrong: got {mask_hash(out)} "
                f"want {mask_hash(e)}",
            )
            masks_ok += 1
        check(not router.degraded, "healthy fleet degraded the router")
        clock.record("router.clean", time.perf_counter() - t0)
        det["clean_batches"] = masks_ok
        served_counts = [
            servers[a].stats.summary()["requests"] for a in addrs
        ]
        check(
            sum(served_counts) >= len(sizes),
            f"fleet served {sum(served_counts)} < {len(sizes)} batches",
        )
        obs["clean_served_per_endpoint"] = served_counts

        # -- phase 2: kill the preferred endpoint mid-batch; the
        # in-flight async dispatch must re-verify on a healthy peer
        k2, s2, d2, e2, _ = pool.lanes(rng, 48)
        preferred = router._order(48)[0]
        victim = servers[preferred.address]
        plan = FaultPlan.parse("serve.dispatch=delay:1.0:ms=500", seed=seed)
        with plan_installed(plan):
            resolver = router.batch_verify_async(k2, s2, d2)
            victim.stop()
            out2 = clock.timed("router.kill_midbatch", resolver)
        check(list(out2) == e2, "mask wrong after endpoint kill mid-batch")
        check(
            not router.degraded,
            "router degraded in-process with healthy endpoints remaining",
        )
        det["kill_midbatch_mask_ok"] = True
        det["kill_midbatch_mask"] = mask_hash(out2)

        def wait_back_in_rotation(addr: str) -> None:
            """The rolling-restart runbook discipline: an instance must
            be probed healthy again BEFORE the next one is rolled —
            without it, cooldown windows can overlap into a
            full-fleet blackout and the roll degrades to in-process."""
            target = next(
                e for e in router.endpoints if e.address == addr
            )
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if target.gate.ready() and router._probe_ok(target):  # fablife: disable=pair-imbalance  # scenario OBSERVES the router's gate state; the verdict is recorded by the router's own mark_up/mark_down inside _probe_ok's health path
                    return
                time.sleep(0.02)
            raise ChaosAssertionError(
                "restarted endpoint never re-entered rotation"
            )

        # restart the victim for the rolling phase
        servers[preferred.address] = start_server(preferred.address)
        wait_back_in_rotation(preferred.address)

        # -- phase 3: rolling restart of EVERY sidecar under load
        t0 = time.perf_counter()
        roll_masks_ok = 0
        drains_acked = 0
        for addr in addrs:
            drains_acked += 1 if router.drain_endpoint(addr) else 0
            servers[addr].stop()
            # traffic keeps flowing while the endpoint is down
            for n in (64, 256):
                k3, s3, d3, e3, _ = pool.lanes(rng, n)
                out3 = router.batch_verify(k3, s3, d3)
                check(
                    list(out3) == e3,
                    f"mask wrong during rolling restart of {addr}",
                )
                roll_masks_ok += 1
            servers[addr] = start_server(addr)
            wait_back_in_rotation(addr)
        check(
            not router.degraded,
            "rolling restart degraded the router to in-process",
        )
        check(
            all(e.healthy for e in router.endpoints),
            "an endpoint never recovered after its rolling restart",
        )
        # and the recovered fleet serves again
        k4, s4, d4, e4, _ = pool.lanes(rng, 128)
        out4 = router.batch_verify(k4, s4, d4)
        check(list(out4) == e4, "mask wrong after the roll completed")
        clock.record("router.rolling_restart", time.perf_counter() - t0)
        det.update(
            {
                "endpoints": len(addrs),
                "rolling_restart_batches_ok": roll_masks_ok,
                "drains_acked": drains_acked,
                "all_endpoints_recovered": True,
                "post_roll_mask": mask_hash(out4),
                "router_degraded": router.degraded,
            }
        )
    finally:
        router.stop()
        for srv in servers.values():
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        shutil.rmtree(base, ignore_errors=True)
    return det, obs


# ---------------------------------------------------------------------------
# fabtail: gray_failure / hedge_storm / deadline_storm
# ---------------------------------------------------------------------------


def _start_tail_server(addr: str, chaos_key: int, **kw):
    from fabric_tpu.serve.server import SidecarServer

    srv = SidecarServer(
        addr, engine="host", warm_ladder="off", buckets=(64, 256),
        chaos_key=chaos_key, **kw,
    )
    srv.warm()
    srv.start()
    return srv


@scenario("gray_failure")
def run_gray_failure(seed: int, clock: StageClock, scale: float = 1.0):
    """The third production failure mode (after death and overload): a
    sidecar that is alive, answers PING, and is dead slow.  Two
    sidecars behind a hedging router; the batch's PREFERRED endpoint is
    delay-faulted at ``serve.dispatch`` (pinned to that one server via
    its chaos key).  Asserts: (1) every mask stays bit-exact vs the
    by-construction ground truth (the same-seed no-fault expectation);
    (2) hedges fire and win — time-to-verdict for every faulted batch
    stays BELOW the injected delay, i.e. the tail is bounded by the
    hedge, not the gray sidecar; (3) after a short streak of lost
    hedges the gray endpoint is EVICTED through the same cooldown
    ladder as a dead one; (4) with the fault lifted it earns traffic
    back through a probe — recovery, same ladder as death."""
    import os
    import shutil
    import tempfile

    from fabric_tpu.common.retry import RetryPolicy as _RP
    from fabric_tpu.serve.router import SidecarRouter

    rng = random.Random(seed * 1000003 + 15)
    pool = LanePool(rng)
    base = tempfile.mkdtemp(prefix="fabchaos-gray-")
    addrs = [os.path.join(base, f"g{i}.sock") for i in range(2)]
    servers = {
        addr: _start_tail_server(addr, chaos_key=i + 1)
        for i, addr in enumerate(addrs)
    }
    delay_ms = 1200
    n_lanes = 32
    router = SidecarRouter(
        endpoints=addrs,
        sleeper=lambda s: None,
        # short recovery gate so the earn-back leg fits the smoke
        gate_policy=_RP(base_s=1.0, multiplier=2.0, cap_s=1.0,
                        deadline_s=float("inf")),
        hedge_fraction=1.0,  # the BUDGET bound is hedge_storm's proof
        # hedging disarmed for the warm phase (a cold first batch on a
        # loaded box can outlast the pre-sample delay and flap the det
        # counts); armed with a tiny floor before the fault phase
        hedge_min_ms=10_000.0,
    )
    det: Dict[str, object] = {}
    obs: Dict[str, object] = {}
    all_masks: List[bool] = []
    try:
        # -- phase 1: healthy warm-up — the preferred endpoint's
        # latency tracker learns its real quantiles (the hedge delay is
        # derived from OBSERVED latency, never a static knob)
        t0 = time.perf_counter()
        warm_batches = 4
        for _ in range(warm_batches):
            k, s, d, e, _ = pool.lanes(rng, n_lanes)
            out = router.batch_verify(k, s, d)
            check(list(out) == e, "mask wrong during healthy warm-up")
            all_masks.extend(out)
        check(router.hedges == 0, "healthy fleet hedged")
        clock.record("gray.warm", time.perf_counter() - t0)

        # the batch size pins the preferred endpoint; THAT one goes gray
        router.hedge_min_s = 0.015  # arm hedging, floor 15ms
        victim = router._order(n_lanes)[0]
        gray = servers[victim.address]
        plan = FaultPlan.parse(
            f"serve.dispatch=delay:1.0:ms={delay_ms}:at={gray.chaos_key}",
            seed=seed,
        )
        faulted_batches = 4
        faulted_walls: List[float] = []
        with plan_installed(plan):
            for _ in range(faulted_batches):
                k, s, d, e, _ = pool.lanes(rng, n_lanes)
                t1 = time.perf_counter()
                out = router.batch_verify(k, s, d)
                wall = time.perf_counter() - t1
                faulted_walls.append(wall)
                clock.record("gray.faulted_verdict", wall)
                check(
                    list(out) == e,
                    f"mask wrong under gray failure: got {mask_hash(out)} "
                    f"want {mask_hash(e)}",
                )
                all_masks.extend(out)
        # hedges: the first two faulted batches route to the gray
        # preferred endpoint, go silent past the learned delay, hedge,
        # and the hedge WINS (the gray reply is 1.2s out); two straight
        # lost hedges evict the gray endpoint, so the last two batches
        # route direct — token accounting is count-based, so these are
        # exact, not racy
        check(router.hedges == 2, f"expected 2 hedges, got {router.hedges}")
        check(
            router.hedge_wins == 2,
            f"expected 2 hedge wins, got {router.hedge_wins}",
        )
        check(
            router.slow_evictions == 1,
            f"expected 1 gray eviction, got {router.slow_evictions}",
        )
        check(not victim.healthy, "gray endpoint still in rotation")
        check(
            not router.degraded,
            "router degraded in-process with a healthy endpoint up",
        )
        # the tail is bounded by the HEDGE, not the gray sidecar: every
        # faulted verdict landed before the injected delay alone would
        # have let the gray endpoint answer
        tail_bounded = all(w < delay_ms / 1000.0 for w in faulted_walls)
        check(
            tail_bounded,
            "a faulted batch waited out the gray sidecar instead of "
            "hedging/failing over",
        )

        # -- phase 3: fault lifted — the evicted endpoint earns traffic
        # back through the probe ladder, exactly like a restart
        deadline = time.monotonic() + 10.0
        recovered = False
        while time.monotonic() < deadline:
            if victim.gate.ready() and router._probe_ok(victim):  # fablife: disable=pair-imbalance  # scenario OBSERVES the router's gate state; the verdict is recorded by the router's own mark_up/mark_down inside _probe_ok's health path
                recovered = True
                break
            time.sleep(0.05)
        check(recovered, "gray endpoint never earned its way back")
        k, s, d, e, _ = pool.lanes(rng, n_lanes)
        out = router.batch_verify(k, s, d)
        check(list(out) == e, "mask wrong after gray recovery")
        all_masks.extend(out)
        det.update(  # fabdet: disable=wallclock-in-det  # tail_bounded/recovered are check()-dominated: any run reaching this sink records the constant True — a timing excursion CRASHES the scenario instead of flapping the scorecard bytes
            {
                "endpoints": 2,
                "delay_ms": delay_ms,
                "warm_batches": warm_batches,
                "faulted_batches": faulted_batches,
                "hedges": router.hedges,
                "hedge_wins": router.hedge_wins,
                "slow_evictions": router.slow_evictions,
                "gray_evicted": True,
                "tail_bounded": tail_bounded,
                "recovered": recovered,
                "router_degraded": router.degraded,
                "masks_sha": mask_hash(all_masks),
            }
        )
        obs["faulted_walls_ms"] = [round(w * 1e3, 1) for w in faulted_walls]
        obs["victim_stats"] = gray.stats.summary()
    finally:
        router.stop()
        for srv in servers.values():
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        shutil.rmtree(base, ignore_errors=True)
    return det, obs


@scenario("hedge_storm")
def run_hedge_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """Fleet-wide load with hedging armed and EVERY sidecar slow: the
    pathological regime where naive hedging amplifies an overloaded
    fleet into collapse.  Four driver threads push batches through one
    hedging router over two uniformly delay-faulted sidecars.  Asserts:
    (1) hedge-issued extra requests stay under the configured token-
    bucket budget (burst + fraction * primaries — the count-based bound
    holds by construction and is cross-checked against the router's
    protocol-level counters); (2) the QoS ledger's lane accounting
    balances to zero leaked / double-released lanes on every server
    once traffic quiesces (hedged + cancelled lanes included); (3) no
    admission collapse: every batch is served with a bit-exact mask,
    none degrade to in-process."""
    import os
    import shutil
    import tempfile

    from fabric_tpu.serve.router import SidecarRouter

    rng = random.Random(seed * 1000003 + 16)
    pool = LanePool(rng)
    base = tempfile.mkdtemp(prefix="fabchaos-hedge-")
    addrs = [os.path.join(base, f"h{i}.sock") for i in range(2)]
    servers = {
        addr: _start_tail_server(addr, chaos_key=i + 1,
                                 max_pending_lanes=64)
        for i, addr in enumerate(addrs)
    }
    hedge_fraction = 0.1
    n_threads, per_thread, n_lanes = 4, 5, 16
    router = SidecarRouter(
        endpoints=addrs,
        hedge_fraction=hedge_fraction,
        hedge_min_ms=5.0,
    )
    det: Dict[str, object] = {}
    obs: Dict[str, object] = {}
    # per-thread deterministic workloads, generated before threading
    work = [
        [pool.lanes(random.Random(seed * 4049 + t * 97 + i), n_lanes)
         for i in range(per_thread)]
        for t in range(n_threads)
    ]
    results: List[List[Optional[List[bool]]]] = [
        [None] * per_thread for _ in range(n_threads)
    ]
    errors: List[str] = []
    err_lock = threading.Lock()

    def drive(t: int) -> None:
        for i, (k, s, d, e, _kinds) in enumerate(work[t]):
            out = clock.timed("hedge.verdict", router.batch_verify, k, s, d)
            results[t][i] = list(out)
            if list(out) != e:
                with err_lock:
                    errors.append(f"thread {t} batch {i} mask mismatch")

    plan = FaultPlan.parse("serve.dispatch=delay:1.0:ms=60", seed=seed)
    try:
        with plan_installed(plan):
            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=drive, args=(t,))
                for t in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            clock.record("hedge.storm_wall", time.perf_counter() - t0)
        check(not errors, "; ".join(sorted(errors)[:3]))
        check(
            all(r is not None for row in results for r in row),
            "a driver thread never finished",
        )
        n_primary = router.hedge_budget.earned
        budget_cap = router.hedge_budget.burst + hedge_fraction * n_primary
        check(
            router.hedges <= budget_cap,
            f"hedges {router.hedges} exceed budget cap {budget_cap}",
        )
        check(
            not router.degraded,
            "admission collapse: the fleet degraded to in-process",
        )
        # quiesce, then the ledger lane-flow balance must be exact on
        # every server: acquired == released, zero in flight, zero
        # leaked — hedged and cancelled lanes included (a double
        # release would drive `leaked` negative, a leak positive)
        balanced = True
        quiesce_deadline = time.monotonic() + 10.0
        for srv in servers.values():
            while time.monotonic() < quiesce_deadline:
                if srv.qos.balance()["in_flight"] == 0:
                    break
                time.sleep(0.02)
            bal = srv.qos.balance()
            if bal["in_flight"] != 0 or bal["leaked"] != 0:
                balanced = False
        check(balanced, "QoS ledger lane accounting did not balance")
        # protocol-level cross-check: every served request the ledger
        # admitted is visible in the servers' stats (no silent lanes)
        ledger_admitted = sum(
            sum(srv.qos.admitted) for srv in servers.values()
        )
        stats_requests = sum(
            srv.stats.summary()["requests"]
            + srv.stats.summary()["cancelled_post"]
            for srv in servers.values()
        )
        check(
            ledger_admitted == stats_requests,
            f"ledger admitted {ledger_admitted} != protocol-visible "
            f"{stats_requests}",
        )
        masks_flat: List[bool] = []
        for row in results:
            for r in row:
                masks_flat.extend(r or [])
        det.update(
            {
                "endpoints": 2,
                "threads": n_threads,
                "batches": n_threads * per_thread,
                "mask_mismatches": 0,
                "hedges_within_budget": True,
                "budget_fraction": hedge_fraction,
                "ledger_balanced": True,
                "ledger_matches_protocol": True,
                "no_admission_collapse": True,
                "masks_sha": mask_hash(masks_flat),
            }
        )
        obs["hedges"] = router.hedges
        obs["hedge_wins"] = router.hedge_wins
        obs["primaries"] = n_primary
        obs["busy_rejects"] = router.busy_rejects
        obs["per_server"] = [
            {
                "stats": srv.stats.summary(),
                "qos_balance": srv.qos.balance(),
            }
            for srv in servers.values()
        ]
    finally:
        router.stop()
        for srv in servers.values():
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        shutil.rmtree(base, ignore_errors=True)
    return det, obs


@scenario("deadline_storm")
def run_deadline_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """Aggressive wire budgets against a dead-slow sidecar: (1) the
    SERVER sheds work it provably cannot finish (budget below its
    best-ever service time for the bucket) as an explicit ST_BUSY —
    never a silent drop, never a fabricated verdict; (2) a CLIENT whose
    budget expires hands the batch to the in-process ladder and the
    mask is bit-exact (degrade, not guess); (3) only a DOUBLE fault
    (expired budget AND broken fallback) produces all-False."""
    import os
    import shutil
    import tempfile

    from fabric_tpu.serve import protocol as sproto
    from fabric_tpu.serve.client import SidecarClient, SidecarProvider, encode_lanes

    rng = random.Random(seed * 1000003 + 17)
    pool = LanePool(rng)
    base = tempfile.mkdtemp(prefix="fabchaos-deadline-")
    addr = os.path.join(base, "d.sock")
    server = _start_tail_server(addr, chaos_key=1)
    det: Dict[str, object] = {}
    obs: Dict[str, object] = {}
    all_masks: List[bool] = []
    try:
        # -- leg 1 (no faults): the server learns its per-bucket floor,
        # then sheds a 1ms-budget request as an explicit ST_BUSY
        raw = SidecarClient(addr)
        k, s, d, e, _ = pool.lanes(rng, 64)
        status, _, mask, _ = sproto.decode_verify_response(
            raw.request(
                sproto.OP_VERIFY, encode_lanes(k, s, d, version=raw.version)
            )
        )
        check(status == sproto.ST_OK and list(mask) == e,
              "floor-establishing request failed")
        all_masks.extend(mask)
        status2, retry_ms, mask2, _ = sproto.decode_verify_response(
            raw.request(
                sproto.OP_VERIFY,
                encode_lanes(k, s, d, deadline_ms=1, version=raw.version),
            )
        )
        check(
            status2 == sproto.ST_BUSY and mask2 is None,
            f"provably-unfinishable budget answered status {status2}, "
            "not an explicit ST_BUSY",
        )
        check(retry_ms >= 5, "deadline shed without a retry_after hint")
        check(
            server.stats.deadline_shed == 1,
            f"deadline_shed counted {server.stats.deadline_shed}, not 1",
        )
        raw.close()

        # -- leg 2: delay-faulted sidecar + 40ms client budgets — every
        # batch expires, degrades to the in-process ladder, and the
        # mask is STILL bit-exact (an expired budget buys an earlier
        # failover, never a fabricated verdict)
        n_batches = 3
        plan = FaultPlan.parse("serve.dispatch=delay:1.0:ms=600", seed=seed)
        with plan_installed(plan):
            provider = SidecarProvider(address=addr, deadline_ms=40)
            t0 = time.perf_counter()
            for _ in range(n_batches):
                k, s, d, e, _ = pool.lanes(rng, 24)
                out = clock.timed(
                    "deadline.expired_verdict", provider.batch_verify,
                    k, s, d,
                )
                check(
                    list(out) == e,
                    "mask wrong after deadline degrade: got "
                    f"{mask_hash(out)} want {mask_hash(e)}",
                )
                all_masks.extend(out)
            wall = time.perf_counter() - t0
            check(
                provider.deadline_expired == n_batches,
                f"{provider.deadline_expired} budgets expired, "
                f"expected {n_batches}",
            )
            check(provider.degraded, "expired budgets never degraded")
            # the whole leg must complete far below the injected delay
            # times the batch count: budgets bound time-to-verdict
            check(
                wall < n_batches * 0.6,
                "deadline leg waited out the slow sidecar",
            )
            provider.stop()

            # -- leg 3: expired budget AND broken fallback: the ONLY
            # path to all-False (fail closed, never fabricated VALID)
            class _Exploding:
                def batch_verify(self, keys, sigs, digests):
                    raise RuntimeError("fallback broken too")

            double = SidecarProvider(
                address=addr, deadline_ms=40, fallback=_Exploding()
            )
            k, s, d, e, _ = pool.lanes(rng, 16)
            out = double.batch_verify(k, s, d)
            check(
                list(out) == [False] * len(k),
                "double fault did not fail closed all-False",
            )
            double.stop()
        det.update(
            {
                "floor_request_lanes": 64,
                "server_shed_status": "busy",
                "server_deadline_shed": server.stats.deadline_shed,
                "client_budget_ms": 40,
                "expired_batches": n_batches,
                "deadline_expired": n_batches,
                "masks_exact": True,
                "all_false_on_double_fault": True,
                "masks_sha": mask_hash(all_masks),
            }
        )
        obs["server_stats"] = server.stats.summary()
    finally:
        server.stop()
        shutil.rmtree(base, ignore_errors=True)
    return det, obs


# ---------------------------------------------------------------------------
# gossip_storm: block dissemination over a lossy gossip plane
# ---------------------------------------------------------------------------


@scenario("gossip_storm")
def run_gossip_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """The ROADMAP gossip-plane scenario: a leader pushes a block chain
    to a follower over real sockets while the ``gossip.comm.send`` drop
    site loses a seeded fraction of sends.  Membership re-broadcast +
    anti-entropy must recover every dropped block IN ORDER, and the
    follower's per-block verify masks (its commit path verifies each
    block's lanes through the real SW provider) must equal ground truth
    bit-exactly — lossy gossip may delay a block, never corrupt its
    mask or skip it (fail-closed ordering)."""
    from fabric_tpu.crypto.bccsp import SoftwareProvider
    from fabric_tpu.gossip.comm import GossipNode
    from fabric_tpu.gossip.state import StateProvider
    from fabric_tpu.protos import protoutil

    rng = random.Random(seed * 1000003 + 12)
    pool = LanePool(rng)
    n_blocks = max(6, int(8 * scale))
    # per-block deterministic lane workloads + ground-truth masks
    lanes_by_block = []
    for i in range(n_blocks):
        brng = random.Random(seed * 7919 + i)
        lanes_by_block.append(pool.lanes(brng, 12))
    provider = SoftwareProvider()

    class VerifyingLedger:
        """Commit = verify the block's lanes + append; the follower's
        masks are the scenario's ground-truth comparison."""

        def __init__(self, verify: bool):
            self.blocks: List = []
            self.masks: Dict[int, List[bool]] = {}
            self.verify = verify
            self._lock = threading.Lock()

        def commit(self, block) -> None:
            with self._lock:
                n = block.header.number
                check(
                    n == len(self.blocks),
                    f"out-of-order commit: block {n} at height {len(self.blocks)}",
                )
                if self.verify:
                    keys, sigs, digests, _, _ = lanes_by_block[n]
                    self.masks[n] = list(
                        provider.batch_verify(keys, sigs, digests)
                    )
                self.blocks.append(block)

        def get_block(self, n: int):
            with self._lock:
                return self.blocks[n] if n < len(self.blocks) else None

        @property
        def height(self) -> int:
            with self._lock:
                return len(self.blocks)

    leader_ledger = VerifyingLedger(verify=False)
    follower_ledger = VerifyingLedger(verify=True)

    def make_node(name: str, ledger: VerifyingLedger) -> GossipNode:
        state = StateProvider("chaoschan", ledger.commit, lambda: ledger.height)
        return GossipNode(
            name,
            "chaoschan",
            state,
            ledger.get_block,
            lambda: ledger.height,
            tick_interval=0.1,
        )

    blocks = []
    prev = b""
    for i in range(n_blocks):
        b = protoutil.new_block(i, prev)
        b.data.data.append(b"chaos tx %d" % i)
        protoutil.seal_block(b)
        prev = protoutil.block_header_hash(b.header)
        blocks.append(b)

    # drop 40% of stream opens, keyed per (endpoint, seq): a lossy link,
    # not a partition — ticks re-broadcast and anti-entropy back-fills
    plan = FaultPlan.parse("gossip.comm.send=drop:0.4", seed=seed)
    leader = make_node("leader", leader_ledger)
    follower = make_node("follower", follower_ledger)
    t0 = time.perf_counter()
    with plan_installed(plan):
        leader.start()
        follower.start()
        try:
            follower.connect(leader.addr)
            for b in blocks:
                leader_ledger.commit(b)
                leader.broadcast_block(b)
            deadline = time.monotonic() + 30.0
            while (
                follower_ledger.height < n_blocks
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
        finally:
            leader.stop()
            follower.stop()
    clock.record("gossip.converge", time.perf_counter() - t0)
    check(
        follower_ledger.height == n_blocks,
        f"follower converged to {follower_ledger.height}/{n_blocks} "
        "blocks despite anti-entropy",
    )
    mask_hashes = []
    for i in range(n_blocks):
        _, _, _, expected, _ = lanes_by_block[i]
        got = follower_ledger.masks.get(i)
        check(got == expected, f"block {i} mask != ground truth under drops")
        mask_hashes.append(mask_hash(expected))
    det = {
        "blocks": n_blocks,
        "converged": True,
        "mask_hashes": mask_hashes,
        "lanes_per_block": 12,
    }
    return det, {"drops_fired": plan.fired().get("gossip.comm.send", 0)}


# ---------------------------------------------------------------------------
# raft_churn: leader kill + message loss on the real raft consenter
# ---------------------------------------------------------------------------


class _RaftWorld:
    """Deterministic in-memory raft cluster over the REAL RaftChain
    objects (WAL + blockwriter + blockcutter included): single-threaded
    tick/deliver pump, explicit message queues, kill = the node's
    queued AND future messages vanish (a killed process never flushes
    its socket buffers)."""

    def __init__(self, wal_root: str, ids=(1, 2, 3)):
        from fabric_tpu.orderer.blockcutter import BatchConfig
        from fabric_tpu.orderer.raft_chain import RaftChain

        self.ids = tuple(ids)
        self.dead: set = set()
        self.queues: Dict[int, List] = {i: [] for i in ids}
        self.chains = {}
        for i in ids:
            self.chains[i] = RaftChain(
                "churn",
                i,
                ids,
                wal_dir=f"{wal_root}/node{i}",
                batch_config=BatchConfig(max_message_count=1),
                snapshot_interval=0,
                transport=self._transport(i),
            )

    def _transport(self, frm: int):
        def send(to: int, msg) -> None:
            if frm in self.dead or to in self.dead:
                return
            if to in self.queues:
                self.queues[to].append(msg)

        return send

    def kill(self, node_id: int) -> None:
        self.dead.add(node_id)
        # a killed node's unflushed packets never arrive, and packets
        # addressed to it are dropped by every peer's dead transport
        for q in self.queues.values():
            q[:] = [m for m in q if m.frm != node_id]
        self.queues[node_id].clear()

    def deliver(self, rounds: int = 30) -> None:
        for _ in range(rounds):
            moved = False
            for i in self.ids:
                q, self.queues[i] = self.queues[i], []
                for m in q:
                    if i in self.dead or m.frm in self.dead:
                        continue
                    self.chains[i].step(m)
                    moved = True
            if not moved:
                return

    def run(self, ticks: int) -> None:
        for _ in range(ticks):
            for i in self.ids:
                if i not in self.dead:
                    self.chains[i].tick()
            self.deliver()

    @property
    def leader(self):
        for i in self.ids:
            if i in self.dead:
                continue
            if self.chains[i].node.role == "leader":
                return self.chains[i]
        return None

    def live_chains(self):
        return [self.chains[i] for i in self.ids if i not in self.dead]


def _drive_raft_sequence(
    world: _RaftWorld, payloads: List[bytes], kill_at: Optional[int]
) -> List[Tuple[int, str]]:
    """Order every payload (one block each: max_message_count=1),
    killing the leader right after proposal ``kill_at`` is submitted —
    mid-stream, before delivery, so the entry is lost with the leader
    and MUST be resubmitted through the failover.  Returns the
    committed chain as (number, header_hash_hex) from a survivor."""
    for k, payload in enumerate(payloads):
        env = common_pb2.Envelope()
        env.payload = payload
        guard = 0
        while True:
            guard += 1
            check(guard < 100, f"raft churn livelocked ordering block {k}")
            world.run(10)
            leader = world.leader
            if leader is None:
                continue
            try:
                leader.order(env)
            except Exception:  # deposed between checks: re-elect
                continue
            if kill_at is not None and k == kill_at:
                # kill mid-stream: the proposal sits in the dead
                # leader's outbox/queues and vanishes with it
                world.kill(leader.node.id)
                kill_at = None
            # wait for the commit; a lost leader breaks out instead
            waited = 0
            committed = False
            while True:
                live = world.live_chains()
                if all(ch.height >= k + 1 for ch in live):
                    committed = True
                    break
                if (
                    leader.node.id in world.dead
                    or world.leader is not leader
                ):
                    break  # leader lost: decide below whether to resubmit
                waited += 1
                check(
                    waited < 100,
                    f"entry for block {k} never committed under a live "
                    "leader (raft retransmission broken)",
                )
                world.run(5)
            if committed:
                break
            # leader lost: settle the election, then re-check — the
            # entry may have replicated before the loss and commit via
            # the NEW leader (resubmitting then would duplicate it)
            world.run(60)
            if all(ch.height >= k + 1 for ch in world.live_chains()):
                break
            # entry truly lost with the old leader: resubmit (loop)
    survivor = world.live_chains()[0]
    chain: List[Tuple[int, str]] = []
    for num in range(survivor.height):
        block = survivor.get_block(num)
        chain.append(
            (num, protoutil.block_header_hash(block.header).hex())
        )
    return chain


@scenario("raft_churn")
def run_raft_churn(seed: int, clock: StageClock, scale: float = 1.0):
    """Control-plane chaos on the REAL raft consenter: a 3-orderer
    cluster orders a stream of envelopes while (1) the LEADER is killed
    mid-stream — its in-flight proposal vanishes with it — and (2) a
    seeded fraction of consensus messages is dropped at the
    ``raft.step`` seam.  Deliver failover (resubmission through the new
    leader, stale-proposal dedup by block number) must yield a
    committed chain BYTE-IDENTICAL to the no-fault run: same heights,
    same header hashes, on every survivor."""
    import shutil
    import tempfile

    rng = random.Random(seed * 1000003 + 15)
    n_blocks = max(4, int(6 * scale))
    payloads = [b"churn tx %d %d" % (seed, i) for i in range(n_blocks)]
    kill_at = 1 + rng.randrange(max(1, n_blocks - 2))

    root = tempfile.mkdtemp(prefix="fabchaos-raft-")
    try:
        # -- baseline: same payloads, no faults, no kill
        t0 = time.perf_counter()
        baseline_world = _RaftWorld(f"{root}/baseline")
        baseline = _drive_raft_sequence(baseline_world, payloads, None)
        clock.record("raft.baseline", time.perf_counter() - t0)
        check(
            len(baseline) == n_blocks,
            f"baseline committed {len(baseline)}/{n_blocks} blocks",
        )

        # -- churn: leader kill mid-stream + raft.step message drops.
        # The drop site is unkeyed (per-site seeded stream): raft
        # retransmits the SAME append on every heartbeat, so the drop
        # decision must re-roll per delivery or a lost message would
        # stay lost forever.
        t0 = time.perf_counter()
        plan = FaultPlan.parse("raft.step=drop:0.1", seed=seed)
        churn_world = _RaftWorld(f"{root}/churn")
        with plan_installed(plan):
            churn = _drive_raft_sequence(churn_world, payloads, kill_at)
        clock.record("raft.churn", time.perf_counter() - t0)
        drops = plan.fired().get("raft.step", 0)

        check(
            churn == baseline,
            "committed chain diverged from the no-fault run: "
            f"churn {churn[:3]}... != baseline {baseline[:3]}...",
        )
        # every SURVIVOR converged to the same chain
        for ch in churn_world.live_chains():
            check(
                ch.height == n_blocks,
                f"survivor {ch.node.id} at height {ch.height} != {n_blocks}",
            )
            for num, want_hash in churn:
                got = protoutil.block_header_hash(
                    ch.get_block(num).header
                ).hex()
                check(
                    got == want_hash,
                    f"survivor {ch.node.id} block {num} hash diverged",
                )
        killed = sorted(churn_world.dead)
        check(len(killed) == 1, f"expected exactly one kill: {killed}")
        det = {
            "blocks": n_blocks,
            "kill_at": kill_at,
            "killed_leader": killed,
            "chain": [h for _n, h in churn],
            "chain_matches_no_fault_run": True,
            "survivors_converged": True,
            "drops_fired": drops,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return det, {"message_drops": drops}


# ---------------------------------------------------------------------------
# fabcrash: deterministic process-kill matrix over the commit plane
# ---------------------------------------------------------------------------

#: every kill-eligible durability seam the crash matrix walks.  These
#: literals double as the fabreg fault-site exercise proof — each one is
#: a real fault_point site threaded through blockstore/kvledger/
#: persistent/pipeline (see the README fault-point table).
CRASH_SITES = (
    "blockstore.append.pre_fsync",
    "blockstore.append.post_fsync",
    "blockstore.append.pre_index",
    "kvledger.commit.pre_pvt",
    "kvledger.commit.post_block",
    "persistent.commit.mid",
    "pipeline.commit",
)


def _run_crash_sites(seed: int, clock: StageClock, sites, scale: float):
    """Shared crash-matrix driver: build a deterministic multi-channel
    block stream, run a reference (no-crash) subprocess peer to digest
    the converged state, then for each kill site SIGKILL-equivalent a
    fresh peer mid-commit (os._exit at the armed fault point), restart
    it, re-pull the missing blocks over the deliver failover path (a
    deliver.pull flap is armed so failover is actually taken), and
    require chain bytes + commit hash + VALID/INVALID masks + full
    state/hashed/pvt digests byte-identical to the no-crash run."""
    import os
    import shutil
    import subprocess
    import tempfile

    import fabric_tpu
    from fabric_tpu.common.faults import KILL_EXIT_CODE
    from fabric_tpu.tools import crashchild

    n_channels = 3
    n_blocks = max(5, int(6 * scale))
    kill_block = max(2, n_blocks // 2)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(fabric_tpu.__file__))
    )
    root = tempfile.mkdtemp(prefix="fabcrash_")
    try:
        stream = os.path.join(root, "stream")
        crashchild.build_stream(
            stream, seed=seed, n_channels=n_channels, n_blocks=n_blocks
        )

        base_env = {
            k: v
            for k, v in os.environ.items()
            if not k.startswith("FABRIC_TPU_FAULTS")
            and k != "FABRIC_TPU_CRASH_SITES"
        }
        base_env["PYTHONPATH"] = repo_root + os.pathsep + base_env.get(
            "PYTHONPATH", ""
        )

        def child(mode: str, workdir: str, extra: Dict[str, str]):
            env = dict(base_env)
            env.update(extra)
            return subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "fabric_tpu.tools.crashchild",
                    mode,
                    "--dir",
                    workdir,
                    "--stream",
                    stream,
                ],
                env=env,
                capture_output=True,
                text=True,
                timeout=120,
                cwd=repo_root,
            )

        ref_dir = os.path.join(root, "ref")
        r = clock.timed("crash.reference_commit", child, "commit", ref_dir, {})
        check(
            r.returncode == 0,
            f"reference commit run failed rc={r.returncode}",
        )
        r = child("recover", ref_dir, {})
        check(
            r.returncode == 0,
            f"reference recover run failed rc={r.returncode}",
        )
        with open(os.path.join(ref_dir, "digest.json")) as fh:
            ref_digest = json.load(fh)

        per_site: Dict[str, Dict[str, object]] = {}
        for site in sites:
            workdir = os.path.join(root, site.replace(".", "_"))
            r1 = clock.timed(
                "crash.kill_run",
                child,
                "commit",
                workdir,
                {"FABRIC_TPU_CRASH_SITES": f"{site}@{kill_block}"},
            )
            check(
                r1.returncode == KILL_EXIT_CODE,
                f"{site}: kill run exited {r1.returncode}, want "
                f"{KILL_EXIT_CODE}",
            )
            r2 = clock.timed(
                "crash.restart_recover",
                child,
                "recover",
                workdir,
                {"FABRIC_TPU_FAULTS": "deliver.pull=raise:1.0:max=1"},
            )
            check(
                r2.returncode == 0,
                f"{site}: restart recovery failed rc={r2.returncode}",
            )
            with open(os.path.join(workdir, "digest.json")) as fh:
                digest = json.load(fh)
            check(
                digest == ref_digest,  # fablint: disable=digest-compare  # JSON scorecard equality (convergence check), not a MAC comparison
                f"{site}: restart state DIVERGED from the no-crash run "
                f"(channels differing: "
                f"{sorted(c for c in ref_digest if digest.get(c) != ref_digest[c])})",
            )
            per_site[site] = {"killed": True, "converged": True}

        det = {
            "channels": n_channels,
            "blocks": n_blocks,
            "kill_block": kill_block,
            "sites": per_site,
            "ref_digest_sha": hashlib.sha256(
                json.dumps(ref_digest, sort_keys=True).encode()
            ).hexdigest()[:16],
        }
        return det, {"sites_run": len(per_site)}
    finally:
        shutil.rmtree(root, ignore_errors=True)


@scenario("crash_single")
def run_crash_single(seed: int, clock: StageClock, scale: float = 1.0):
    """Fast single-kill-site crash leg (the chaos_gate / tier-1 canary):
    kill one subprocess peer at the block-durable/state-missing window
    (kvledger.commit.post_block), restart, and byte-diff against the
    no-crash run."""
    return _run_crash_sites(
        seed, clock, ("kvledger.commit.post_block",), scale
    )


@scenario("crash_matrix")
def run_crash_matrix(seed: int, clock: StageClock, scale: float = 1.0):
    """Full deterministic kill-point matrix: a subprocess peer commits a
    multi-channel stream and is killed at EVERY durability seam in turn
    (torn-tail truncation, state replay, pvt-guard redelivery, sqlite
    WAL rollback all exercised); each restart must converge to chain
    bytes, state commit-hash and validation masks byte-identical to the
    no-crash same-seed run."""
    return _run_crash_sites(seed, clock, CRASH_SITES, scale)


@scenario("invalidation_storm")
def run_invalidation_storm(seed: int, clock: StageClock, scale: float = 1.0):
    """Resident-table invalidation storm (the ROADMAP fail-closed
    headroom): a ResidentDeviceValidator streams blocks while the state
    db is mutated BEHIND ITS BACK — rollback + re-commit between blocks,
    a rebuild mid-stream, and one mutation landing between encode and
    emit.  Every block's codes must match a fresh host oracle evaluated
    against the LIVE db (zero stale-version reads), stale tables must be
    dropped via the generation stamp (counted deterministically), and
    the mid-block mutation must force the verdicts to re-resolve on the
    host — never emitted from a dead table generation."""
    from fabric_tpu.ledger.mvcc import Validator
    from fabric_tpu.ledger.mvcc_device import ResidentDeviceValidator
    from fabric_tpu.ledger.rwset import (
        KVRead,
        KVWrite,
        NsRwSet,
        TxRwSet,
        Version,
    )
    from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB

    rng = random.Random(seed * 1000003 + 6)
    n_blocks = max(9, int(9 * scale))
    keys = [f"k{i}" for i in range(10)]

    db = VersionedDB()
    # seed committed state
    seed_batch = UpdateBatch()
    for i, k in enumerate(keys):
        seed_batch.put("cc", k, b"seed", Version(0, i))
    db.apply_updates(seed_batch)

    class _MidBlockMutator(ResidentDeviceValidator):
        """Scenario-local seam: run a mutation after the encode pass
        (slots assigned, launch imminent) — the window where only the
        post-launch generation re-check can save the mask."""

        mutate_after_encode = None

        def _encode_resident(self, *args, **kwargs):
            enc = super()._encode_resident(*args, **kwargs)
            if self.mutate_after_encode is not None:
                fn, self.mutate_after_encode = self.mutate_after_encode, None
                fn()
            return enc

    res = _MidBlockMutator(db, capacity=64)

    def behind_the_back_rollback(bn: int) -> None:
        """Rollback + re-commit: rewrite a hot key's committed version
        without going through the validator, then bump the generation
        (the contract every out-of-band mutator carries)."""
        batch = UpdateBatch()
        batch.put("cc", keys[bn % len(keys)], b"rolled", Version(0, 90 + bn))
        db.apply_updates(batch)
        db.bump_generation()

    def behind_the_back_rebuild(bn: int) -> None:
        """rebuild_dbs analog: delete + rewrite several keys at new
        versions, bump once."""
        batch = UpdateBatch()
        for i in range(0, len(keys), 2):
            batch.put("cc", keys[i], b"rebuilt", Version(0, 70 + i))
        batch.delete("cc", keys[1], Version(0, 60))
        db.apply_updates(batch)
        db.bump_generation()

    mutate_between = {3: behind_the_back_rollback, 6: behind_the_back_rebuild}
    mid_block_at = n_blocks - 1
    expected_invalidations = len(mutate_between) + 1

    codes_all: List[int] = []
    device_blocks = 0
    host_fallbacks = 0
    for bn in range(1, n_blocks + 1):
        rwsets = []
        for t in range(12):
            k = keys[min(int(rng.paretovariate(1.3)) - 1, len(keys) - 1)]
            committed = db.get_version("cc", k)
            stale = rng.random() < 0.25
            claim = (
                Version(committed.block_num, committed.tx_num + 1)
                if (stale and committed is not None)
                else committed
            )
            rwsets.append(
                TxRwSet(
                    (
                        NsRwSet(
                            "cc",
                            (KVRead(k, claim),),
                            (KVWrite(k, False, b"v%d" % bn),),
                        ),
                    )
                )
            )
        incoming = [VALID] * len(rwsets)
        if bn == mid_block_at:
            res.mutate_after_encode = lambda: behind_the_back_rollback(99)
        t0 = time.perf_counter()
        res_codes, _res_up, _res_hup = res.validate_and_prepare_batch(
            bn, rwsets, list(incoming)
        )
        clock.record("invalidation.block", time.perf_counter() - t0)
        # ground truth: a fresh host oracle over the LIVE (possibly just
        # mutated) db — any stale-table read diverges from this
        host_codes, host_up, host_hup = Validator(db).validate_and_prepare_batch(
            bn, rwsets, list(incoming)
        )
        check(
            res_codes == host_codes,
            f"block {bn}: resident codes diverged from live-state oracle "
            f"(stale-version read served?) at indexes "
            f"{[i for i, (a, b) in enumerate(zip(res_codes, host_codes)) if a != b][:8]}",
        )
        if bn == mid_block_at:
            check(
                res.last_path == "host",
                "mid-block mutation did not force host re-resolution — "
                "a mask was emitted from a dead table generation",
            )
            host_fallbacks += 1
        else:
            check(
                res.last_path == "device",
                f"block {bn}: expected the device-resident path",
            )
            device_blocks += 1
        db.apply_updates(host_up, host_hup)
        codes_all.extend(int(c) for c in res_codes)
        if bn in mutate_between:
            mutate_between[bn](bn)

    check(
        res.invalidations == expected_invalidations,
        f"saw {res.invalidations} table invalidations, expected "
        f"{expected_invalidations} (2 between-block + 1 mid-block)",
    )
    n_conflicts = sum(
        1 for c in codes_all if c == int(TxValidationCode.MVCC_READ_CONFLICT)
    )
    check(n_conflicts > 0, "storm produced no conflicts — not a storm")
    det = {
        "blocks": n_blocks,
        "txs": len(codes_all),
        "mvcc_conflicts": n_conflicts,
        "codes_sha": hashlib.sha256(bytes(codes_all)).hexdigest()[:16],
        "invalidations": res.invalidations,
        "device_blocks": device_blocks,
        "mid_block_host_fallbacks": host_fallbacks,
        "stale_reads_served": 0,
    }
    return det, {}


#: the <60s CI smoke: fast, no process pools, no real sleeps
SMOKE = (
    "verify_faults",
    "commit_storm",
    "deliver_flap",
    "corrupt_detect",
    "serve_flap",
    "qos_storm",
    "router_flap",
    "gray_failure",
    "hedge_storm",
    "deadline_storm",
    "raft_churn",
)


@scenario("soak")
def run_soak(seed: int, clock: StageClock, scale: float = 1.0,
             seconds: float = 20.0):
    """Long mixed soak: loop the storm scenarios with rotating seeds
    until the time budget expires.  Excluded from --scenario all (wall
    clock in, determinism out); the pytest soak is marked slow."""
    rounds = 0
    t_end = time.monotonic() + seconds
    while time.monotonic() < t_end:
        sub_seed = seed + rounds * 101
        run_verify_faults(sub_seed, clock, scale)
        run_commit_storm(sub_seed, clock, scale)
        run_mvcc_storm(sub_seed, clock, scale)
        rounds += 1
    det = {"note": "soak det fields vary by wall clock; see observed"}
    return det, {"rounds": rounds, "seconds": seconds}


# ---------------------------------------------------------------------------
# Runner + scorecard
# ---------------------------------------------------------------------------


def run_scenarios(
    names: Sequence[str],
    seed: int,
    scale: float = 1.0,
    soak_seconds: float = 20.0,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict:
    """Run scenarios; returns the full scorecard dict:
    {"deterministic": {...}, "observed": {...}}."""
    det_card: Dict[str, object] = {
        "harness": "fabchaos",
        "seed": seed,
        "scale": scale,
        "scenarios": {},
    }
    obs_card: Dict[str, object] = {"scenarios": {}, "stages": {}}
    ok_all = True
    for name in names:
        fn = SCENARIOS[name]
        clock = StageClock()
        if progress:
            progress(f"fabchaos: running {name} (seed {seed})")
        t0 = time.perf_counter()
        try:
            if name == "soak":
                det, obs = fn(seed, clock, scale, seconds=soak_seconds)
            else:
                det, obs = fn(seed, clock, scale)
            entry = {"ok": True}
            entry.update(det)
        except ChaosAssertionError as exc:
            ok_all = False
            entry = {"ok": False, "assertion": str(exc)}
            obs = {}
        det_card["scenarios"][name] = entry  # type: ignore[index]
        obs_card["scenarios"][name] = obs  # type: ignore[index]
        obs_card["stages"][name] = clock.summary()  # type: ignore[index]
        obs_card["scenarios"][name]["wall_s"] = round(  # type: ignore[index]
            time.perf_counter() - t0, 3
        )
    det_card["ok"] = ok_all
    return {"deterministic": det_card, "observed": obs_card}


def scorecard_for_bench(seed: int = 7, scale: float = 1.0) -> Dict:
    """Compact scorecard for bench.py's BENCH_*.json: smoke scenarios
    plus the per-stage latency summary."""
    card = run_scenarios(SMOKE, seed=seed, scale=scale)
    return {
        "seed": seed,
        "ok": card["deterministic"]["ok"],
        "scenarios": {
            name: {
                "ok": entry["ok"],
                "stages": card["observed"]["stages"].get(name, {}),
            }
            for name, entry in card["deterministic"]["scenarios"].items()
        },
        "det_sha": hashlib.sha256(
            json.dumps(card["deterministic"], sort_keys=True).encode()
        ).hexdigest()[:16],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fabchaos",
        description="deterministic fault-injection + adversarial traffic "
        "harness with per-stage SLO scorecard",
    )
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--scenario",
        default="smoke",
        help="comma-separated scenario names, or 'smoke' / 'all' "
        "(all excludes the wall-clock soak)",
    )
    ap.add_argument(
        "--scale", type=float, default=1.0, help="workload multiplier"
    )
    ap.add_argument("--soak-seconds", type=float, default=20.0)
    ap.add_argument(
        "--out", default="", help="write the FULL scorecard (deterministic "
        "+ observed latencies) to this JSON file",
    )
    ap.add_argument("--list-scenarios", action="store_true")
    ap.add_argument(
        "--quiet", action="store_true", help="suppress stderr progress"
    )
    args = ap.parse_args(argv)

    if args.list_scenarios:
        for name, fn in SCENARIOS.items():
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{name:18s} {doc}")
        return 0

    if args.scenario == "all":
        names = [n for n in SCENARIOS if n != "soak"]
    elif args.scenario == "smoke":
        names = list(SMOKE)
    else:
        names = [s.strip() for s in args.scenario.split(",") if s.strip()]
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            print(f"fabchaos: unknown scenarios {unknown}", file=sys.stderr)
            return 2

    progress = None if args.quiet else (
        lambda msg: print(msg, file=sys.stderr, flush=True)
    )
    card = run_scenarios(
        names,
        seed=args.seed,
        scale=args.scale,
        soak_seconds=args.soak_seconds,
        progress=progress,
    )
    # stdout carries ONLY the deterministic scorecard: two runs with the
    # same seed must be byte-identical (the ci_gate chaos stage diffs)
    print(json.dumps(card["deterministic"], sort_keys=True, indent=1))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(card, fh, sort_keys=True, indent=1)
    if not args.quiet:
        for name, stages in card["observed"]["stages"].items():
            for stage, s in stages.items():
                print(
                    f"fabchaos: {name:16s} {stage:24s} n={s['n']:<5d} "
                    f"p50={s['p50_ms']:.2f}ms p99={s['p99_ms']:.2f}ms",
                    file=sys.stderr,
                )
    return 0 if card["deterministic"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
