"""Developer tooling: the static-analysis gates.

- ``fablint``  — per-file AST invariants (imports, excepts, asserts...)
- ``fabdep``   — whole-program import layering + concurrency analysis
- ``fabflow``  — value-range/dtype abstract interpreter (the limb
  headroom proof) + mask-soundness pass

Everything in this package is dependency-free stdlib so the gates run in
minimal environments (no ``cryptography``, no ``jax``) without importing
any of the code they inspect.
"""
