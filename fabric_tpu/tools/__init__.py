"""Developer tooling: the static-analysis gates.

- ``fablint``  — per-file AST invariants (imports, excepts, asserts...)
- ``fabdep``   — whole-program import layering + concurrency analysis
- ``fabflow``  — value-range/dtype abstract interpreter (the limb
  headroom proof) + mask-soundness pass
- ``fabreg``   — declarative-contract drift (env registry, metric
  table, fault sites, suppression staleness)
- ``fablife``  — resource-lifetime + wire-trust analysis
- ``fabwire``  — wire-format conformance (encode/decode layout
  symmetry, rev gating, bounded lengths, dispatch totality)
- ``fabtrace`` — device-plane trace discipline (recompile hazards,
  hidden host syncs, per-lane transfer inventory; ``hotpath.toml``)

Everything in this package is dependency-free stdlib so the gates run in
minimal environments (no ``cryptography``, no ``jax``) without importing
any of the code they inspect.
"""
