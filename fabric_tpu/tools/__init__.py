"""Developer tooling: static analysis (fablint) and repo gates.

Everything in this package is dependency-free stdlib so the gates run in
minimal environments (no ``cryptography``, no ``jax``) without importing
any of the code they inspect.
"""
