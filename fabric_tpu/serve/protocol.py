"""Wire protocol of the resident validation sidecar.

Length-prefixed binary frames over a local stream socket (AF_UNIX path
or 127.0.0.1 TCP) — the software analogue of the whole-block offload
link in Blockchain Machine (PAPERS.md 2104.06968: the peer streams its
validation workload to an attached verifier over a fixed framing).

Frame layout (big-endian)::

    magic   2s   b"FT"
    version u8   the frame's protocol revision (1 or 2)
    opcode  u8   OP_*
    req_id  u32  caller-chosen; echoed verbatim on the response
    length  u32  payload byte count (bounded by MAX_PAYLOAD)
    payload length bytes

A version-1 VERIFY request payload is a key-deduplicated lane table::

    u16 n_keys, then per key:  u16 klen + klen bytes (SEC1 point)
    u32 n_lanes, then per lane: u16 key_idx | u16 siglen + sig
                                | u8 diglen + digest

``key_idx == NO_KEY`` marks a lane with no usable key — the server MUST
verify it as False (fail-closed), never error the whole batch.

Protocol revision 2 (the fleet QoS rev) prefixes the SAME lane table
with an admission-class header so a shared sidecar can shed
priority-aware::

    u8  qos_class   QOS_HIGH | QOS_NORMAL | QOS_BULK
    u8  chan_len  + chan_len bytes of UTF-8 channel id (accounting only)
    ... the v1 lane table, unchanged ...

Negotiation is per-frame and downgrade-safe in both directions: the
version byte rides every header, a v2 server accepts v1 frames (class
defaults to ``QOS_NORMAL``), and a v2 client hellos with a PING at its
preferred revision, latching v1 when a v1-only server refuses the
stream — old clients and old servers keep working unmodified.
Revision 2 also adds ``OP_DRAIN``: answer new VERIFY work
``ST_STOPPING`` while in-flight requests settle with their real
verdicts, then exit — the rolling-restart half of the failover story.

A VERIFY response payload::

    u8  status    ST_OK | ST_BUSY | ST_ERROR | ST_STOPPING
    u32 retry_after_ms   (admission control; meaningful for ST_BUSY)
    u32 n         (ST_OK: lane count, mask bytes follow; else message)
    n bytes       0/1 verdict per lane, or a UTF-8 message

Admission-control contract: ST_BUSY is a *rejection*, not an error —
the sidecar's lane budget is full and the client should retry after
``retry_after_ms`` (``common.retry`` paces the client side).  ST_ERROR
and ST_STOPPING are terminal for the request; the client shim degrades
to in-process verification (masks stay correct, never guessed VALID).

Every decode path raises :class:`ProtocolError` on malformed input —
a corrupt frame must kill the one request, not wedge the stream.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

PROTOCOL_VERSION = 2
MIN_PROTOCOL_VERSION = 1
MAGIC = b"FT"

# opcodes
OP_PING = 1
OP_VERIFY = 2
OP_STATS = 3
OP_SHUTDOWN = 4
OP_DRAIN = 5  # protocol rev 2: refuse new work, settle in-flight, exit

# admission (QoS) classes, protocol rev 2.  Lower id = higher priority;
# the names are the metric/scorecard vocabulary (label ``cls``).
QOS_HIGH = 0
QOS_NORMAL = 1
QOS_BULK = 2
QOS_NAMES = ("high", "normal", "bulk")
DEFAULT_QOS = QOS_NORMAL


def qos_name(qos_class: int) -> str:
    """Stable label text for a wire class id (unknown ids are clamped
    to bulk — an out-of-range class must never grant priority)."""
    if 0 <= qos_class < len(QOS_NAMES):
        return QOS_NAMES[qos_class]
    return QOS_NAMES[QOS_BULK]

# response statuses
ST_OK = 0
ST_BUSY = 1
ST_ERROR = 2
ST_STOPPING = 3

#: lane marker: no usable public key — the lane verifies False
NO_KEY = 0xFFFF

#: hard bound on one frame's payload; an oversized frame is a protocol
#: violation (fail-closed: reject, never buffer unbounded attacker data)
MAX_PAYLOAD = 64 << 20

_HEADER = struct.Struct(">2sBBII")
HEADER_SIZE = _HEADER.size


class ProtocolError(Exception):
    """Malformed frame or payload (bad magic, truncation, bounds)."""


def parse_address(address: str) -> Tuple[int, object]:
    """(family, bind/dial target): a path (contains '/') is AF_UNIX,
    else 'host:port' TCP on localhost.  Wire-level address format,
    shared by both ends (the client must not import the server)."""
    import socket

    if "/" in address:
        return socket.AF_UNIX, address
    host, _, port = address.rpartition(":")
    if not host:
        raise ValueError(f"address {address!r} is neither a path nor host:port")
    return socket.AF_INET, (host, int(port))


def pack_frame(
    opcode: int, req_id: int, payload: bytes,
    version: int = PROTOCOL_VERSION,
) -> bytes:
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError(
            f"payload {len(payload)} exceeds MAX_PAYLOAD {MAX_PAYLOAD}"
        )
    return _HEADER.pack(
        MAGIC, version, opcode, req_id & 0xFFFFFFFF, len(payload)
    ) + payload


def _recv_exact(sock, n: int) -> Optional[bytes]:
    """n bytes off the socket; None on clean EOF at a frame boundary,
    ProtocolError on EOF mid-frame."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise ProtocolError(f"connection closed mid-frame ({got}/{n}B)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame_ex(sock) -> Optional[Tuple[int, int, bytes, int]]:
    """(opcode, req_id, payload, version), or None on clean EOF.  Any
    revision in [MIN_PROTOCOL_VERSION, PROTOCOL_VERSION] is accepted —
    a v2 server keeps serving v1 clients, frame by frame."""
    head = _recv_exact(sock, HEADER_SIZE)
    if head is None:
        return None
    magic, version, opcode, req_id, length = _HEADER.unpack(head)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if not MIN_PROTOCOL_VERSION <= version <= PROTOCOL_VERSION:
        raise ProtocolError(f"unsupported protocol version {version}")
    if length > MAX_PAYLOAD:
        raise ProtocolError(f"frame length {length} exceeds MAX_PAYLOAD")
    payload = _recv_exact(sock, length) if length else b""
    if length and payload is None:
        raise ProtocolError("connection closed before payload")
    return opcode, req_id, payload or b"", version


def recv_frame(sock) -> Optional[Tuple[int, int, bytes]]:
    """(opcode, req_id, payload), or None on clean EOF (the version
    byte dropped — response payload layouts are revision-stable)."""
    frame = recv_frame_ex(sock)
    if frame is None:
        return None
    return frame[0], frame[1], frame[2]


def send_frame(
    sock, opcode: int, req_id: int, payload: bytes,
    version: int = PROTOCOL_VERSION,
) -> None:
    sock.sendall(pack_frame(opcode, req_id, payload, version=version))


# ---------------------------------------------------------------------------
# VERIFY request: key-deduplicated lane table
# ---------------------------------------------------------------------------


def encode_verify_request(
    key_table: Sequence[bytes],
    lanes: Sequence[Tuple[int, bytes, bytes]],
    qos_class: Optional[int] = None,
    channel: str = "",
) -> bytes:
    """key_table: SEC1 key bytes per distinct key; lanes: (key_idx, sig,
    digest) with key_idx == NO_KEY for unusable-key lanes.  Passing a
    ``qos_class`` produces the protocol-rev-2 body (class + channel
    prefix); ``None`` keeps the v1 layout byte-identical, so a client
    latched to v1 never emits a body an old server cannot parse."""
    out: List[bytes] = []
    if qos_class is not None:
        if not 0 <= qos_class < len(QOS_NAMES):
            raise ProtocolError(f"qos class {qos_class} out of range")
        chan = channel.encode("utf-8", "backslashreplace")[:255]
        out.append(struct.pack(">BB", qos_class, len(chan)))
        out.append(chan)
    out.append(_encode_lane_table(key_table, lanes))
    return b"".join(out)


def _encode_lane_table(
    key_table: Sequence[bytes],
    lanes: Sequence[Tuple[int, bytes, bytes]],
) -> bytes:
    if len(key_table) >= NO_KEY:
        raise ProtocolError(f"too many distinct keys ({len(key_table)})")
    out = [struct.pack(">H", len(key_table))]
    for k in key_table:
        if len(k) > 0xFFFF:
            raise ProtocolError("key too long")
        out.append(struct.pack(">H", len(k)))
        out.append(k)
    out.append(struct.pack(">I", len(lanes)))
    for key_idx, sig, digest in lanes:
        if len(sig) > 0xFFFF or len(digest) > 0xFF:
            raise ProtocolError("lane field too long")
        out.append(struct.pack(">HH", key_idx, len(sig)))
        out.append(sig)
        out.append(struct.pack(">B", len(digest)))
        out.append(digest)
    return b"".join(out)


class _Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def take(self, n: int) -> bytes:
        end = self.off + n
        if end > len(self.buf):
            raise ProtocolError("truncated payload")
        out = self.buf[self.off : end]
        self.off = end  # fabdep: disable=unguarded-shared-write  # request-scoped reader, single owner thread
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]


def decode_verify_request(
    payload: bytes,
    version: int = 1,
) -> Tuple[List[bytes], List[Tuple[int, bytes, bytes]], int, str]:
    """(keys, lanes, qos_class, channel).  v1 payloads decode with the
    default class (``QOS_NORMAL``) and an empty channel — the QoS
    admission path treats old clients exactly like unclassified
    traffic, never an error."""
    r = _Reader(payload)
    qos_class, channel = DEFAULT_QOS, ""
    if version >= 2:
        qos_class = r.u8()
        if not 0 <= qos_class < len(QOS_NAMES):
            raise ProtocolError(f"qos class {qos_class} out of range")
        channel = r.take(r.u8()).decode("utf-8", "replace")
    n_keys = r.u16()
    keys = [r.take(r.u16()) for _ in range(n_keys)]
    n_lanes = r.u32()
    if n_lanes > MAX_PAYLOAD:  # cheap sanity before the loop allocates
        raise ProtocolError(f"absurd lane count {n_lanes}")
    lanes = []
    for _ in range(n_lanes):
        key_idx = r.u16()
        sig = r.take(r.u16())
        digest = r.take(r.u8())
        if key_idx != NO_KEY and key_idx >= n_keys:
            raise ProtocolError(f"lane key index {key_idx} out of range")
        lanes.append((key_idx, sig, digest))
    if r.off != len(payload):
        raise ProtocolError("trailing bytes after lane table")
    return keys, lanes, qos_class, channel


# ---------------------------------------------------------------------------
# VERIFY response
# ---------------------------------------------------------------------------


def encode_verify_response(
    status: int,
    mask: Optional[Sequence[bool]] = None,
    message: str = "",
    retry_after_ms: int = 0,
) -> bytes:
    if status == ST_OK:
        body = bytes(1 if b else 0 for b in (mask or ()))
    else:
        body = message.encode("utf-8", "backslashreplace")[:4096]
    return struct.pack(
        ">BII", status, retry_after_ms & 0xFFFFFFFF, len(body)
    ) + body


def decode_verify_response(
    payload: bytes,
) -> Tuple[int, int, Optional[List[bool]], str]:
    """(status, retry_after_ms, mask-or-None, message)."""
    r = _Reader(payload)
    status = r.u8()
    retry_after_ms = r.u32()
    n = r.u32()
    body = r.take(n)
    if r.off != len(payload):
        raise ProtocolError("trailing bytes after response body")
    if status == ST_OK:
        return status, retry_after_ms, [b != 0 for b in body], ""
    return status, retry_after_ms, None, body.decode("utf-8", "replace")
