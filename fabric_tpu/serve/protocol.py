"""Wire protocol of the resident validation sidecar.

Length-prefixed binary frames over a local stream socket (AF_UNIX path
or 127.0.0.1 TCP) — the software analogue of the whole-block offload
link in Blockchain Machine (PAPERS.md 2104.06968: the peer streams its
validation workload to an attached verifier over a fixed framing).

Frame layout (big-endian)::

    magic   2s   b"FT"
    version u8   the frame's protocol revision (1, 2 or 3)
    opcode  u8   OP_*
    req_id  u32  caller-chosen; echoed verbatim on the response
    length  u32  payload byte count (bounded by MAX_PAYLOAD)
    payload length bytes

A version-1 VERIFY request payload is a key-deduplicated lane table::

    u16 n_keys, then per key:  u16 klen + klen bytes (SEC1 point)
    u32 n_lanes, then per lane: u16 key_idx | u16 siglen + sig
                                | u8 diglen + digest

``key_idx == NO_KEY`` marks a lane with no usable key — the server MUST
verify it as False (fail-closed), never error the whole batch.

Protocol revision 2 (the fleet QoS rev) prefixes the SAME lane table
with an admission-class header so a shared sidecar can shed
priority-aware::

    u8  qos_class   QOS_HIGH | QOS_NORMAL | QOS_BULK
    u8  chan_len  + chan_len bytes of UTF-8 channel id (accounting only)
    ... the v1 lane table, unchanged ...

Protocol revision 3 (the tail-tolerance rev) inserts a per-request
latency budget between the QoS prefix and the lane table::

    u32 deadline_ms   remaining budget when the frame was sent
                      (0 = no deadline — the v2 semantics exactly)
    ... the v1 lane table, unchanged ...

The deadline contract: the server sheds work it provably cannot finish
inside the budget as an explicit ``ST_BUSY`` — never a silent drop,
never a fabricated verdict — and caps its coalescing linger by the
tightest in-flight budget.  Revision 3 also adds ``OP_CANCEL``: a
fire-and-forget frame whose ``req_id`` names an in-flight VERIFY the
client no longer wants (a hedge lost the race, a budget expired).
Cancellation is best-effort bookkeeping, not a correctness lever: a
cancel that arrives before dispatch sheds the work uncomputed, one
that loses the race to the settlement merely suppresses the reply the
client would drop anyway.  ``OP_CANCEL`` carries no response frame —
it must never collide with the cancelled request's own reply in the
client's demux.

Negotiation is per-frame and downgrade-safe in both directions: the
version byte rides every header, a v3 server accepts v1/v2 frames
(class defaults to ``QOS_NORMAL``, deadline to none), and a v3 client
hellos with a PING at its preferred revision, stepping down one
revision per refusal (v3 -> v2 -> v1) so each vintage of server keeps
every feature it understands — an old server costs the client the
newer fields, never the connection.
Revision 2 also adds ``OP_DRAIN``: answer new VERIFY work
``ST_STOPPING`` while in-flight requests settle with their real
verdicts, then exit — the rolling-restart half of the failover story.

A VERIFY response payload::

    u8  status    ST_OK | ST_BUSY | ST_ERROR | ST_STOPPING
    u32 retry_after_ms   (admission control; meaningful for ST_BUSY)
    u32 n         (ST_OK: lane count, mask bytes follow; else message)
    n bytes       0/1 verdict per lane, or a UTF-8 message

Admission-control contract: ST_BUSY is a *rejection*, not an error —
the sidecar's lane budget is full and the client should retry after
``retry_after_ms`` (``common.retry`` paces the client side).  ST_ERROR
and ST_STOPPING are terminal for the request; the client shim degrades
to in-process verification (masks stay correct, never guessed VALID).

Every decode path raises :class:`ProtocolError` on malformed input —
a corrupt frame must kill the one request, not wedge the stream.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

PROTOCOL_VERSION = 3
MIN_PROTOCOL_VERSION = 1
MAGIC = b"FT"

# opcodes
OP_PING = 1
OP_VERIFY = 2
OP_STATS = 3
OP_SHUTDOWN = 4
OP_DRAIN = 5  # protocol rev 2: refuse new work, settle in-flight, exit
OP_CANCEL = 6  # protocol rev 3: best-effort abandon of an in-flight VERIFY

# admission (QoS) classes, protocol rev 2.  Lower id = higher priority;
# the names are the metric/scorecard vocabulary (label ``cls``).
QOS_HIGH = 0
QOS_NORMAL = 1
QOS_BULK = 2
QOS_NAMES = ("high", "normal", "bulk")
DEFAULT_QOS = QOS_NORMAL


def qos_name(qos_class: int) -> str:
    """Stable label text for a wire class id (unknown ids are clamped
    to bulk — an out-of-range class must never grant priority)."""
    if 0 <= qos_class < len(QOS_NAMES):
        return QOS_NAMES[qos_class]
    return QOS_NAMES[QOS_BULK]

# response statuses
ST_OK = 0
ST_BUSY = 1
ST_ERROR = 2
ST_STOPPING = 3

#: lane marker: no usable public key — the lane verifies False
NO_KEY = 0xFFFF

#: hard bound on one frame's payload; an oversized frame is a protocol
#: violation (fail-closed: reject, never buffer unbounded attacker data)
MAX_PAYLOAD = 64 << 20

_HEADER = struct.Struct(">2sBBII")
HEADER_SIZE = _HEADER.size


class ProtocolError(Exception):
    """Malformed frame or payload (bad magic, truncation, bounds)."""


def parse_address(address: str) -> Tuple[int, object]:
    """(family, bind/dial target): a path (contains '/') is AF_UNIX,
    else 'host:port' TCP on localhost.  Wire-level address format,
    shared by both ends (the client must not import the server)."""
    import socket

    if "/" in address:
        return socket.AF_UNIX, address
    host, _, port = address.rpartition(":")
    if not host:
        raise ValueError(f"address {address!r} is neither a path nor host:port")
    return socket.AF_INET, (host, int(port))


def pack_frame(
    opcode: int, req_id: int, payload: bytes,
    version: int = PROTOCOL_VERSION,
) -> bytes:
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError(
            f"payload {len(payload)} exceeds MAX_PAYLOAD {MAX_PAYLOAD}"
        )
    return _HEADER.pack(
        MAGIC, version, opcode, req_id & 0xFFFFFFFF, len(payload)
    ) + payload


def _recv_exact(sock, n: int) -> Optional[bytes]:
    """n bytes off the socket; None on clean EOF at a frame boundary,
    ProtocolError on EOF mid-frame."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))  # fablife: disable=blocking-unbudgeted  # the socket's timeout is owned by the CALLER (server arms per-conn settimeout; the client demux select-bounds before reading): protocol.py is the framing layer and must not override it
        if not chunk:
            if got == 0:
                return None
            raise ProtocolError(f"connection closed mid-frame ({got}/{n}B)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame_ex(sock) -> Optional[Tuple[int, int, bytes, int]]:
    """(opcode, req_id, payload, version), or None on clean EOF.  Any
    revision in [MIN_PROTOCOL_VERSION, PROTOCOL_VERSION] is accepted —
    a v2 server keeps serving v1 clients, frame by frame."""
    head = _recv_exact(sock, HEADER_SIZE)
    if head is None:
        return None
    magic, version, opcode, req_id, length = _HEADER.unpack(head)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if not MIN_PROTOCOL_VERSION <= version <= PROTOCOL_VERSION:
        raise ProtocolError(f"unsupported protocol version {version}")
    if length > MAX_PAYLOAD:
        raise ProtocolError(f"frame length {length} exceeds MAX_PAYLOAD")
    payload = _recv_exact(sock, length) if length else b""
    if length and payload is None:
        raise ProtocolError("connection closed before payload")
    return opcode, req_id, payload or b"", version


def recv_frame(sock) -> Optional[Tuple[int, int, bytes]]:
    """(opcode, req_id, payload), or None on clean EOF (the version
    byte dropped — response payload layouts are revision-stable)."""
    frame = recv_frame_ex(sock)
    if frame is None:
        return None
    return frame[0], frame[1], frame[2]


def send_frame(
    sock, opcode: int, req_id: int, payload: bytes,
    version: int = PROTOCOL_VERSION,
) -> None:
    sock.sendall(pack_frame(opcode, req_id, payload, version=version))


# ---------------------------------------------------------------------------
# VERIFY request: key-deduplicated lane table
# ---------------------------------------------------------------------------


def encode_verify_request(
    key_table: Sequence[bytes],
    lanes: Sequence[Tuple[int, bytes, bytes]],
    qos_class: Optional[int] = None,
    channel: str = "",
    deadline_ms: Optional[int] = None,
) -> bytes:
    """key_table: SEC1 key bytes per distinct key; lanes: (key_idx, sig,
    digest) with key_idx == NO_KEY for unusable-key lanes.  Passing a
    ``qos_class`` produces the protocol-rev-2 body (class + channel
    prefix); ``None`` keeps the v1 layout byte-identical, so a client
    latched to v1 never emits a body an old server cannot parse.
    Passing ``deadline_ms`` (remaining latency budget; 0 = no deadline)
    produces the rev-3 body — only valid on top of the QoS prefix, and
    REQUIRED on every v3 frame: the body layout is keyed to the frame
    revision, so a v3 sender with no budget passes 0, never None (a
    v2-latched client passes None).  Callers with a live budget floor
    it at 1 themselves — a budget that rounds to 0 must not decode as
    'no deadline'."""
    out: List[bytes] = []
    if deadline_ms is not None and qos_class is None:
        raise ProtocolError(
            "deadline_ms requires the rev-2 QoS prefix (qos_class)"
        )
    if qos_class is not None:
        if not 0 <= qos_class < len(QOS_NAMES):
            raise ProtocolError(f"qos class {qos_class} out of range")
        chan = channel.encode("utf-8", "backslashreplace")[:255]
        out.append(struct.pack(">BB", qos_class, len(chan)))
        out.append(chan)
    if deadline_ms is not None:
        out.append(struct.pack(">I", max(0, int(deadline_ms)) & 0xFFFFFFFF))
    out.append(_encode_lane_table(key_table, lanes))
    return b"".join(out)


def _encode_lane_table(
    key_table: Sequence[bytes],
    lanes: Sequence[Tuple[int, bytes, bytes]],
) -> bytes:
    if len(key_table) >= NO_KEY:
        raise ProtocolError(f"too many distinct keys ({len(key_table)})")
    out = [struct.pack(">H", len(key_table))]
    for k in key_table:
        if len(k) > 0xFFFF:
            raise ProtocolError("key too long")
        out.append(struct.pack(">H", len(k)))
        out.append(k)
    out.append(struct.pack(">I", len(lanes)))
    for key_idx, sig, digest in lanes:
        if len(sig) > 0xFFFF or len(digest) > 0xFF:
            raise ProtocolError("lane field too long")
        out.append(struct.pack(">HH", key_idx, len(sig)))
        out.append(sig)
        out.append(struct.pack(">B", len(digest)))
        out.append(digest)
    return b"".join(out)


class _Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def take(self, n: int) -> bytes:
        end = self.off + n
        if end > len(self.buf):
            raise ProtocolError("truncated payload")
        out = self.buf[self.off : end]
        self.off = end  # fabdep: disable=unguarded-shared-write  # request-scoped reader, single owner thread
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]


def decode_verify_request(
    payload: bytes,
    version: int = 1,
) -> Tuple[List[bytes], List[Tuple[int, bytes, bytes]], int, str, int]:
    """(keys, lanes, qos_class, channel, deadline_ms).  v1 payloads
    decode with the default class (``QOS_NORMAL``) and an empty channel
    — the QoS admission path treats old clients exactly like
    unclassified traffic, never an error.  Pre-v3 payloads decode with
    ``deadline_ms == 0`` (no deadline): an old client's work is never
    shed on a budget it could not have set."""
    r = _Reader(payload)
    qos_class, channel, deadline_ms = DEFAULT_QOS, "", 0
    if version >= 2:
        qos_class = r.u8()
        if not 0 <= qos_class < len(QOS_NAMES):
            raise ProtocolError(f"qos class {qos_class} out of range")
        channel = r.take(r.u8()).decode("utf-8", "replace")
    if version >= 3:
        deadline_ms = r.u32()
    n_keys = r.u16()
    keys = [r.take(r.u16()) for _ in range(n_keys)]
    n_lanes = r.u32()
    if n_lanes > MAX_PAYLOAD:  # cheap sanity before the loop allocates
        raise ProtocolError(f"absurd lane count {n_lanes}")
    lanes = []
    for _ in range(n_lanes):
        key_idx = r.u16()
        sig = r.take(r.u16())
        digest = r.take(r.u8())
        if key_idx != NO_KEY and key_idx >= n_keys:
            raise ProtocolError(f"lane key index {key_idx} out of range")
        lanes.append((key_idx, sig, digest))
    if r.off != len(payload):
        raise ProtocolError("trailing bytes after lane table")
    return keys, lanes, qos_class, channel, deadline_ms


# ---------------------------------------------------------------------------
# VERIFY response
# ---------------------------------------------------------------------------


def encode_verify_response(
    status: int,
    mask: Optional[Sequence[bool]] = None,
    message: str = "",
    retry_after_ms: int = 0,
) -> bytes:
    if status == ST_OK:
        body = bytes(1 if b else 0 for b in (mask or ()))
    else:
        body = message.encode("utf-8", "backslashreplace")[:4096]
    return struct.pack(
        ">BII", status, retry_after_ms & 0xFFFFFFFF, len(body)
    ) + body


def decode_verify_response(
    payload: bytes,
) -> Tuple[int, int, Optional[List[bool]], str]:
    """(status, retry_after_ms, mask-or-None, message)."""
    r = _Reader(payload)
    status = r.u8()
    retry_after_ms = r.u32()
    n = r.u32()
    body = r.take(n)
    if r.off != len(payload):
        raise ProtocolError("trailing bytes after response body")
    if status == ST_OK:
        return status, retry_after_ms, [b != 0 for b in body], ""
    return status, retry_after_ms, None, body.decode("utf-8", "replace")
