"""The resident validation sidecar process.

One long-lived process owns the verify backends (host EC ladder or the
device provider), pre-warms the bucketed program registry at startup,
and serves whole-batch validation requests over a local socket — the
software analogue of 2104.06968's attached hardware validator, serving
1907.08367's reordered validation stages from a warm process.  What a
cold bench run pays per invocation (backend init, pool spin-up, minutes
of XLA compile), the sidecar pays once per process lifetime.

Request flow per VERIFY frame::

    decode -> serve.dispatch fault seam -> QoS CLASS ADMISSION
    (per-class lane quotas, work-conserving borrowing) -> ADMISSION
    (VerifyBatcher bounded lanes, non-blocking) -> coalesced launch ->
    mask reply

Admission control is two-tiered protocol backpressure: the per-class
:class:`~fabric_tpu.serve.qos.ClassLedger` quota first (priority-aware
— a zipf spam channel can borrow idle lanes but never a paying
channel's reservation), then the VerifyBatcher's bounded-lane budget.
A request that does not fit NOW is REJECTED with ``ST_BUSY`` + a
per-class ``retry_after_ms`` instead of blocking the socket thread —
the client shim paces retries with ``common.retry`` and the peer's
deliver loop stalls exactly like the reference's WaitReady discipline.
Every shed is a protocol-level reply, never a silent drop.

Shutdown is fail-closed *and* mask-exact: in-flight requests settled by
a dying batcher are answered ``ST_STOPPING`` (never an OK carrying
guessed verdicts), so the client re-verifies in-process and masks stay
bit-exact through a sidecar kill.  ``drain()`` (OP_DRAIN / SIGTERM) is
the rolling-restart half: NEW work answers ``ST_STOPPING`` immediately
while in-flight requests settle with their real computed verdicts, so
restarting every sidecar behind a router under load never costs a mask
bit.

Run it::

    python -m fabric_tpu.serve --address /tmp/fabserve.sock \
        --engine host --warm demo --aot-dir .jax_cache/serve_aot
"""

from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.metrics import latency_summary
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.qos import ClassLedger
from fabric_tpu.serve.registry import (
    BucketProgramRegistry,
    DEFAULT_BUCKETS,
    demo_limb_program,
    verify_limb_program,
)

logger = must_get_logger("serve.server")

ENGINES = ("auto", "host", "device")
WARM_LADDERS = ("off", "demo", "verify")


# wire-level address parsing lives with the protocol (shared by both
# ends); re-exported here for back-compat with existing importers
parse_address = proto.parse_address


class ServeStats:
    """Request accounting with a dual surface: ``summary()`` stays the
    STATS reply and the ``configs.serve`` bench column (exact, local,
    provider-free), while every recording call ALSO drives the fabobs
    metric SPI — so a scrape of the mounted ops server's ``/metrics``
    sees the same traffic as live ``fabric_serve_*`` series.  The SPI
    emission is the zero-when-disabled fabobs hook; nothing here blocks
    or raises on an obs failure."""

    RESERVOIR = 8192

    def __init__(self):
        self._lock = threading.Lock()
        self.requests = 0
        self.lanes = 0
        self.rejects = 0
        self.errors = 0
        self.degraded_replies = 0
        # tail-tolerance accounting (protocol rev 3): work shed because
        # its wire budget provably could not be met, and requests the
        # client abandoned via OP_CANCEL (pre-dispatch sheds vs replies
        # suppressed after the verdict was computed)
        self.deadline_shed = 0
        self.class_deadline_shed: Dict[str, int] = {}
        self.cancelled_pre = 0
        self.cancelled_post = 0
        # monotone per-bucket service-time floor: the fastest this
        # sidecar has EVER served the bucket — the evidence behind the
        # "provably cannot finish" deadline shed (no evidence = serve)
        self.min_service_s: Dict[int, float] = {}
        # newest-win sliding window: a long-lived sidecar that slows
        # down later must not keep reporting startup-era p50/p99
        self._latency_s: collections.deque = collections.deque(
            maxlen=self.RESERVOIR
        )
        self.per_bucket: Dict[int, int] = {}
        # per-class request/lane/shed accounting (protocol rev 2): the
        # qos_storm scorecard proves priority-aware shedding off these
        # numbers, and every shed here was an explicit ST_BUSY reply
        self.class_served: Dict[str, int] = {}
        self.class_lanes: Dict[str, int] = {}
        self.class_busy: Dict[str, int] = {}
        # per-class latency windows back the per-class p99 the fleet
        # bench reports (same newest-win discipline as the global one)
        self._class_latency_s: Dict[str, collections.deque] = {}

    def record(
        self, lanes: int, bucket: int, seconds: float,
        qos_class: int = proto.DEFAULT_QOS,
    ) -> None:
        cls = proto.qos_name(qos_class)
        with self._lock:
            self.requests += 1
            self.lanes += lanes
            self.per_bucket[bucket] = self.per_bucket.get(bucket, 0) + 1
            self._latency_s.append(seconds)
            prior = self.min_service_s.get(bucket)
            if prior is None or seconds < prior:
                self.min_service_s[bucket] = seconds
            self.class_served[cls] = self.class_served.get(cls, 0) + 1
            self.class_lanes[cls] = self.class_lanes.get(cls, 0) + lanes
            window = self._class_latency_s.get(cls)
            if window is None:
                window = self._class_latency_s[cls] = collections.deque(
                    maxlen=self.RESERVOIR
                )
            window.append(seconds)
        fabobs.obs_count("fabric_serve_requests_total", status="ok")
        fabobs.obs_count("fabric_serve_lanes_total", lanes)
        fabobs.obs_count("fabric_serve_class_lanes_total", lanes, cls=cls)
        fabobs.obs_count(
            "fabric_serve_bucket_requests_total", bucket=str(bucket)
        )
        fabobs.obs_observe("fabric_serve_request_seconds", seconds)

    def reject(self, qos_class: int = proto.DEFAULT_QOS) -> None:
        cls = proto.qos_name(qos_class)
        with self._lock:
            self.rejects += 1
            self.class_busy[cls] = self.class_busy.get(cls, 0) + 1
        fabobs.obs_count("fabric_serve_requests_total", status="busy")
        fabobs.obs_count("fabric_serve_class_busy_total", cls=cls)

    def error(self) -> None:
        with self._lock:
            self.errors += 1
        fabobs.obs_count("fabric_serve_requests_total", status="error")

    def stopping_reply(self) -> None:
        with self._lock:
            self.degraded_replies += 1
        fabobs.obs_count("fabric_serve_requests_total", status="stopping")

    def deadline_reject(self, qos_class: int = proto.DEFAULT_QOS) -> None:
        """An explicit ST_BUSY shed because the request's wire budget
        provably cannot be met — counted apart from admission rejects
        (the QoS ledger never saw this request, so the qos_storm
        ledger/stats cross-check stays exact), attributed per class
        like every other shed."""
        cls = proto.qos_name(qos_class)
        with self._lock:
            self.deadline_shed += 1
            self.class_deadline_shed[cls] = (
                self.class_deadline_shed.get(cls, 0) + 1
            )
        fabobs.obs_count(
            "fabric_serve_deadline_expired_total", seam="serve.server"
        )
        fabobs.obs_count(
            "fabric_serve_requests_total", status="deadline_shed"
        )

    def cancel(self, pre_dispatch: bool) -> None:
        with self._lock:
            if pre_dispatch:
                self.cancelled_pre += 1
            else:
                self.cancelled_post += 1

    def floor_s(self, bucket: int) -> Optional[float]:
        """The bucket's best-ever service time (evidence floor for the
        deadline shed), or None before the first served request."""
        with self._lock:
            return self.min_service_s.get(bucket)

    def summary(self) -> Dict:
        with self._lock:
            return {
                "requests": self.requests,
                "lanes": self.lanes,
                "rejects": self.rejects,
                "errors": self.errors,
                "degraded_replies": self.degraded_replies,
                "deadline_shed": self.deadline_shed,
                "cancelled_pre": self.cancelled_pre,
                "cancelled_post": self.cancelled_post,
                "per_bucket": {str(k): v for k, v in self.per_bucket.items()},
                "request_latency": latency_summary(list(self._latency_s)),
                "per_class": {
                    cls: {
                        "served": self.class_served.get(cls, 0),
                        "lanes": self.class_lanes.get(cls, 0),
                        "busy": self.class_busy.get(cls, 0),
                        "deadline_shed": self.class_deadline_shed.get(
                            cls, 0
                        ),
                        "latency": latency_summary(
                            list(self._class_latency_s.get(cls, ()))
                        ),
                    }
                    for cls in proto.QOS_NAMES
                    if self.class_served.get(cls, 0)
                    or self.class_busy.get(cls, 0)
                    or self.class_deadline_shed.get(cls, 0)
                },
            }


class _CancelSet:
    """Per-connection registry of OP_CANCELled request ids, shared by
    the read loop (writer) and the verify workers (consumers).  Bounded
    LRU: a cancel that arrives after its request already settled leaves
    an id nobody will ever take — the cap stops a cancel-spamming
    client from growing server memory."""

    MAX = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._ids: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )

    def add(self, req_id: int) -> None:
        with self._lock:
            self._ids[req_id] = None
            self._ids.move_to_end(req_id)
            while len(self._ids) > self.MAX:
                self._ids.popitem(last=False)

    def take(self, req_id: int) -> bool:
        """True exactly once per cancelled id (the taker owns the
        suppression; a second racer sees False — no double-count)."""
        with self._lock:
            return self._ids.pop(req_id, 0) is None


def build_provider(engine: str = "auto"):
    """The sidecar's verify backend.  'host' is the SW EC ladder
    (fastec -> hostec_np -> hostec); 'device' is the accelerator
    provider; 'auto' defers to the shared bounded probe ladder
    (``bccsp.probe_provider`` — one copy of the probe/degrade policy,
    not a local fork that could drift)."""
    from fabric_tpu.crypto.bccsp import SoftwareProvider

    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r} (expected {ENGINES})")
    if engine == "auto":
        from fabric_tpu.crypto.bccsp import probe_provider

        provider = probe_provider()
        return provider, (
            "host" if isinstance(provider, SoftwareProvider) else "device"
        )
    if engine == "device":
        from fabric_tpu.crypto.tpu_provider import TPUProvider

        return TPUProvider(), "device"
    return SoftwareProvider(), "host"


class SidecarServer:
    """Resident sidecar: socket front, VerifyBatcher middle, warm
    bucketed backends behind.  Usable in-process (tests, fabchaos
    serve_flap) or as the ``python -m fabric_tpu.serve`` daemon."""

    def __init__(
        self,
        address: str,
        engine: str = "auto",
        provider=None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_pending_lanes: int = 65536,
        linger_s: float = 0.002,
        warm_ladder: str = "off",
        aot_dir: Optional[str] = None,
        retry_after_base_ms: int = 25,
        ops_address: Optional[str] = None,
        qos_shares: Optional[Dict[str, float]] = None,
        drain_timeout_s: float = 5.0,
        chaos_key: Optional[int] = None,
    ):
        from fabric_tpu.parallel.batcher import VerifyBatcher

        if warm_ladder not in WARM_LADDERS:
            raise ValueError(
                f"unknown warm ladder {warm_ladder!r} (expected {WARM_LADDERS})"
            )
        self.address = address
        self.buckets = tuple(buckets)
        if provider is not None:
            self.provider, self.engine = provider, engine
        else:
            self.provider, self.engine = build_provider(engine)
        self.batcher = VerifyBatcher(
            self.provider,
            max_pending_lanes=max_pending_lanes,
            linger_s=linger_s,
        )
        self.max_pending_lanes = max_pending_lanes
        self.retry_after_base_ms = retry_after_base_ms
        # per-class admission in FRONT of the batcher's global budget:
        # the ledger's lanes are held submit -> dispatch, the SAME
        # window as the batcher's own permits (released through its
        # on_dispatch hook), so the class quotas partition exactly the
        # budget the batcher enforces and shedding is priority-aware
        self.qos = ClassLedger(max_pending_lanes, qos_shares)
        self.drain_timeout_s = drain_timeout_s
        # chaos addressing: when set, the serve.dispatch fault point is
        # keyed by this int so a plan's at= pin can fault ONE sidecar
        # of an in-process fleet (the gray-failure scenarios); None
        # keeps the PR 12 unkeyed per-site stream semantics unchanged
        self.chaos_key = chaos_key
        self._draining = False
        self._active_verifies = 0
        self._drain_cv = threading.Condition()
        self.stats = ServeStats()
        self.registry: Optional[BucketProgramRegistry] = None
        self.warm_ladder = warm_ladder
        self.aot_dir = aot_dir
        self.warm_report: Dict = {}
        self._listener: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conn_lock = threading.Lock()
        self._stopping = False
        self._started = False
        # optional mounted ops plane: /metrics + /healthz for THIS
        # sidecar (started in start(), torn down in stop()).  The obs
        # registry is enabled NOW, not at mount time, so warm() — which
        # runs before start() — already lands its per-bucket series on
        # the provider the ops server will scrape.
        self.ops_address = ops_address
        self.ops = None
        if ops_address:
            fabobs.ensure_enabled()

    # -- warm-up -----------------------------------------------------------
    def warm(self) -> Dict:
        """Pre-warm before accepting traffic: spin the host pools with
        one small batch, and AOT-warm the jax bucket ladder when asked.
        Returns the warm report (bench's ``configs.serve.warm``)."""
        t0 = time.perf_counter()
        report: Dict = {"engine": self.engine, "ladder": self.warm_ladder}
        report["host_warm_ms"] = round(self._warm_host() * 1000.0, 1)
        if self.warm_ladder != "off":
            fn, shapes_for = (
                demo_limb_program()
                if self.warm_ladder == "demo"
                else verify_limb_program()
            )
            self.registry = BucketProgramRegistry.for_jax_program(
                fn,
                shapes_for,
                buckets=self.buckets,
                label=f"serve-{self.warm_ladder}",
                aot_dir=self.aot_dir,
            )
            self.registry.warm()
            report["per_bucket"] = {
                str(k): v for k, v in self.registry.warm_report.items()
            }
            report["traces"] = self.registry.traces
        report["total_warm_ms"] = round((time.perf_counter() - t0) * 1000.0, 1)
        self.warm_report = report
        self._export_warm_metrics(report)
        return report

    @staticmethod
    def _export_warm_metrics(report: Dict) -> None:
        """Registry warm accounting -> per-bucket gauge series, so a
        /metrics scrape carries the same cold/cache/AOT story as the
        warm report without re-deriving it."""
        for bucket, rep in (report.get("per_bucket") or {}).items():
            fabobs.obs_gauge(
                "fabric_serve_bucket_warm_ms",
                rep.get("warm_ms", 0.0), bucket=str(bucket),
            )
            fabobs.obs_gauge(
                "fabric_serve_bucket_xla_compiles",
                rep.get("xla_compiles", 0), bucket=str(bucket),
            )
            fabobs.obs_gauge(
                "fabric_serve_bucket_cache_hits",
                rep.get("cache_hits", 0), bucket=str(bucket),
            )
            fabobs.obs_gauge(
                "fabric_serve_bucket_aot_hit",
                1.0 if rep.get("aot_hit") else 0.0, bucket=str(bucket),
            )

    def _warm_host(self) -> float:
        """One tiny batch through the provider so pool spin-up and key
        tables are paid before the first real request."""
        from fabric_tpu.crypto.bccsp import ECDSAPublicKey, ec_backend

        t0 = time.perf_counter()
        ec = ec_backend()
        kp = ec.generate_keypair()
        import hashlib as _hashlib

        from fabric_tpu.common import der as _der

        digest = _hashlib.sha256(b"serve warm lane").digest()
        r, s = ec.sign_digest(kp.priv, digest)
        sig = _der.marshal_signature(r, s)
        key = ECDSAPublicKey(*kp.pub)
        n = 8
        mask = self.batcher.verify_batch([key] * n, [sig] * n, [digest] * n)
        if list(mask) != [True] * n:
            raise RuntimeError("warm-up batch failed verification")
        return time.perf_counter() - t0

    # -- socket front ------------------------------------------------------
    def start(self) -> str:
        """Bind + accept loop; returns the bound address (TCP port
        resolved).  ``warm()`` is NOT implied — call it first so the
        READY line means 'steady state will not compile'."""
        family, target = parse_address(self.address)
        listener = socket.socket(family, socket.SOCK_STREAM)
        if family == socket.AF_UNIX:
            try:
                os.unlink(target)
            except FileNotFoundError:
                pass
        else:
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(target)
        listener.listen(64)
        if family != socket.AF_UNIX:
            host, port = listener.getsockname()[:2]
            self.address = f"{host}:{port}"
        self._listener = listener
        self._started = True
        accept = threading.Thread(
            target=self._accept_loop, name="serve-accept", daemon=True
        )
        accept.start()
        with self._conn_lock:
            self._threads.append(accept)
        if self.ops_address:
            self.mount_operations()
        logger.info("sidecar serving on %s (engine %s)", self.address, self.engine)
        return self.address

    # -- mounted operations plane ------------------------------------------
    def mount_operations(self) -> str:
        """Start the node-admin HTTP server inside the sidecar process:
        ``/metrics`` serves the fabobs data-plane series live (batcher,
        ladder rungs, serve requests, registry warm, faults, retries)
        and ``/healthz`` runs the sidecar's registered checkers.  The
        obs registry and the ops provider are the SAME PrometheusProvider
        — first enabler wins, so a process already observed (env
        FABRIC_TPU_OBS) mounts its existing provider."""
        from fabric_tpu.operations import Options as OpsOptions, System

        reg = fabobs.active()
        if reg is not None:
            system = System(
                OpsOptions(
                    listen_address=self.ops_address, provider=reg.provider
                )
            )
        else:
            system = System(OpsOptions(listen_address=self.ops_address))
            fabobs.ensure_enabled(provider=system.provider)
        self._register_health_checkers(system)
        addr = system.start()
        self.ops = system
        self.ops_address = addr
        logger.info("sidecar ops plane on %s (/metrics /healthz)", addr)
        return addr

    def _register_health_checkers(self, system) -> None:
        """The sidecar's /healthz surface (healthz checker contract:
        raise = unhealthy): batcher alive, registry warm, EC pool not in
        cooldown, listener accepting."""

        def batcher_check():
            if self._stopping:
                raise RuntimeError("sidecar is stopping")
            thread = getattr(self.batcher, "_thread", None)
            if getattr(self.batcher, "_stopped", False) or (
                thread is not None and not thread.is_alive()
            ):
                raise RuntimeError("verify batcher is stopped or dead")

        def registry_check():
            if self.warm_ladder != "off" and (
                self.registry is None or not self.registry.warmed
            ):
                raise RuntimeError(
                    f"bucket registry not warmed (ladder {self.warm_ladder})"
                )

        def pool_check():
            from fabric_tpu.crypto.bccsp import ec_pool_ready

            if not ec_pool_ready():
                raise RuntimeError(
                    "EC verify pool is in rebuild cooldown (serving inline)"
                )

        def listener_check():
            if self._listener is None or self._stopping:
                raise RuntimeError("sidecar listener is not accepting")
            if self._draining:
                # a draining sidecar flips unhealthy NOW so router
                # health probes evict it before the restart, not after
                raise RuntimeError("sidecar is draining (rolling restart)")

        system.register_checker("batcher", batcher_check)
        system.register_checker("registry", registry_check)
        system.register_checker("ec-pool", pool_check)
        system.register_checker("listener", listener_check)

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="serve-conn", daemon=True,
            )
            fabobs.obs_count("fabric_serve_connections_total", event="open")
            with self._conn_lock:
                if self._stopping:
                    conn.close()
                    return
                self._conns.append(conn)
                # register BEFORE start: a connection that EOFs
                # instantly would otherwise run its own cleanup-remove
                # before the append, leaking a dead Thread object in
                # the resident process forever
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            self._serve_conn_inner(conn)
        finally:
            # a resident process accumulates reconnecting clients for
            # its whole lifetime: drop this connection's bookkeeping as
            # it closes or _conns/_threads grow without bound
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass  # stop() already claimed it
                try:
                    self._threads.remove(threading.current_thread())
                except ValueError:
                    pass
            fabobs.obs_count("fabric_serve_connections_total", event="close")

    def _serve_conn_inner(self, conn: socket.socket) -> None:
        # one writer lock per connection: verify requests settle on
        # worker threads (the read loop keeps draining frames so a
        # client's pipelined requests coalesce in the batcher instead of
        # serializing behind each other), and interleaved sendall calls
        # on a stream socket would corrupt frames
        send_lock = threading.Lock()
        workers: List[threading.Thread] = []
        cancelled = _CancelSet()
        try:
            while True:
                frame = proto.recv_frame_ex(conn)
                if frame is None:
                    return
                opcode, req_id, payload, version = frame
                if opcode == proto.OP_CANCEL:
                    # fire-and-forget by contract: NO reply frame (a
                    # response here could collide with the cancelled
                    # request's own reply in the client's demux).  The
                    # worker that owns req_id sheds pre-dispatch or
                    # suppresses its reply; a cancel for an id that
                    # already settled ages out of the bounded set.
                    cancelled.add(req_id)
                elif opcode == proto.OP_PING:
                    self._send(
                        conn, proto.OP_PING, req_id,
                        proto.encode_verify_response(proto.ST_OK, mask=[]),
                        send_lock, version=version,
                    )
                elif opcode == proto.OP_STATS:
                    self._send(
                        conn, proto.OP_STATS, req_id,
                        json.dumps(self.describe(), sort_keys=True).encode(),
                        send_lock,
                        version=version,
                    )
                elif opcode == proto.OP_SHUTDOWN:
                    self._send(
                        conn, proto.OP_SHUTDOWN, req_id,
                        proto.encode_verify_response(proto.ST_OK, mask=[]),
                        send_lock, version=version,
                    )
                    # registered on _threads like every other serve
                    # thread: stop() skips joining current_thread, so
                    # the self-stop cannot deadlock on itself
                    st = threading.Thread(
                        target=self.stop, name="serve-shutdown", daemon=True
                    )
                    with self._conn_lock:
                        self._threads.append(st)
                    st.start()
                    return
                elif opcode == proto.OP_DRAIN:
                    # rolling restart: refuse new work NOW, settle the
                    # in-flight requests with real verdicts, then stop.
                    # The OK reply goes out before the drain so the
                    # restart orchestrator is not racing its own ack.
                    self._send(
                        conn, proto.OP_DRAIN, req_id,
                        proto.encode_verify_response(proto.ST_OK, mask=[]),
                        send_lock, version=version,
                    )
                    dt = threading.Thread(
                        target=self.drain_and_stop,
                        name="serve-drain", daemon=True,
                    )
                    with self._conn_lock:
                        self._threads.append(dt)
                    dt.start()
                    return
                elif opcode == proto.OP_VERIFY:
                    # concurrency is bounded by the batcher's admission
                    # control: a request only occupies its worker past
                    # decode if try_submit admitted its lanes
                    w = threading.Thread(
                        target=self._handle_verify,
                        args=(conn, req_id, payload, send_lock, version,
                              cancelled),
                        name="serve-verify", daemon=True,
                    )
                    w.start()
                    workers.append(w)
                    workers = [t for t in workers if t.is_alive()]
                else:
                    self._send(
                        conn, opcode, req_id,
                        proto.encode_verify_response(
                            proto.ST_ERROR,
                            message=f"unknown opcode {opcode}",
                        ),
                        send_lock, version=version,
                    )
        except proto.ProtocolError as exc:
            # a desynced STREAM is unusable (bad magic/oversized frame —
            # recv_frame cannot resync): answer if possible, close.
            # Payload-level decode failures never reach here; they are
            # answered ST_ERROR per request in _handle_verify.
            logger.warning("protocol error on %s: %s", self.address, exc)
            self._try_reply_error(conn, 0, exc, send_lock)
        except OSError:
            pass  # peer went away; nothing to answer
        finally:
            for w in workers:
                w.join(timeout=2.0)
            try:
                conn.close()
            except OSError:
                pass

    # -- the verify path ---------------------------------------------------
    def _handle_verify(
        self, conn, req_id: int, payload: bytes, send_lock=None,
        version: int = 1, cancelled: Optional[_CancelSet] = None,
    ) -> None:
        """Decode, class-admit, admit, launch, reply (on a per-request
        worker thread; replies may interleave out of order — the client
        demuxes by request id).  Every failure path answers the client
        with a non-OK status (the client's degrade path owns the mask
        then) — this function must never reply OK with verdicts it did
        not compute, and every shed is an explicit ST_BUSY frame (a
        cancelled request excepted: its client explicitly abandoned the
        reply, which is the one sanctioned silence)."""
        t0 = time.perf_counter()
        qos_class = proto.DEFAULT_QOS
        release_qos: Optional[Callable[[], None]] = None
        entered = False
        try:
            # chaos seam: an injected dispatch fault fails THIS request
            # with ST_ERROR before any batcher state is touched (keyed
            # only when the operator addressed this sidecar explicitly)
            fault_point("serve.dispatch", key=self.chaos_key)
            with fabobs.span("serve.decode", req_id=req_id):
                (keys, sigs, digests, qos_class, channel,
                 deadline_ms) = self._decode_lanes(payload, version)
            if self._stopping or self._draining:
                # draining: NEW work is refused here while in-flight
                # requests (already past this gate) settle with their
                # real verdicts below — the rolling-restart contract
                self.stats.stopping_reply()
                self._reply_status(
                    conn, req_id, proto.ST_STOPPING, send_lock=send_lock,
                    version=version,
                )
                return
            entered = self._enter_verify()
            if not entered:
                self.stats.stopping_reply()
                self._reply_status(
                    conn, req_id, proto.ST_STOPPING, send_lock=send_lock,
                    version=version,
                )
                return
            if cancelled is not None and cancelled.take(req_id):
                # the client abandoned this request before any batcher
                # state was touched: shed uncomputed, nothing to reply
                # (the one silence the protocol sanctions), no lanes to
                # release — the QoS ledger never saw the request
                self.stats.cancel(pre_dispatch=True)
                return
            if deadline_ms > 0:
                bucket_est = (
                    self.registry.bucket_for(len(keys))
                    if self.registry is not None else len(keys)
                )
                floor = self.stats.floor_s(bucket_est)
                if floor is not None and deadline_ms / 1000.0 < floor:
                    # the budget is smaller than the FASTEST this
                    # sidecar has ever served the bucket: provably
                    # unfinishable — shed as an explicit ST_BUSY so the
                    # client fails over/degrades NOW instead of paying
                    # the full service time for a verdict it will drop
                    self.stats.deadline_reject(qos_class)
                    self._reply_status(
                        conn, req_id, proto.ST_BUSY,
                        retry_after_ms=self.retry_after_ms(qos_class),
                        send_lock=send_lock, version=version,
                    )
                    return
            if not self.qos.try_acquire(qos_class, len(keys)):
                self.stats.reject(qos_class)
                self._reply_status(
                    conn, req_id, proto.ST_BUSY,
                    retry_after_ms=self.retry_after_ms(qos_class),
                    send_lock=send_lock, version=version,
                )
                return
            # the ledger mirrors the batcher's admission window exactly:
            # class lanes release when the dispatcher picks the request
            # up (on_dispatch), the same moment the batcher's own lane
            # permits release — one-shot so the failure-path release in
            # the finally block can never double-free
            release_qos = self._qos_release_once(qos_class, len(keys))
            resolver = self.batcher.try_submit(
                keys, sigs, digests, on_dispatch=release_qos,
                deadline_s=(
                    time.monotonic() + deadline_ms / 1000.0
                    if deadline_ms > 0 else None
                ),
            )
            if resolver is None:
                self.stats.reject(qos_class)
                self._reply_status(
                    conn, req_id, proto.ST_BUSY,
                    retry_after_ms=self.retry_after_ms(qos_class),
                    send_lock=send_lock, version=version,
                )
                return
            with fabobs.span(
                "serve.verify", req_id=req_id, lanes=len(keys),
                cls=proto.qos_name(qos_class), channel=channel,
            ):
                mask = resolver()
            if self._stopping:
                # the batcher may have settled this request fail-closed
                # during shutdown; an OK here could carry guessed
                # verdicts — tell the client to re-verify in-process
                self.stats.stopping_reply()
                self._reply_status(
                    conn, req_id, proto.ST_STOPPING, send_lock=send_lock,
                    version=version,
                )
                return
            if cancelled is not None and cancelled.take(req_id):
                # a cancel lost the race to the settlement: the verdict
                # was computed but the client stopped listening —
                # suppress the reply (the client's demux would drop it
                # anyway) and account the wasted work.  QoS lanes were
                # already released at dispatcher pickup; the one-shot
                # release makes the finally-block release a no-op, so a
                # cancel racing a settle can neither leak nor
                # double-release lanes.
                self.stats.cancel(pre_dispatch=False)
                return
            bucket = (
                self.registry.bucket_for(len(mask))
                if self.registry is not None
                else len(mask)
            )
            # record BEFORE the reply frame: any client that has seen
            # the OK must also see it in STATS (the chaos scorecard's
            # served_after_restart reads stats right after a reply —
            # recording after send made that a same-seed determinism
            # race).  The local-socket send itself is excluded from the
            # latency sample; it is microseconds against lane math.
            self.stats.record(
                len(mask), bucket, time.perf_counter() - t0, qos_class
            )
            self._send(
                conn, proto.OP_VERIFY, req_id,
                proto.encode_verify_response(proto.ST_OK, mask=mask),
                send_lock, version=version,
            )
        except Exception as exc:  # noqa: BLE001 - per-request fail-closed
            # includes a payload-level ProtocolError: recv_frame already
            # consumed the whole length-prefixed frame, so the stream is
            # still in sync — a malformed payload fails THIS request
            # with ST_ERROR, never the connection's other requests
            logger.warning("verify request failed (%s); replying ST_ERROR", exc)
            self.stats.error()
            self._try_reply_error(conn, req_id, exc, send_lock, version)
        finally:
            if release_qos is not None:
                # covers every path where the dispatcher never fired
                # the hook (batcher reject, exception); idempotent
                release_qos()
            if entered:
                self._exit_verify()

    def _qos_release_once(
        self, qos_class: int, lanes: int
    ) -> Callable[[], None]:
        """One-shot ledger release shared by the dispatch hook and the
        handler's failure paths (whichever fires first wins)."""
        state = {"done": False}
        state_lock = threading.Lock()

        def release() -> None:
            with state_lock:
                if state["done"]:
                    return
                state["done"] = True
            self.qos.release(qos_class, lanes)

        return release

    def _enter_verify(self) -> bool:
        """Count this worker into the drain barrier; False when the
        sidecar began draining while the worker was being scheduled."""
        with self._drain_cv:
            if self._draining or self._stopping:
                return False
            self._active_verifies += 1
            return True

    def _exit_verify(self) -> None:
        with self._drain_cv:
            self._active_verifies -= 1
            if self._active_verifies <= 0:
                self._drain_cv.notify_all()

    def _decode_lanes(self, payload: bytes, version: int = 1):
        """Wire lanes -> provider lanes.  A key that fails SEC1 import
        becomes None — the EC ladder verifies such lanes False, exactly
        like the in-process parse path (fail-closed, never an error that
        would take down the batch's good lanes)."""
        from fabric_tpu.common import p256
        from fabric_tpu.crypto.bccsp import ECDSAPublicKey

        (key_bytes, lanes, qos_class, channel,
         deadline_ms) = proto.decode_verify_request(payload, version)
        key_objs: List[Optional[ECDSAPublicKey]] = []
        for raw in key_bytes:
            try:
                x, y = p256.pubkey_from_bytes(raw)
                key_objs.append(ECDSAPublicKey(x, y))
            except Exception as exc:  # noqa: BLE001 - bad key: dead lane below
                logger.debug("unusable key in verify request (%s)", exc)
                key_objs.append(None)
        keys = [
            key_objs[idx] if idx != proto.NO_KEY else None
            for idx, _, _ in lanes
        ]
        sigs = [sig for _, sig, _ in lanes]
        digests = [d for _, _, d in lanes]
        return keys, sigs, digests, qos_class, channel, deadline_ms

    def retry_after_ms(self, qos_class: Optional[int] = None) -> int:
        """Admission-control hint: scale the base backoff by queue
        fill so a saturated sidecar pushes clients further away.  With
        a class, the CLASS's quota fill is the signal — a saturated
        bulk lane pushes bulk clients away without inflating the hint
        a high-priority client sees for its own idle quota."""
        fill = self.batcher.pending_lanes / max(self.max_pending_lanes, 1)
        if qos_class is not None:
            fill = max(fill, self.qos.fill(qos_class))
        return max(5, int(self.retry_after_base_ms * (1.0 + 3.0 * fill)))

    @staticmethod
    def _send(
        conn, opcode: int, req_id: int, payload: bytes, send_lock=None,
        version: int = proto.PROTOCOL_VERSION,
    ):
        """One frame out, serialized under the connection's writer lock
        when given (worker threads reply concurrently; interleaved
        sendall calls would corrupt the stream).  Replies echo the
        REQUEST frame's version so a v1 client never sees a v2 header
        its recv loop would refuse."""
        if send_lock is not None:
            with send_lock:
                proto.send_frame(sock=conn, opcode=opcode, req_id=req_id,
                                 payload=payload, version=version)
        else:
            proto.send_frame(sock=conn, opcode=opcode, req_id=req_id,
                             payload=payload, version=version)

    def _reply_status(
        self, conn, req_id: int, status: int, retry_after_ms: int = 0,
        send_lock=None, version: int = 1,
    ) -> None:
        reply = proto.encode_verify_response(
            status, message="", retry_after_ms=retry_after_ms
        )
        try:
            self._send(conn, proto.OP_VERIFY, req_id, reply, send_lock,
                       version=version)
        except OSError as exc:
            logger.warning("reply failed (%s); client will degrade", exc)

    def _try_reply_error(
        self, conn, req_id: int, exc: BaseException, send_lock=None,
        version: int = 1,
    ) -> None:
        reply = proto.encode_verify_response(
            proto.ST_ERROR, message=f"{type(exc).__name__}: {exc}"
        )
        try:
            self._send(conn, proto.OP_VERIFY, req_id, reply, send_lock,
                       version=version)
        except OSError as send_exc:
            logger.warning(
                "error reply failed (%s) after %s; client will degrade",
                send_exc, exc,
            )

    # -- introspection -----------------------------------------------------
    def describe(self) -> Dict:
        out = {
            "address": self.address,
            "engine": self.engine,
            "buckets": list(self.buckets),
            "max_pending_lanes": self.max_pending_lanes,
            "pending_lanes": self.batcher.pending_lanes,
            "launches": self.batcher.launches,
            "batched_lanes": self.batcher.lanes,
            "warm": self.warm_report,
            "stats": self.stats.summary(),
            "qos": self.qos.snapshot(),
            "stopping": self._stopping,
            "draining": self._draining,
            "ops_address": self.ops_address if self.ops is not None else None,
        }
        if self.registry is not None:
            out["registry"] = self.registry.stats()
        return out

    # -- drain (rolling restart) -------------------------------------------
    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Refuse NEW verify work (``ST_STOPPING``) while in-flight
        requests settle with their real computed verdicts; returns True
        when the last in-flight request settled inside the timeout.
        Unlike stop(), the batcher stays alive, so nothing settles
        fail-closed — a drained sidecar has answered every admitted
        request with the mask it actually computed (the rolling-restart
        bit-exactness contract)."""
        if timeout_s is None:
            timeout_s = self.drain_timeout_s
        with self._drain_cv:
            self._draining = True
        logger.info("sidecar on %s draining (timeout %.1fs)",
                    self.address, timeout_s)
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._drain_cv:
            while self._active_verifies > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logger.warning(
                        "drain timed out with %d verify worker(s) in "
                        "flight; stop() will settle them ST_STOPPING",
                        self._active_verifies,
                    )
                    return False
                self._drain_cv.wait(min(remaining, 0.2))
        return True

    def drain_and_stop(self) -> None:
        """The OP_DRAIN / SIGTERM path: settle in-flight, then exit."""
        self.drain()
        self.stop()

    # -- shutdown ----------------------------------------------------------
    def stop(self) -> None:
        """Idempotent: refuse new work, settle the batcher (fail-closed),
        close the socket front.  In-flight verify handlers observe
        ``_stopping`` and answer ST_STOPPING, never guessed verdicts."""
        with self._conn_lock:
            if self._stopping:
                return
            self._stopping = True
        if self.ops is not None:
            try:
                self.ops.stop()
            except Exception as exc:  # noqa: BLE001 - ops teardown best-effort
                logger.warning("ops server stop failed (%s)", exc)
        if self._listener is not None:
            # close() alone does NOT wake a thread blocked in accept()
            # (the syscall keeps blocking on the detached fd — every
            # stop used to eat the full 2s join timeout on the accept
            # thread, ~25s across the serve test suite): shutdown the
            # listener first, then poke it with a throwaway connect so
            # the accept loop observes the stop NOW on platforms where
            # shutdown on a listening socket is a no-op
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                family, target = parse_address(self.address)
                poke = socket.socket(family, socket.SOCK_STREAM)
                poke.settimeout(0.2)
                try:
                    poke.connect(target)
                except OSError:
                    pass
                finally:
                    poke.close()
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        self.batcher.stop()
        with self._conn_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        with self._conn_lock:
            threads = list(self._threads)
        for t in threads:
            if t is not threading.current_thread():
                try:
                    t.join(timeout=2.0)
                except RuntimeError:
                    pass  # registered but not yet started (append-before-start window)
        family, target = parse_address(self.address)
        if family == socket.AF_UNIX and self._started:
            try:
                os.unlink(target)
            except OSError:
                pass
        logger.info("sidecar on %s stopped", self.address)


# ---------------------------------------------------------------------------
# CLI entrypoint: python -m fabric_tpu.serve
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="fabric_tpu.serve",
        description="resident validation sidecar: warm bucketed "
        "executables + admission-controlled batch verify serving",
    )
    ap.add_argument(
        "--address",
        default=os.environ.get("FABRIC_TPU_SERVE_ADDR", "/tmp/fabserve.sock"),
        help="unix socket path (contains '/') or host:port",
    )
    ap.add_argument("--engine", default="auto", choices=ENGINES)
    ap.add_argument(
        "--buckets",
        default="",
        help="comma-separated lane bucket ladder (default: "
        + ",".join(str(b) for b in DEFAULT_BUCKETS) + ")",
    )
    ap.add_argument(
        "--warm", default="off", choices=WARM_LADDERS,
        help="jax bucket ladder to pre-warm: 'verify' = the real ECDSA "
        "limb kernel (minutes cold), 'demo' = the CI-able ops.bignum "
        "exponentiation ladder, 'off' = host warm-up only",
    )
    ap.add_argument(
        "--aot-dir", default="",
        help="directory for serialized AOT executables (warm restarts "
        "skip trace AND compile); empty = persistent compile cache only",
    )
    ap.add_argument("--max-pending-lanes", type=int, default=65536)
    ap.add_argument("--linger-ms", type=float, default=2.0)
    ap.add_argument(
        "--qos-shares", default="",
        help="per-class admission lane shares, e.g. "
        "'high=0.5,normal=0.35,bulk=0.15' (empty = defaults)",
    )
    ap.add_argument(
        "--drain-timeout-s", type=float, default=None,
        help="rolling-restart drain budget: how long SIGTERM/OP_DRAIN "
        "waits for in-flight requests to settle with real verdicts "
        "(default: FABRIC_TPU_SERVE_DRAIN_S or 5)",
    )
    ap.add_argument(
        "--ops-address", default=os.environ.get("FABRIC_TPU_OPS_ADDR", ""),
        help="mount the operations HTTP server (/metrics /healthz) on "
        "host:port ('127.0.0.1:0' = loopback ephemeral); empty = off",
    )
    args = ap.parse_args(argv)

    buckets = (
        tuple(int(b) for b in args.buckets.split(",") if b.strip())
        if args.buckets
        else DEFAULT_BUCKETS
    )
    from fabric_tpu.serve.qos import parse_shares

    qos_shares = parse_shares(args.qos_shares) if args.qos_shares else None
    drain_timeout_s = args.drain_timeout_s
    if drain_timeout_s is None:
        # shared env read discipline: a malformed value degrades the
        # knob to its default, never breaks the sidecar start
        raw = os.environ.get("FABRIC_TPU_SERVE_DRAIN_S", "")
        try:
            drain_timeout_s = float(raw) if raw else 5.0
        except ValueError:
            drain_timeout_s = 5.0
    server = SidecarServer(
        args.address,
        engine=args.engine,
        buckets=buckets,
        max_pending_lanes=args.max_pending_lanes,
        linger_s=args.linger_ms / 1000.0,
        warm_ladder=args.warm,
        aot_dir=args.aot_dir or None,
        ops_address=args.ops_address or None,
        qos_shares=qos_shares,
        drain_timeout_s=drain_timeout_s,
    )
    warm = server.warm()
    addr = server.start()
    # the READY line is the contract with scripts/serve_gate.sh,
    # scripts/obs_gate.sh (reads ops_address) and the warm-restart
    # test: one JSON line, stdout, after warm-up completes
    print(
        "SERVE_READY " + json.dumps(
            {
                "address": addr,
                "ops_address": server.ops_address
                if server.ops is not None else None,
                "warm": warm,
            },
            sort_keys=True,
        ),
        flush=True,
    )

    done = threading.Event()

    def _stop(signum, frame):  # noqa: ARG001 - signal signature
        done.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    try:
        while not done.is_set() and not server._stopping:
            done.wait(0.2)
    finally:
        if not server._stopping:
            # SIGTERM/SIGINT: drain first — in-flight requests settle
            # with real verdicts before the socket front goes away, so
            # a rolling restart under load never converts a computed
            # mask into a fail-closed settlement
            server.drain()
        server.stop()
        print(
            "SERVE_EXIT " + json.dumps(server.stats.summary(), sort_keys=True),
            flush=True,
        )
    return 0
