"""Client shim: the sidecar as a BCCSP provider rung.

``SidecarProvider`` speaks the serve protocol to a resident sidecar and
presents the standard Provider SPI, so ``peer/pipeline``, the
VerifyBatcher and the chaos harness route through the sidecar without
knowing it exists.  Select it like any other rung::

    provider_from_config({"Default": "SERVE", "SERVE": {"Address": addr}})
    FABRIC_TPU_SERVE_ADDR=/tmp/fabserve.sock   # default_provider() routes

Degrade contract (the mask discipline this file is in the fabflow MASK
tier for):

- ``ST_BUSY`` is admission control, not failure: the client retries on
  the shared ``common.retry`` pacing, honoring the sidecar's
  ``retry_after_ms`` hint, until the policy budget is spent.
- A dead/stopping sidecar (connect failure, mid-batch socket death,
  ST_STOPPING, budget exhausted) degrades to IN-PROCESS verification
  through the local probe ladder (device if present, else SW) — masks
  stay bit-exact, requests never fail just because the sidecar died.
- If even the in-process fallback throws, the batch's mask is all-False
  (fail-closed) — a lane is never guessed VALID on any failure path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.retry import Backoff, CooldownGate, RetryPolicy
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.protocol import parse_address

logger = must_get_logger("serve.client")

#: Admission-control pacing: capped exponential between BUSY retries,
#: bounded total wait before the client degrades to in-process verify.
BUSY_POLICY = RetryPolicy(
    base_s=0.01, multiplier=2.0, cap_s=0.5, deadline_s=10.0, max_attempts=16
)


class SidecarUnavailable(Exception):
    """The sidecar cannot serve this request (dead socket, stopping,
    protocol violation).  The provider degrades to in-process verify."""


class SidecarClient:
    """One pipelined connection to a sidecar.

    ``submit_verify`` writes the request frame and returns a token;
    ``await_verify`` demultiplexes response frames until the token's
    reply arrives — concurrent callers cooperate under the receive lock,
    and replies may arrive in ANY order (the server settles verify
    requests concurrently): each frame is matched to its waiter by
    request id.  Any socket failure fails every pending token with
    :class:`SidecarUnavailable`: the waiters' provider degrades
    in-process, so a sidecar killed mid-batch still yields bit-exact
    masks.
    """

    def __init__(
        self,
        address: str,
        connect_timeout_s: float = 5.0,
        request_timeout_s: float = 120.0,
    ):
        self.address = address
        self.connect_timeout_s = connect_timeout_s
        self.request_timeout_s = request_timeout_s
        # negotiated protocol revision: optimistic v2, latched down to
        # v1 when the connect-time hello learns the server refuses v2
        # frames (an old sidecar kills the stream on an unknown
        # version) — old servers keep serving new clients, minus QoS
        self.version = proto.PROTOCOL_VERSION
        self._sock = None
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._next_id = 0
        # token -> {"event": Event, "reply": payload|None, "error": exc|None}
        self._pending: Dict[int, Dict] = {}
        # failure-driven dial circuit: a permanently-dead TCP endpoint
        # (SYN blackholed) costs connect_timeout_s PER BATCH without it
        # — every commit would stall ~5s before degrading.  CooldownGate
        # carries its own leaf lock, so it is safe both under
        # _state_lock (ready) and outside it (record_* after a dial).
        self._dial_gate = CooldownGate()

    # -- connection --------------------------------------------------------
    def _connect(self):
        import socket as _socket

        family, target = parse_address(self.address)
        sock = _socket.socket(family, _socket.SOCK_STREAM)
        sock.settimeout(self.connect_timeout_s)
        sock.connect(target)
        sock.settimeout(self.request_timeout_s)
        return self._hello(sock, family, target)

    def _hello(self, sock, family, target):
        """Connect-time version negotiation: one PING at the preferred
        revision, raw on the fresh socket (nothing else is in flight
        yet).  The downgrade to v1 is EVIDENCE-BASED: only a reply that
        is not a PING ST_OK (the old server answers one ST_ERROR frame
        before closing) latches v1 — a silent EOF or reset (a sidecar
        restarting under the dial) is a transport failure that raises,
        so a transient crash window can never permanently strip the
        QoS class off a long-lived client.  A server refusing v1 too
        is genuinely unusable."""
        import socket as _socket

        while True:
            refusal = False
            try:
                proto.send_frame(sock, proto.OP_PING, 0, b"",
                                 version=self.version)
                reply = proto.recv_frame(sock)
                if reply is not None:
                    opcode, _rid, payload = reply
                    if opcode == proto.OP_PING:
                        status, _, _, _ = proto.decode_verify_response(
                            payload
                        )
                        if status == proto.ST_OK:
                            return sock
                    # it answered SOMETHING that is not an acceptance:
                    # the refusing server's one error frame
                    refusal = True
            except proto.ProtocolError:
                refusal = True  # unparseable reply: not our revision
            except OSError as exc:
                try:
                    sock.close()
                except OSError:
                    pass
                raise SidecarUnavailable(f"hello transport: {exc}") from exc
            try:
                sock.close()
            except OSError:
                pass
            if not refusal:
                # clean EOF, no refusal frame: the server went away
                # mid-hello — retry later at the SAME revision
                raise SidecarUnavailable("hello: stream closed")
            if self.version <= proto.MIN_PROTOCOL_VERSION:
                raise SidecarUnavailable(
                    f"hello refused at protocol v{self.version}"
                )
            with self._state_lock:
                self.version = proto.MIN_PROTOCOL_VERSION
            sock = _socket.socket(family, _socket.SOCK_STREAM)
            sock.settimeout(self.connect_timeout_s)
            sock.connect(target)
            sock.settimeout(self.request_timeout_s)

    def _ensure_sock(self):
        with self._state_lock:
            if self._sock is not None:
                return self._sock
            if not self._dial_gate.ready():
                raise SidecarUnavailable(
                    f"connect {self.address}: cooling down after "
                    "dial failure"
                )
        # dial OUTSIDE the state lock: a blackholed endpoint blocks in
        # connect() for connect_timeout_s, and close()/_fail_all/the
        # await_reply loop must not stall behind the dialer
        try:
            sock = self._connect()
        except (OSError, SidecarUnavailable) as exc:
            self._dial_gate.record_failure()
            raise SidecarUnavailable(
                f"connect {self.address}: {exc}"
            ) from exc
        self._dial_gate.record_success()
        with self._state_lock:
            if self._sock is None:
                self._sock = sock
                return sock
            winner = self._sock
        # a concurrent dialer won the install race: use its socket
        try:
            sock.close()
        except OSError:
            pass
        return winner

    def _fail_all(self, exc: Exception) -> None:
        """Socket death: every pending waiter learns, the connection is
        torn down (the next call reconnects)."""
        with self._state_lock:
            sock, self._sock = self._sock, None
            pending = list(self._pending.values())
            self._pending.clear()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for entry in pending:
            entry["error"] = SidecarUnavailable(str(exc))
            entry["event"].set()

    def close(self) -> None:
        self._fail_all(SidecarUnavailable("client closed"))

    # -- request plumbing --------------------------------------------------
    def submit(self, opcode: int, payload: bytes) -> int:
        """Send one frame; returns the token to await.  Raises
        SidecarUnavailable on any transport failure."""
        sock = self._ensure_sock()
        with self._send_lock:
            with self._state_lock:
                self._next_id = (self._next_id + 1) & 0xFFFFFFFF
                token = self._next_id
                self._pending[token] = {
                    "event": threading.Event(), "reply": None, "error": None,
                }
            try:
                proto.send_frame(sock, opcode, token, payload,
                                 version=self.version)
            except OSError as exc:
                self._fail_all(exc)
                raise SidecarUnavailable(f"send: {exc}") from exc
        return token

    def await_reply(self, token: int) -> bytes:
        """Block until the token's response payload arrives (cooperative
        demux: whichever waiter holds the recv lock reads frames and
        settles the tokens they answer)."""
        deadline = time.monotonic() + self.request_timeout_s
        while True:
            with self._state_lock:
                entry = self._pending.get(token)
            if entry is None:
                raise SidecarUnavailable("reply already consumed or failed")
            if entry["event"].is_set():
                with self._state_lock:
                    self._pending.pop(token, None)
                if entry["error"] is not None:
                    raise entry["error"]
                return entry["reply"]
            got_lock = self._recv_lock.acquire(timeout=0.1)
            if not got_lock:
                if time.monotonic() > deadline:
                    # give up on THIS token only: the demux holder is
                    # legitimately blocked on a slower request, and the
                    # connection is still healthy — tearing it down
                    # would discard the holder's nearly-done server-side
                    # work.  A late reply for this token is dropped by
                    # the holder's gave-up branch below.  (A truly dead
                    # sidecar is caught by the HOLDER's own socket
                    # timeout, which does fail all waiters.)
                    with self._state_lock:
                        self._pending.pop(token, None)
                    raise SidecarUnavailable("reply timeout")
                continue
            try:
                if entry["event"].is_set():
                    continue  # settled while we waited for the lock
                sock = self._sock
                if sock is None:
                    raise SidecarUnavailable("connection lost")
                try:
                    frame = proto.recv_frame(sock)
                except (OSError, proto.ProtocolError) as exc:
                    self._fail_all(exc)
                    raise SidecarUnavailable(f"recv: {exc}") from exc
                if frame is None:
                    self._fail_all(ConnectionError("sidecar closed stream"))
                    raise SidecarUnavailable("sidecar closed the stream")
                _opcode, rid, payload = frame
                with self._state_lock:
                    settled = self._pending.get(rid)
                if settled is not None:
                    settled["reply"] = payload
                    settled["event"].set()
                # else: reply for a token whose waiter gave up — drop
            finally:
                self._recv_lock.release()

    def request(self, opcode: int, payload: bytes = b"") -> bytes:
        return self.await_reply(self.submit(opcode, payload))

    def ensure_connected(self) -> None:
        """Dial (and version-hello) now if not connected.  Callers that
        encode version-dependent payloads use this to latch the
        negotiated revision BEFORE building the request body."""
        self._ensure_sock()

    # -- typed helpers -----------------------------------------------------
    def ping(self) -> bool:
        status, _, _, _ = proto.decode_verify_response(
            self.request(proto.OP_PING)
        )
        return status == proto.ST_OK

    def stats(self) -> Dict:
        import json

        return json.loads(self.request(proto.OP_STATS).decode())

    def shutdown(self) -> None:
        self.request(proto.OP_SHUTDOWN)


def encode_lanes(
    keys: Sequence, signatures: Sequence[bytes], digests: Sequence[bytes],
    qos_class: Optional[int] = proto.DEFAULT_QOS, channel: str = "",
) -> bytes:
    """Provider lanes -> wire payload, deduplicating repeated key
    objects (the MSP cache reuses them) into the frame's key table.  A
    key that cannot serialize maps to NO_KEY — the server verifies that
    lane False, same as the in-process parse path.  The default body is
    the protocol-rev-2 layout (QoS prefix, matching SidecarClient's
    default frame revision); pass ``qos_class=None`` for the v1 body a
    v1-latched connection must send."""
    from fabric_tpu.common import p256

    table: List[bytes] = []
    index_of: Dict[int, int] = {}
    lanes: List[Tuple[int, bytes, bytes]] = []
    for key, sig, digest in zip(keys, signatures, digests, strict=True):
        idx = proto.NO_KEY
        if key is not None:
            idx = index_of.get(id(key), -1)
            if idx < 0:
                try:
                    raw = p256.pubkey_to_bytes(key.point)
                except Exception as exc:  # noqa: BLE001 - bad key: dead lane
                    logger.debug("unserializable key (%s); lane fails", exc)
                    raw = None
                if raw is None:
                    idx = proto.NO_KEY
                else:
                    idx = len(table)
                    table.append(raw)
                    index_of[id(key)] = idx
        lanes.append((idx, bytes(sig), bytes(digest)))
    return proto.encode_verify_request(
        table, lanes, qos_class=qos_class, channel=channel
    )


class SidecarProvider:
    """BCCSP rung routing batch verification through a resident sidecar,
    degrading to the in-process SW provider when the sidecar cannot
    serve.  Single verify/sign/hash/key ops run in-process always — the
    sidecar exists for the batch plane, and interactive single calls
    must not inherit its failure modes."""

    def __init__(
        self,
        address: Optional[str] = None,
        fallback=None,
        busy_policy: RetryPolicy = BUSY_POLICY,
        sleeper: Callable[[float], None] = time.sleep,
        qos_class: Optional[int] = None,
        channel: str = "",
    ):
        address = address or os.environ.get("FABRIC_TPU_SERVE_ADDR", "")
        if not address:
            raise ValueError(
                "sidecar address required (FABRIC_TPU_SERVE_ADDR or "
                "BCCSP.SERVE.Address)"
            )
        self.client = SidecarClient(address)
        self.busy_policy = busy_policy
        self._sleeper = sleeper
        self._fallback = fallback
        self._fallback_lock = threading.Lock()
        self.degraded = False  # latched: any request served in-process
        self.busy_rejects = 0  # admission rejections observed
        # admission class for protocol rev 2: explicit class wins, else
        # the FABRIC_TPU_SERVE_QOS channel map, else the wire default
        self.channel = channel
        if qos_class is None:
            from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

            qos_class = class_for_channel(channel, qos_map_from_env())
        self.qos_class = qos_class

    def _encode(self, keys, signatures, digests) -> bytes:
        """Lane payload at the negotiated revision: the QoS prefix is
        only emitted once the client knows the server speaks v2."""
        if self.client.version >= 2:
            return encode_lanes(
                keys, signatures, digests,
                qos_class=self.qos_class, channel=self.channel,
            )
        return encode_lanes(keys, signatures, digests, qos_class=None)

    # -- in-process fallback ----------------------------------------------
    def fallback_provider(self):
        with self._fallback_lock:
            if self._fallback is None:
                # the device-probe ladder, not a hardcoded SW rung: an
                # accelerator-attached node whose sidecar dies (or whose
                # FABRIC_TPU_SERVE_ADDR went stale) keeps its device
                from fabric_tpu.crypto.bccsp import probe_provider

                self._fallback = probe_provider()
            return self._fallback

    def _degrade(self, keys, signatures, digests, why) -> List[bool]:
        """In-process verification when the sidecar cannot serve.  The
        mask stays bit-exact (same ladder semantics); only if the local
        path ALSO fails is the batch failed closed as all-False."""
        if not self.degraded:
            logger.warning(
                "sidecar %s unavailable (%s); degrading to in-process "
                "verification", self.client.address, why,
            )
            # the first degrade is the flight-recorder moment: dump what
            # led here (obs failures swallow; the mask path continues).
            # The counter sits in the same transition gate — the family
            # counts degrade TRANSITIONS like every other seam, not one
            # tick per batch served by a latched-degraded provider.
            fabobs.obs_count("fabric_degrade_total", seam="serve.client")
            fabobs.obs_trigger("serve.client_degraded")
        self.degraded = True
        try:
            mask = self.fallback_provider().batch_verify(
                keys, signatures, digests
            )
            return list(mask)
        except Exception as exc:  # noqa: BLE001 - double fault: fail closed
            logger.error(
                "in-process fallback failed too (%s): batch fails closed",
                exc,
            )
            return [False] * len(keys)

    # -- the remote verify loop -------------------------------------------
    def _verify_once(self, payload: bytes) -> Tuple[int, int, Optional[List[bool]], str]:
        return proto.decode_verify_response(
            self.client.request(proto.OP_VERIFY, payload)
        )

    def batch_verify(
        self, keys, signatures, digests
    ) -> List[bool]:
        n = len(keys)
        if n == 0:
            return []
        t0 = time.perf_counter()
        bo = Backoff(self.busy_policy, sleeper=self._sleeper)
        while True:
            try:
                # connect (and hello) BEFORE encoding: the QoS prefix
                # is only valid at the negotiated revision, and a retry
                # after a reconnect may have latched a different one
                self.client.ensure_connected()
                payload = self._encode(keys, signatures, digests)
                status, retry_ms, mask, message = self._verify_once(payload)
            except (SidecarUnavailable, proto.ProtocolError) as exc:
                # a reply body that decodes to garbage (version skew,
                # truncation) is as unusable as a dead socket: degrade,
                # never let the exception escape past the mask contract
                return self._degrade(keys, signatures, digests, exc)
            if status == proto.ST_OK:
                if mask is None or len(mask) != n:
                    # a length-skewed mask is a protocol violation; never
                    # stretch or truncate verdicts to fit
                    return self._degrade(
                        keys, signatures, digests,
                        f"mask length {0 if mask is None else len(mask)} != {n}",
                    )
                fabobs.obs_count("fabric_verify_lanes_total", n, rung="serve")
                fabobs.obs_observe(
                    "fabric_verify_seconds",
                    time.perf_counter() - t0, rung="serve",
                )
                return mask
            if status == proto.ST_BUSY:
                self.busy_rejects += 1  # GIL-atomic add, stats only
                delay = bo.next_delay()
                if delay is None:
                    return self._degrade(
                        keys, signatures, digests, "admission budget spent"
                    )
                bo.sleep()
                # honor the sidecar's patience hint, but clamp it to our
                # own policy cap: retry_after_ms is a u32 off the wire and
                # must never buy a server-controlled unbounded sleep
                hint_s = min(retry_ms / 1000.0, self.busy_policy.cap_s)
                if hint_s > delay:
                    self._sleeper(hint_s - delay)
                continue
            if status == proto.ST_ERROR:
                # transient per-request failure (injected fault, launch
                # error): bounded retry like BUSY, then degrade
                if bo.sleep():
                    continue
                return self._degrade(keys, signatures, digests, message)
            # ST_STOPPING or unknown status: the sidecar is going away
            return self._degrade(
                keys, signatures, digests, message or f"status {status}"
            )

    def batch_verify_async(self, keys, signatures, digests):
        """Pipelined dispatch: the request frame goes out NOW; the
        resolver demuxes the reply later (stage-A/B overlap through the
        socket).  Any failure at either end resolves through the same
        degrade ladder as the sync path."""
        n = len(keys)
        if n == 0:
            return list
        t0 = time.perf_counter()
        try:
            self.client.ensure_connected()
            payload = self._encode(keys, signatures, digests)
            token = self.client.submit(proto.OP_VERIFY, payload)
        except (proto.ProtocolError, SidecarUnavailable) as exc:
            why = exc

            def degraded_resolve() -> List[bool]:
                return self._degrade(keys, signatures, digests, why)

            return degraded_resolve

        def resolve() -> List[bool]:
            try:
                status, _, mask, _ = proto.decode_verify_response(
                    self.client.await_reply(token)
                )
            except (SidecarUnavailable, proto.ProtocolError) as exc:
                return self._degrade(keys, signatures, digests, exc)
            if status == proto.ST_OK and mask is not None and len(mask) == n:
                fabobs.obs_count("fabric_verify_lanes_total", n, rung="serve")
                fabobs.obs_observe(
                    "fabric_verify_seconds",
                    time.perf_counter() - t0, rung="serve",
                )
                return mask
            # BUSY/ERROR/STOPPING at resolve time: fall into the sync
            # path, which owns the retry/degrade ladder
            return self.batch_verify(keys, signatures, digests)

        return resolve

    # -- pass-through SPI --------------------------------------------------
    def verify(self, key, signature: bytes, digest: bytes) -> bool:
        return self.fallback_provider().verify(key, signature, digest)

    def batch_hash(self, msgs):
        return self.fallback_provider().batch_hash(msgs)

    def hash(self, msg: bytes) -> bytes:
        return self.fallback_provider().hash(msg)

    def key_import(self, raw: bytes):
        return self.fallback_provider().key_import(raw)

    def key_gen(self):
        return self.fallback_provider().key_gen()

    def sign(self, key, digest: bytes) -> bytes:
        return self.fallback_provider().sign(key, digest)

    def for_channel(self, channel_id: str) -> "SidecarProvider":
        """A channel-bound view of this provider: SAME pipelined
        connection and fallback, the CHANNEL's admission class (from
        the FABRIC_TPU_SERVE_QOS map) stamped on every batch — how a
        peer's per-channel validators become per-class traffic on a
        shared sidecar without a socket per channel."""
        import copy

        from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

        cls = class_for_channel(channel_id, qos_map_from_env())
        if channel_id == self.channel and cls == self.qos_class:
            return self
        bound = copy.copy(self)
        bound.channel = channel_id
        bound.qos_class = cls
        return bound

    def describe_backend(self) -> str:
        if self.degraded:
            return (
                f"serve-degraded({self.fallback_provider().describe_backend()})"
            )
        return f"serve:{self.client.address}"

    def stop(self) -> None:
        self.client.close()


def _provider_from_config(cfg: dict):
    """BCCSP factory hook: Default: SERVE -> SidecarProvider, or the
    multi-endpoint SidecarRouter when a fleet is configured
    (``SERVE.Endpoints`` or ``FABRIC_TPU_SERVE_ENDPOINTS``).  The SW
    sub-config's tier pins were already applied by the factory, so the
    in-process fallback rides the operator's chosen ladder."""
    serve_cfg = (cfg or {}).get("SERVE") or {}
    channel = serve_cfg.get("Channel") or ""
    qos_class = None
    qos_name = serve_cfg.get("QoS")
    if qos_name in proto.QOS_NAMES:
        qos_class = proto.QOS_NAMES.index(qos_name)
    endpoints = serve_cfg.get("Endpoints")
    if not endpoints:
        from fabric_tpu.serve.router import endpoints_from_env

        endpoints = endpoints_from_env() or None
    if endpoints:
        from fabric_tpu.serve.router import SidecarRouter

        return SidecarRouter(
            endpoints=endpoints, qos_class=qos_class, channel=channel
        )
    return SidecarProvider(
        address=serve_cfg.get("Address"), qos_class=qos_class, channel=channel
    )


# Dependency inversion keeps the layer map acyclic: serve (layer 6) may
# import crypto (layer 2), so the RUNG registers itself with the factory
# instead of the factory importing upward.
from fabric_tpu.crypto import factory as _factory  # noqa: E402

_factory.register_provider_factory("SERVE", _provider_from_config)
