"""Client shim: the sidecar as a BCCSP provider rung.

``SidecarProvider`` speaks the serve protocol to a resident sidecar and
presents the standard Provider SPI, so ``peer/pipeline``, the
VerifyBatcher and the chaos harness route through the sidecar without
knowing it exists.  Select it like any other rung::

    provider_from_config({"Default": "SERVE", "SERVE": {"Address": addr}})
    FABRIC_TPU_SERVE_ADDR=/tmp/fabserve.sock   # default_provider() routes

Degrade contract (the mask discipline this file is in the fabflow MASK
tier for):

- ``ST_BUSY`` is admission control, not failure: the client retries on
  the shared ``common.retry`` pacing, honoring the sidecar's
  ``retry_after_ms`` hint, until the policy budget is spent.
- A dead/stopping sidecar (connect failure, mid-batch socket death,
  ST_STOPPING, budget exhausted) degrades to IN-PROCESS verification
  through the local probe ladder (device if present, else SW) — masks
  stay bit-exact, requests never fail just because the sidecar died.
- If even the in-process fallback throws, the batch's mask is all-False
  (fail-closed) — a lane is never guessed VALID on any failure path.
"""

from __future__ import annotations

import os
import select
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.retry import Backoff, CooldownGate, RetryPolicy
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.protocol import parse_address

logger = must_get_logger("serve.client")

#: Admission-control pacing: capped exponential between BUSY retries,
#: bounded total wait before the client degrades to in-process verify.
BUSY_POLICY = RetryPolicy(
    base_s=0.01, multiplier=2.0, cap_s=0.5, deadline_s=10.0, max_attempts=16
)


class SidecarUnavailable(Exception):
    """The sidecar cannot serve this request (dead socket, stopping,
    protocol violation).  The provider degrades to in-process verify."""


class SidecarClient:
    """One pipelined connection to a sidecar.

    ``submit_verify`` writes the request frame and returns a token;
    ``await_verify`` demultiplexes response frames until the token's
    reply arrives — concurrent callers cooperate under the receive lock,
    and replies may arrive in ANY order (the server settles verify
    requests concurrently): each frame is matched to its waiter by
    request id.  Any socket failure fails every pending token with
    :class:`SidecarUnavailable`: the waiters' provider degrades
    in-process, so a sidecar killed mid-batch still yields bit-exact
    masks.
    """

    def __init__(
        self,
        address: str,
        connect_timeout_s: float = 5.0,
        request_timeout_s: float = 120.0,
    ):
        self.address = address
        self.connect_timeout_s = connect_timeout_s
        self.request_timeout_s = request_timeout_s
        # negotiated protocol revision: optimistic v2, latched down to
        # v1 when the connect-time hello learns the server refuses v2
        # frames (an old sidecar kills the stream on an unknown
        # version) — old servers keep serving new clients, minus QoS
        self.version = proto.PROTOCOL_VERSION
        self._sock = None
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._next_id = 0
        # token -> {"event": Event, "reply": payload|None, "error": exc|None}
        self._pending: Dict[int, Dict] = {}
        # failure-driven dial circuit: a permanently-dead TCP endpoint
        # (SYN blackholed) costs connect_timeout_s PER BATCH without it
        # — every commit would stall ~5s before degrading.  CooldownGate
        # carries its own leaf lock, so it is safe both under
        # _state_lock (ready) and outside it (record_* after a dial).
        self._dial_gate = CooldownGate()

    # -- connection --------------------------------------------------------
    def _connect(self):
        import socket as _socket

        family, target = parse_address(self.address)
        sock = _socket.socket(family, _socket.SOCK_STREAM)
        sock.settimeout(self.connect_timeout_s)
        sock.connect(target)
        # the hello stays on the CONNECT budget: it is one tiny
        # round-trip, and a gray endpoint that accepts but never
        # answers must stall a dialer (and the router's probe path)
        # for seconds, not the full request timeout
        return self._hello(sock, family, target)

    def _hello(self, sock, family, target):
        """Connect-time version negotiation: one PING at the preferred
        revision, raw on the fresh socket (nothing else is in flight
        yet).  The downgrade to v1 is EVIDENCE-BASED: only a reply that
        is not a PING ST_OK (the old server answers one ST_ERROR frame
        before closing) latches v1 — a silent EOF or reset (a sidecar
        restarting under the dial) is a transport failure that raises,
        so a transient crash window can never permanently strip the
        QoS class off a long-lived client.  A server refusing v1 too
        is genuinely unusable."""
        import socket as _socket

        while True:
            refusal = False
            try:
                proto.send_frame(sock, proto.OP_PING, 0, b"",
                                 version=self.version)
                reply = proto.recv_frame(sock)
                if reply is not None:
                    opcode, _rid, payload = reply
                    if opcode == proto.OP_PING:
                        status, _, _, _ = proto.decode_verify_response(
                            payload
                        )
                        if status == proto.ST_OK:
                            # negotiated: switch to the request budget
                            sock.settimeout(self.request_timeout_s)
                            return sock
                    # it answered SOMETHING that is not an acceptance:
                    # the refusing server's one error frame
                    refusal = True
            except proto.ProtocolError:
                refusal = True  # unparseable reply: not our revision
            except OSError as exc:
                try:
                    sock.close()
                except OSError:
                    pass
                raise SidecarUnavailable(f"hello transport: {exc}") from exc
            try:
                sock.close()
            except OSError:
                pass
            if not refusal:
                # clean EOF, no refusal frame: the server went away
                # mid-hello — retry later at the SAME revision
                raise SidecarUnavailable("hello: stream closed")
            if self.version <= proto.MIN_PROTOCOL_VERSION:
                raise SidecarUnavailable(
                    f"hello refused at protocol v{self.version}"
                )
            # step DOWN one revision per refusal (v3 -> v2 -> v1): a
            # v2 server costs a v3 client only the deadline/cancel
            # fields, never the QoS class it still understands
            with self._state_lock:
                self.version -= 1
            sock = _socket.socket(family, _socket.SOCK_STREAM)
            sock.settimeout(self.connect_timeout_s)
            sock.connect(target)

    def _ensure_sock(self):
        with self._state_lock:
            if self._sock is not None:
                return self._sock
            if not self._dial_gate.ready():
                raise SidecarUnavailable(
                    f"connect {self.address}: cooling down after "
                    "dial failure"
                )
        # dial OUTSIDE the state lock: a blackholed endpoint blocks in
        # connect() for connect_timeout_s, and close()/_fail_all/the
        # await_reply loop must not stall behind the dialer
        try:
            sock = self._connect()
        except (OSError, SidecarUnavailable) as exc:
            self._dial_gate.record_failure()
            raise SidecarUnavailable(
                f"connect {self.address}: {exc}"
            ) from exc
        self._dial_gate.record_success()
        with self._state_lock:
            if self._sock is None:
                self._sock = sock
                return sock
            winner = self._sock
        # a concurrent dialer won the install race: use its socket
        try:
            sock.close()
        except OSError:
            pass
        return winner

    def _fail_all(self, exc: Exception) -> None:
        """Socket death: every pending waiter learns, the connection is
        torn down (the next call reconnects)."""
        with self._state_lock:
            sock, self._sock = self._sock, None
            pending = list(self._pending.values())
            self._pending.clear()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for entry in pending:
            entry["error"] = SidecarUnavailable(str(exc))
            entry["event"].set()

    def close(self) -> None:
        self._fail_all(SidecarUnavailable("client closed"))

    # -- request plumbing --------------------------------------------------
    def submit(self, opcode: int, payload: bytes) -> int:
        """Send one frame; returns the token to await.  Raises
        SidecarUnavailable on any transport failure."""
        sock = self._ensure_sock()
        with self._send_lock:
            with self._state_lock:
                self._next_id = (self._next_id + 1) & 0xFFFFFFFF
                token = self._next_id
                self._pending[token] = {
                    "event": threading.Event(), "reply": None, "error": None,
                }
            try:
                proto.send_frame(sock, opcode, token, payload,
                                 version=self.version)
            except OSError as exc:
                self._fail_all(exc)
                raise SidecarUnavailable(f"send: {exc}") from exc
        return token

    def await_reply(
        self, token: int, timeout_s: Optional[float] = None
    ) -> bytes:
        """Block until the token's response payload arrives (cooperative
        demux: whichever waiter holds the recv lock reads frames and
        settles the tokens they answer).  ``timeout_s`` overrides the
        connection default — the wire-deadline discipline derives every
        per-hop wait from the request's remaining budget instead of one
        static constant."""
        if timeout_s is None:
            timeout_s = self.request_timeout_s
        out = self._demux_wait(
            token, time.monotonic() + max(0.0, timeout_s), give_up=True
        )
        assert out is not None  # give_up=True raises instead
        return out

    def poll_reply(self, token: int, wait_s: float) -> Optional[bytes]:
        """Bounded, NON-consuming wait: the token's payload if it
        settles within ``wait_s``, else None with the token still
        pending — the hedged-verification primitive (the router polls
        the primary for one hedge delay, then keeps both the primary
        and the hedge in flight, first verdict wins).  Raises
        SidecarUnavailable only on real transport failure."""
        return self._demux_wait(
            token, time.monotonic() + max(0.0, wait_s), give_up=False
        )

    def _demux_wait(
        self, token: int, deadline: float, give_up: bool
    ) -> Optional[bytes]:
        while True:
            with self._state_lock:
                entry = self._pending.get(token)
            if entry is None:
                raise SidecarUnavailable("reply already consumed or failed")
            if entry["event"].is_set():
                with self._state_lock:
                    self._pending.pop(token, None)
                if entry["error"] is not None:
                    raise entry["error"]
                return entry["reply"]
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if not give_up:
                    return None  # token stays pending (hedge polling)
                # give up on THIS token only: the connection may be
                # healthy and another waiter mid-demux — tearing it
                # down would discard that waiter's nearly-done
                # server-side work.  A late reply for this token is
                # dropped by the demux's gave-up branch below.
                with self._state_lock:
                    self._pending.pop(token, None)
                raise SidecarUnavailable("reply timeout")
            got_lock = self._recv_lock.acquire(timeout=min(remaining, 0.1))
            if not got_lock:
                continue
            try:
                if entry["event"].is_set():
                    continue  # settled while we waited for the lock
                sock = self._sock
                if sock is None:
                    raise SidecarUnavailable("connection lost")
                # select before recv: the demux holder must honor ITS
                # deadline without consuming partial frames — a recv
                # timeout mid-frame would desync the stream, a select
                # timeout touches nothing (how a tight budget walks
                # away from a dead-slow socket instead of parking on it)
                readable, _, _ = select.select(
                    [sock], [], [], min(remaining, 0.1)
                )
                if not readable:
                    continue
                try:
                    frame = proto.recv_frame(sock)
                except (OSError, proto.ProtocolError) as exc:
                    self._fail_all(exc)
                    raise SidecarUnavailable(f"recv: {exc}") from exc
                if frame is None:
                    self._fail_all(ConnectionError("sidecar closed stream"))
                    raise SidecarUnavailable("sidecar closed the stream")
                _opcode, rid, payload = frame
                with self._state_lock:
                    settled = self._pending.get(rid)
                if settled is not None:
                    settled["reply"] = payload
                    settled["event"].set()
                # else: reply for a token whose waiter gave up — drop
            finally:
                self._recv_lock.release()

    def cancel(self, token: int) -> None:
        """Best-effort abandon of an in-flight request: the local waiter
        state is dropped NOW (a late reply falls into the demux's
        gave-up branch), and on a rev-3 connection an OP_CANCEL frame
        tells the server to shed or stop replying.  The frame goes out
        even when the token is no longer pending — a reply-timeout
        give-up already popped it, and THAT is exactly the abandonment
        the server should hear about.  Never raises — a cancel races
        the settlement by design, and both orders are correct (the
        reply is either suppressed server-side or dropped
        client-side)."""
        with self._state_lock:
            self._pending.pop(token, None)
            sock = self._sock
        if sock is None or self.version < 3:
            return
        try:
            with self._send_lock:
                proto.send_frame(
                    sock, proto.OP_CANCEL, token, b"", version=self.version
                )
        except OSError as exc:
            logger.debug("cancel frame for token %d failed: %s", token, exc)

    def request(
        self, opcode: int, payload: bytes = b"",
        timeout_s: Optional[float] = None,
    ) -> bytes:
        return self.await_reply(self.submit(opcode, payload), timeout_s)

    def ensure_connected(self) -> None:
        """Dial (and version-hello) now if not connected.  Callers that
        encode version-dependent payloads use this to latch the
        negotiated revision BEFORE building the request body."""
        self._ensure_sock()

    # -- typed helpers -----------------------------------------------------
    def ping(self, timeout_s: Optional[float] = None) -> bool:
        """Liveness probe.  ``timeout_s`` matters: a health probe that
        rides the full request timeout lets one gray endpoint stall the
        whole probe path — the router passes its own short budget."""
        status, _, _, _ = proto.decode_verify_response(
            self.request(proto.OP_PING, timeout_s=timeout_s)
        )
        return status == proto.ST_OK

    def stats(self) -> Dict:
        import json

        return json.loads(self.request(proto.OP_STATS).decode())

    def shutdown(self) -> None:
        self.request(proto.OP_SHUTDOWN)


def deadline_ms_from_env() -> int:
    """``FABRIC_TPU_SERVE_DEADLINE_MS`` -> per-batch latency budget in
    milliseconds (0/unset = no deadline; the shared env read
    discipline: malformed values warn and disable the knob, never break
    a verify path)."""
    raw = os.environ.get("FABRIC_TPU_SERVE_DEADLINE_MS", "")
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        logger.warning(
            "FABRIC_TPU_SERVE_DEADLINE_MS=%r ignored (not an int)", raw
        )
        return 0


def encode_lanes(
    keys: Sequence, signatures: Sequence[bytes], digests: Sequence[bytes],
    qos_class: Optional[int] = proto.DEFAULT_QOS, channel: str = "",
    deadline_ms: Optional[int] = None,
    version: int = proto.PROTOCOL_VERSION,
) -> bytes:
    """Provider lanes -> wire payload, deduplicating repeated key
    objects (the MSP cache reuses them) into the frame's key table.  A
    key that cannot serialize maps to NO_KEY — the server verifies that
    lane False, same as the in-process parse path.  ``version`` picks
    the body layout, which MUST match the frame revision the payload
    rides on: the default is the current revision (deadline_ms 0 = no
    budget), and ``qos_class=None`` forces the v1 body a v1-latched
    connection must send."""
    from fabric_tpu.common import p256

    table: List[bytes] = []
    index_of: Dict[int, int] = {}
    lanes: List[Tuple[int, bytes, bytes]] = []
    for key, sig, digest in zip(keys, signatures, digests, strict=True):
        idx = proto.NO_KEY
        if key is not None:
            idx = index_of.get(id(key), -1)
            if idx < 0:
                try:
                    raw = p256.pubkey_to_bytes(key.point)
                except Exception as exc:  # noqa: BLE001 - bad key: dead lane
                    logger.debug("unserializable key (%s); lane fails", exc)
                    raw = None
                if raw is None:
                    idx = proto.NO_KEY
                else:
                    idx = len(table)
                    table.append(raw)
                    index_of[id(key)] = idx
        lanes.append((idx, bytes(sig), bytes(digest)))
    if qos_class is None:
        version = 1  # explicit v1-body request (legacy calling style)
    return proto.encode_verify_request(
        table, lanes,
        qos_class=qos_class if version >= 2 else None,
        channel=channel,
        deadline_ms=(
            (deadline_ms if deadline_ms is not None else 0)
            if version >= 3 else None
        ),
    )


class SidecarProvider:
    """BCCSP rung routing batch verification through a resident sidecar,
    degrading to the in-process SW provider when the sidecar cannot
    serve.  Single verify/sign/hash/key ops run in-process always — the
    sidecar exists for the batch plane, and interactive single calls
    must not inherit its failure modes."""

    def __init__(
        self,
        address: Optional[str] = None,
        fallback=None,
        busy_policy: RetryPolicy = BUSY_POLICY,
        sleeper: Callable[[float], None] = time.sleep,
        qos_class: Optional[int] = None,
        channel: str = "",
        deadline_ms: Optional[int] = None,
    ):
        address = address or os.environ.get("FABRIC_TPU_SERVE_ADDR", "")
        if not address:
            raise ValueError(
                "sidecar address required (FABRIC_TPU_SERVE_ADDR or "
                "BCCSP.SERVE.Address)"
            )
        self.client = SidecarClient(address)
        self.busy_policy = busy_policy
        self._sleeper = sleeper
        self._fallback = fallback
        self._fallback_lock = threading.Lock()
        self.degraded = False  # latched: any request served in-process
        self.busy_rejects = 0  # admission rejections observed
        self.deadline_expired = 0  # budgets that ran out before a verdict
        # per-batch latency budget (wire deadline, protocol rev 3):
        # every per-hop wait — reply wait, busy-retry pacing — derives
        # from the remaining budget; 0 = no deadline (legacy behavior)
        self.deadline_ms = (
            deadline_ms if deadline_ms is not None else deadline_ms_from_env()
        )
        # admission class for protocol rev 2: explicit class wins, else
        # the FABRIC_TPU_SERVE_QOS channel map, else the wire default
        self.channel = channel
        if qos_class is None:
            from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

            qos_class = class_for_channel(channel, qos_map_from_env())
        self.qos_class = qos_class

    def _encode(
        self, keys, signatures, digests,
        remaining_s: Optional[float] = None,
    ) -> bytes:
        """Lane payload at the negotiated revision: the QoS prefix is
        only emitted once the client knows the server speaks v2, the
        deadline field once it speaks v3 (carrying the budget REMAINING
        at encode time — floored at 1ms so a nearly-spent budget never
        decodes as 'no deadline' — or 0 when no budget is set)."""
        return encode_lanes(
            keys, signatures, digests,
            qos_class=self.qos_class, channel=self.channel,
            deadline_ms=(
                max(1, int(remaining_s * 1000.0))
                if remaining_s is not None else 0
            ),
            version=self.client.version,
        )

    def _deadline(self) -> Optional[float]:
        """Absolute monotonic deadline for a batch entering now, or
        None when no budget is configured."""
        if not self.deadline_ms:
            return None
        return time.monotonic() + self.deadline_ms / 1000.0

    def _expire(self, keys, signatures, digests, why: str) -> List[bool]:
        """Budget ran out: hand the batch back to the in-process ladder
        NOW instead of parking on a dead-slow socket (the mask stays
        bit-exact through the same degrade path)."""
        self.deadline_expired += 1  # GIL-atomic add, stats only
        fabobs.obs_count(
            "fabric_serve_deadline_expired_total", seam="serve.client"
        )
        return self._degrade(keys, signatures, digests, why)

    # -- in-process fallback ----------------------------------------------
    def fallback_provider(self):
        with self._fallback_lock:
            if self._fallback is None:
                # the device-probe ladder, not a hardcoded SW rung: an
                # accelerator-attached node whose sidecar dies (or whose
                # FABRIC_TPU_SERVE_ADDR went stale) keeps its device
                from fabric_tpu.crypto.bccsp import probe_provider

                self._fallback = probe_provider()
            return self._fallback

    def _degrade(self, keys, signatures, digests, why) -> List[bool]:
        """In-process verification when the sidecar cannot serve.  The
        mask stays bit-exact (same ladder semantics); only if the local
        path ALSO fails is the batch failed closed as all-False."""
        if not self.degraded:
            logger.warning(
                "sidecar %s unavailable (%s); degrading to in-process "
                "verification", self.client.address, why,
            )
            # the first degrade is the flight-recorder moment: dump what
            # led here (obs failures swallow; the mask path continues).
            # The counter sits in the same transition gate — the family
            # counts degrade TRANSITIONS like every other seam, not one
            # tick per batch served by a latched-degraded provider.
            fabobs.obs_count("fabric_degrade_total", seam="serve.client")
            fabobs.obs_trigger("serve.client_degraded")
        self.degraded = True
        try:
            mask = self.fallback_provider().batch_verify(
                keys, signatures, digests
            )
            return list(mask)
        except Exception as exc:  # noqa: BLE001 - double fault: fail closed
            logger.error(
                "in-process fallback failed too (%s): batch fails closed",
                exc,
            )
            return [False] * len(keys)

    # -- the remote verify loop -------------------------------------------
    def _verify_once(
        self, payload: bytes, timeout_s: Optional[float] = None
    ) -> Tuple[int, int, Optional[List[bool]], str]:
        token = self.client.submit(proto.OP_VERIFY, payload)
        try:
            return proto.decode_verify_response(
                self.client.await_reply(token, timeout_s)
            )
        except SidecarUnavailable:
            # abandoning the wait (budget/timeout) must TELL the
            # server: an uncancelled tight-deadline batch would make
            # the slow sidecar compute a verdict nobody will read —
            # exactly the capacity OP_CANCEL exists to reclaim
            self.client.cancel(token)
            raise

    def batch_verify(
        self, keys, signatures, digests
    ) -> List[bool]:
        return self._batch_verify(keys, signatures, digests,  # fabdet: disable=wallclock-in-det  # wire deadline budget: deadline_ms carries the budget REMAINING at encode time — a semantically time-derived protocol field (masks, not deadlines, are the replay contract)
                                  self._deadline())

    def _batch_verify(
        self, keys, signatures, digests, deadline: Optional[float]
    ) -> List[bool]:
        """The verify loop against an ALREADY-STARTED budget: the async
        resolver re-enters here with its original deadline, so a
        busy/error resolve can never restart the per-batch clock."""
        n = len(keys)
        if n == 0:
            return []
        t0 = time.perf_counter()
        bo = Backoff(self.busy_policy, sleeper=self._sleeper)
        while True:
            remaining: Optional[float] = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self._expire(
                        keys, signatures, digests, "deadline budget expired"
                    )
            try:
                # connect (and hello) BEFORE encoding: the QoS prefix
                # is only valid at the negotiated revision, and a retry
                # after a reconnect may have latched a different one
                self.client.ensure_connected()
                if deadline is not None:
                    # re-derive AFTER the dial: a reconnect can eat
                    # seconds, and both the reply wait and the budget
                    # advertised on the wire must reflect what is
                    # genuinely left, not the loop-top snapshot
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return self._expire(
                            keys, signatures, digests,
                            "deadline expired during connect",
                        )
                payload = self._encode(keys, signatures, digests, remaining)  # fabdet: disable=wallclock-in-det  # remaining-budget recompute before re-encode: the deadline_ms wire field is semantically time-derived by contract (masks are the det surface)
                status, retry_ms, mask, message = self._verify_once(
                    payload, remaining
                )
            except (SidecarUnavailable, proto.ProtocolError) as exc:
                if deadline is not None and time.monotonic() >= deadline:
                    # the BUDGET, not the transport, gave out: the
                    # reply wait was derived from the remaining budget,
                    # and its expiry hands the batch back (failover/
                    # degrade) instead of parking on a dead-slow socket
                    return self._expire(keys, signatures, digests, exc)
                # a reply body that decodes to garbage (version skew,
                # truncation) is as unusable as a dead socket: degrade,
                # never let the exception escape past the mask contract
                return self._degrade(keys, signatures, digests, exc)
            if status == proto.ST_OK:
                if mask is None or len(mask) != n:
                    # a length-skewed mask is a protocol violation; never
                    # stretch or truncate verdicts to fit
                    return self._degrade(
                        keys, signatures, digests,
                        f"mask length {0 if mask is None else len(mask)} != {n}",
                    )
                fabobs.obs_count("fabric_verify_lanes_total", n, rung="serve")
                fabobs.obs_observe(
                    "fabric_verify_seconds",
                    time.perf_counter() - t0, rung="serve",
                )
                return mask
            if status == proto.ST_BUSY:
                self.busy_rejects += 1  # GIL-atomic add, stats only
                delay = bo.next_delay()
                if delay is None:
                    return self._degrade(
                        keys, signatures, digests, "admission budget spent"
                    )
                if deadline is not None:
                    # the BUSY pacing budget is capped by the request's
                    # REMAINING wire deadline: a tight-deadline batch
                    # fails over to the in-process ladder instead of
                    # sleeping its whole budget away in admission retry
                    remaining = deadline - time.monotonic()
                    if delay >= remaining:
                        return self._expire(
                            keys, signatures, digests,
                            "deadline expired during admission backoff",
                        )
                bo.sleep()
                # honor the sidecar's patience hint, but clamp it to our
                # own policy cap: retry_after_ms is a u32 off the wire and
                # must never buy a server-controlled unbounded sleep —
                # and never more of the remaining deadline than exists
                hint_s = min(retry_ms / 1000.0, self.busy_policy.cap_s)
                if deadline is not None:
                    hint_s = min(
                        hint_s, max(0.0, deadline - time.monotonic())
                    )
                if hint_s > delay:
                    self._sleeper(hint_s - delay)
                continue
            if status == proto.ST_ERROR:
                # transient per-request failure (injected fault, launch
                # error): bounded retry like BUSY, then degrade — the
                # same remaining-budget cap as the BUSY leg
                delay = bo.next_delay()
                if delay is not None and deadline is not None:
                    remaining = deadline - time.monotonic()
                    if delay >= remaining:
                        return self._expire(
                            keys, signatures, digests,
                            "deadline expired during error backoff",
                        )
                if bo.sleep():
                    continue
                return self._degrade(keys, signatures, digests, message)
            # ST_STOPPING or unknown status: the sidecar is going away
            return self._degrade(
                keys, signatures, digests, message or f"status {status}"
            )

    def batch_verify_async(self, keys, signatures, digests):
        """Pipelined dispatch: the request frame goes out NOW; the
        resolver demuxes the reply later (stage-A/B overlap through the
        socket).  Any failure at either end resolves through the same
        degrade ladder as the sync path."""
        n = len(keys)
        if n == 0:
            return list
        t0 = time.perf_counter()
        deadline = self._deadline()
        try:
            self.client.ensure_connected()
            payload = self._encode(  # fabdet: disable=wallclock-in-det  # async-submit remaining budget: deadline_ms is a semantically time-derived wire field by contract (masks are the det surface)
                keys, signatures, digests,
                None if deadline is None else deadline - time.monotonic(),
            )
            token = self.client.submit(proto.OP_VERIFY, payload)
        except (proto.ProtocolError, SidecarUnavailable) as exc:
            why = exc

            def degraded_resolve() -> List[bool]:
                return self._degrade(keys, signatures, digests, why)

            return degraded_resolve

        def resolve() -> List[bool]:
            timeout_s: Optional[float] = None
            if deadline is not None:
                timeout_s = deadline - time.monotonic()
                if timeout_s <= 0:
                    self.client.cancel(token)
                    return self._expire(
                        keys, signatures, digests,
                        "deadline expired before resolve",
                    )
            try:
                status, _, mask, _ = proto.decode_verify_response(
                    self.client.await_reply(token, timeout_s)
                )
            except (SidecarUnavailable, proto.ProtocolError) as exc:
                if deadline is not None and time.monotonic() >= deadline:
                    # the budget, not the transport, gave out: the
                    # batch is handed back to the in-process ladder
                    # and a late reply is dropped by the demux
                    return self._expire(keys, signatures, digests, exc)
                return self._degrade(keys, signatures, digests, exc)
            if status == proto.ST_OK and mask is not None and len(mask) == n:
                fabobs.obs_count("fabric_verify_lanes_total", n, rung="serve")
                fabobs.obs_observe(
                    "fabric_verify_seconds",
                    time.perf_counter() - t0, rung="serve",
                )
                return mask
            # BUSY/ERROR/STOPPING at resolve time: fall into the sync
            # path, which owns the retry/degrade ladder — on the
            # ORIGINAL budget, never a fresh one
            return self._batch_verify(keys, signatures, digests, deadline)

        return resolve

    # -- pass-through SPI --------------------------------------------------
    def verify(self, key, signature: bytes, digest: bytes) -> bool:
        return self.fallback_provider().verify(key, signature, digest)

    def batch_hash(self, msgs):
        return self.fallback_provider().batch_hash(msgs)

    def hash(self, msg: bytes) -> bytes:
        return self.fallback_provider().hash(msg)

    def key_import(self, raw: bytes):
        return self.fallback_provider().key_import(raw)

    def key_gen(self):
        return self.fallback_provider().key_gen()

    def sign(self, key, digest: bytes) -> bytes:
        return self.fallback_provider().sign(key, digest)

    def for_channel(self, channel_id: str) -> "SidecarProvider":
        """A channel-bound view of this provider: SAME pipelined
        connection and fallback, the CHANNEL's admission class (from
        the FABRIC_TPU_SERVE_QOS map) stamped on every batch — how a
        peer's per-channel validators become per-class traffic on a
        shared sidecar without a socket per channel."""
        import copy

        from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

        cls = class_for_channel(channel_id, qos_map_from_env())
        if channel_id == self.channel and cls == self.qos_class:
            return self
        bound = copy.copy(self)
        bound.channel = channel_id
        bound.qos_class = cls
        return bound

    def describe_backend(self) -> str:
        if self.degraded:
            return (
                f"serve-degraded({self.fallback_provider().describe_backend()})"
            )
        return f"serve:{self.client.address}"

    def stop(self) -> None:
        self.client.close()


def _provider_from_config(cfg: dict):
    """BCCSP factory hook: Default: SERVE -> SidecarProvider, or the
    multi-endpoint SidecarRouter when a fleet is configured
    (``SERVE.Endpoints`` or ``FABRIC_TPU_SERVE_ENDPOINTS``).  The SW
    sub-config's tier pins were already applied by the factory, so the
    in-process fallback rides the operator's chosen ladder."""
    serve_cfg = (cfg or {}).get("SERVE") or {}
    channel = serve_cfg.get("Channel") or ""
    qos_class = None
    qos_name = serve_cfg.get("QoS")
    if qos_name in proto.QOS_NAMES:
        qos_class = proto.QOS_NAMES.index(qos_name)
    endpoints = serve_cfg.get("Endpoints")
    if not endpoints:
        from fabric_tpu.serve.router import endpoints_from_env

        endpoints = endpoints_from_env() or None
    if endpoints:
        from fabric_tpu.serve.router import SidecarRouter

        return SidecarRouter(
            endpoints=endpoints, qos_class=qos_class, channel=channel
        )
    return SidecarProvider(
        address=serve_cfg.get("Address"), qos_class=qos_class, channel=channel
    )


# Dependency inversion keeps the layer map acyclic: serve (layer 6) may
# import crypto (layer 2), so the RUNG registers itself with the factory
# instead of the factory importing upward.
from fabric_tpu.crypto import factory as _factory  # noqa: E402

_factory.register_provider_factory("SERVE", _provider_from_config)
