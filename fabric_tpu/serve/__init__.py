"""Resident validation sidecar (the ROADMAP "resident validation
service + AOT/compile-cache runtime" subsystem).

- :mod:`fabric_tpu.serve.protocol` — length-prefixed local socket
  framing (VERIFY/PING/STATS/SHUTDOWN) with explicit admission-control
  statuses (ST_BUSY + retry_after_ms).
- :mod:`fabric_tpu.serve.registry` — bucketed program registry: warm
  AOT executables per lane-bucket shape, with cold/cache/AOT warm-start
  accounting.
- :mod:`fabric_tpu.serve.server` — the sidecar process: owns the verify
  backends for its lifetime, fronts them with the VerifyBatcher's
  bounded-lane admission, serves batches over the socket.
- :mod:`fabric_tpu.serve.client` — the BCCSP rung: SidecarProvider
  routes batch verification through the sidecar and degrades to
  in-process verification (fail-closed masks) when it dies.
- :mod:`fabric_tpu.serve.qos` — per-class admission budgets (protocol
  rev 2): weighted lane quotas with work-conserving borrowing, so a
  shared sidecar sheds priority-aware.
- :mod:`fabric_tpu.serve.router` — the fleet rung: bucket-aware load
  balancing across N sidecar endpoints with health-probe eviction,
  re-verify-on-kill failover and rolling-restart support.

Import the submodules directly; this package namespace stays empty so
importing it costs nothing in jax-free processes.
"""
