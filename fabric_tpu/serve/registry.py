"""Bucketed program registry: warm AOT executables per lane-bucket shape.

The ROADMAP's "AOT-compiled bucketed program variants" piece: a resident
sidecar owns ONE registry per device program family, pre-warms an
executable for every bucket on the fixed ladder at startup, and serves
steady-state requests from the warm table — a request can pick a bucket
and dispatch without ever touching ``jax.jit`` again, so no re-trace and
no recompile can hide in the hot path.

Warm-start discipline (three rungs, best to worst):

1. **AOT artifact** (``jax.experimental.serialize_executable``): the
   compiled executable itself, pickled next to the compile cache.  A
   warm restart deserializes it — no trace, no XLA compile at all.
2. **Persistent compile cache** (``utils.jaxcache``): the trace is
   re-paid but the XLA compile is served from ``.jax_cache``.
3. **Cold**: trace + full XLA compile (the 20+-minute pairing
   differentials of NOTES_BUILD live here — exactly what a resident
   process amortizes away).

Every rung is accounted per bucket (``stats()``): compile wall ms,
whether the AOT artifact hit, how many XLA compile events fired — the
numbers bench.py records as ``configs.serve`` and the warm-restart test
asserts on.

The registry is engine-generic: production wires the ECDSA limb kernel
(``ops.p256_kernel.verify_batch_device``); the CI-able ladder wires
:func:`demo_limb_program` (a real ``ops.bignum`` Montgomery
exponentiation — the same limb code path, a graph small enough to
compile in seconds on the 2-vCPU gate box).

jax is imported lazily and only inside methods — importing this module
costs nothing in jax-free processes (fablint module-import discipline).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.common.flogging import must_get_logger

logger = must_get_logger("serve.registry")

#: The default lane-bucket ladder — the ``tpu_provider._BUCKETS``
#: discipline (a request is padded up to the smallest bucket that fits,
#: so the jitted program's shape set is closed).
DEFAULT_BUCKETS = (128, 256, 512, 1024, 2048, 4096, 8192, 16384)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest ladder bucket >= n; oversize rounds up to a multiple of
    the top bucket (the tpu_provider._bucket discipline)."""
    for b in buckets:
        if n <= b:
            return b
    top = buckets[-1]
    return ((n + top - 1) // top) * top


#: Monotonic per-process sequence for AOT-compile module names (see
#: :meth:`BucketProgramRegistry.for_jax_program`): uniqueness is what
#: guarantees the serialized artifact carries its own object code.
_AOT_SEQ = iter(range(1, 1 << 30))

#: The AOT-artifact compile flips the PROCESS-GLOBAL
#: ``jax_enable_compilation_cache`` flag around one compile.  Two
#: registries warming concurrently (serve_flap runs two sidecars in one
#: process) could interleave read-prev/set-False and restore a stale
#: ``False`` — permanently disabling the persistent cache for every
#: later compile in the process.  All flip/restore windows serialize
#: under this lock.
_AOT_COMPILE_LOCK = threading.Lock()


class _CompileCounters:
    """Process-wide jax compile/cache-event accounting.

    Counts ``/jax/...`` monitoring events whose name marks a backend
    compile or a persistent-cache hit.  One listener for the process
    (jax's listener list only grows); readers snapshot-and-diff."""

    _lock = threading.Lock()
    _installed = False
    compiles = 0
    cache_hits = 0

    @classmethod
    def install(cls) -> None:
        with cls._lock:
            if cls._installed:
                return
            cls._installed = True
        import jax

        def _on_event(event: str, **kwargs) -> None:
            # '/jax/compilation_cache/cache_hits' fires per persistent-
            # cache hit; backend_compile duration events fire per real
            # XLA compile.  Counter writes are GIL-atomic int adds.
            if "cache_hit" in event:
                cls.cache_hits += 1  # GIL-atomic int add, monotonic counter

        def _on_duration(event: str, duration: float, **kwargs) -> None:
            if "backend_compile" in event:
                cls.compiles += 1  # GIL-atomic int add, monotonic counter

        jax.monitoring.register_event_listener(_on_event)
        jax.monitoring.register_event_duration_secs_listener(_on_duration)

    @classmethod
    def snapshot(cls) -> Tuple[int, int]:
        return cls.compiles, cls.cache_hits


class BucketProgramRegistry:
    """Warm table of compiled executables keyed by lane bucket.

    ``builder(bucket)`` returns ``(callable, meta)`` — the warm
    executable for that bucket plus accounting metadata.  The default
    jax builder path is :meth:`for_jax_program`; a host engine that has
    nothing to compile can still use the registry with a trivial builder
    so warm accounting stays uniform.
    """

    def __init__(
        self,
        buckets: Sequence[int],
        builder: Callable[[int], Tuple[Callable, Dict]],
        label: str = "program",
    ):
        if not buckets or list(buckets) != sorted(set(int(b) for b in buckets)):
            raise ValueError(f"bucket ladder must be sorted unique: {buckets!r}")
        self.buckets = tuple(int(b) for b in buckets)
        self.builder = builder
        self.label = label
        self._programs: Dict[int, Callable] = {}
        self._lock = threading.Lock()
        self.warm_report: Dict[int, Dict] = {}
        self.warmed = False

    def bucket_for(self, n: int) -> int:
        return bucket_for(n, self.buckets)

    def warm(self) -> Dict[int, Dict]:
        """Build every bucket's executable, recording per-bucket wall ms
        and the compile/cache counters the build moved.  Idempotent."""
        with self._lock:
            if self.warmed:
                return self.warm_report
            for b in self.buckets:
                c0, h0 = _CompileCounters.snapshot()
                t0 = time.perf_counter()
                program, meta = self.builder(b)
                wall_ms = (time.perf_counter() - t0) * 1000.0
                c1, h1 = _CompileCounters.snapshot()
                self._programs[b] = program
                report = {
                    "warm_ms": round(wall_ms, 1),
                    "xla_compiles": c1 - c0,
                    "cache_hits": h1 - h0,
                }
                report.update(meta)
                self.warm_report[b] = report
                logger.info(
                    "%s bucket %d warm in %.0fms (%s)",
                    self.label, b, wall_ms,
                    "aot" if meta.get("aot_hit") else
                    ("cache" if h1 > h0 else "cold"),
                )
            self.warmed = True
            return self.warm_report

    def program_for(self, n: int) -> Tuple[int, Callable]:
        """(bucket, warm executable) for an n-lane request.  Raises
        KeyError when the bucket was never warmed — steady state must
        not compile, so a missing bucket is a caller bug, not a trigger
        for a hidden jit."""
        b = self.bucket_for(n)
        with self._lock:
            program = self._programs.get(b)
        if program is None:
            raise KeyError(
                f"bucket {b} not warmed for {self.label} "
                f"(ladder {self.buckets})"
            )
        return b, program

    def stats(self) -> Dict:
        with self._lock:
            report = {str(k): dict(v) for k, v in self.warm_report.items()}
        compiles, hits = _CompileCounters.snapshot()
        return {
            "label": self.label,
            "buckets": list(self.buckets),
            "warmed": self.warmed,
            "per_bucket": report,
            "process_xla_compiles": compiles,
            "process_cache_hits": hits,
        }

    # -- jax builder -------------------------------------------------------
    @classmethod
    def for_jax_program(
        cls,
        fn: Callable,
        shapes_for: Callable[[int], Tuple],
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        label: str = "program",
        aot_dir: Optional[str] = None,
    ) -> "BucketProgramRegistry":
        """Registry whose buckets are AOT-compiled variants of ``fn``.

        ``shapes_for(bucket)`` returns the ``jax.ShapeDtypeStruct``
        argument tuple for that bucket.  With ``aot_dir`` set, compiled
        executables are serialized there and warm restarts load them
        back (no trace, no compile); without it, warm restarts still
        ride the persistent compile cache.  Trace-side accounting: the
        traced python body increments ``registry.traces`` — a steady
        state that re-traces (and would therefore recompile) is directly
        observable by tests.
        """
        import jax

        from fabric_tpu.utils.jaxcache import enable_compile_cache

        enable_compile_cache()
        _CompileCounters.install()

        counters = {"traces": 0}

        def traced(*args):
            counters["traces"] += 1  # GIL-atomic add; trace-time only
            return fn(*args)

        def fingerprint(bucket: int) -> str:
            raw = "|".join(
                (
                    label,
                    str(bucket),
                    jax.__version__,
                    jax.default_backend(),
                    str(shapes_for(bucket)),
                )
            )
            return hashlib.sha256(raw.encode()).hexdigest()[:16]

        def builder(bucket: int) -> Tuple[Callable, Dict]:
            meta: Dict = {"aot_hit": False}
            path = None
            if aot_dir:
                path = os.path.join(
                    aot_dir, f"{label}-{bucket}-{fingerprint(bucket)}.aot"
                )
                program = _load_aot(path)
                if program is not None:
                    meta["aot_hit"] = True
                    return program, meta
            t0 = time.perf_counter()
            if path is not None:
                # artifact creation must serialize a REAL, FRESH compile.
                # Two caches can silently hand back an executable whose
                # serialization is a partial blob that fails at load
                # ("Symbols not found"): the persistent compile cache
                # (an entry written by another process deserializes
                # without its object files), and the in-process client
                # layer (a module with the SAME name+content as one
                # already loaded — e.g. warmed earlier from the cache —
                # is deduplicated against it, even with the jax cache
                # disabled).  So exactly here the compile cache is
                # bypassed AND the traced wrapper gets a process-unique
                # name: the HLO module name follows the function name,
                # so nothing in the process can dedupe it.  The cold
                # path pays full price once; every restart loads the AOT.
                def aot_traced(*args):
                    counters["traces"] += 1  # GIL-atomic add; trace-time only
                    return fn(*args)

                aot_traced.__name__ = (
                    f"aot_{os.getpid()}_{next(_AOT_SEQ)}_b{bucket}"
                )
                with _AOT_COMPILE_LOCK:
                    prev = getattr(
                        jax.config, "jax_enable_compilation_cache", True
                    )
                    jax.config.update("jax_enable_compilation_cache", False)
                    try:
                        compiled = (
                            jax.jit(aot_traced)
                            .lower(*shapes_for(bucket))
                            .compile()
                        )
                    finally:
                        jax.config.update("jax_enable_compilation_cache", prev)
                _save_aot(path, compiled)
            else:
                compiled = jax.jit(traced).lower(*shapes_for(bucket)).compile()
            meta["compile_ms"] = round((time.perf_counter() - t0) * 1000.0, 1)
            return compiled, meta

        registry = cls(buckets, builder, label=label)
        registry.traces_counter = counters  # type: ignore[attr-defined]
        return registry

    @property
    def traces(self) -> int:
        """Trace count of the jax builder's python body (0 on pure-AOT
        warm starts).  Steady state must keep this flat."""
        counters = getattr(self, "traces_counter", None)
        return 0 if counters is None else counters["traces"]


def _load_aot(path: str) -> Optional[Callable]:
    """Deserialize an AOT artifact written by :func:`_save_aot`; None on
    any failure (missing, version-skewed, corrupt) — the registry then
    falls back to trace+compile, so a stale artifact can only cost time,
    never correctness.  The artifact directory is operator-owned cache
    state (same trust domain as ``.jax_cache`` itself)."""
    try:
        from jax.experimental import serialize_executable as se

        with open(path, "rb") as fh:
            trees_len = int.from_bytes(fh.read(8), "big")
            in_tree, out_tree = pickle.loads(fh.read(trees_len))  # fabwire: disable=unbounded-wire-alloc  # operator-owned AOT cache in the same trust domain as .jax_cache: fh.read caps at file EOF and any short/garbled artifact falls into the recompile path below
            blob = fh.read()
        return se.deserialize_and_load(blob, in_tree, out_tree)
    except FileNotFoundError:
        return None
    except Exception as exc:  # noqa: BLE001 - stale artifact: rebuild
        logger.warning("AOT artifact %s unusable (%s); recompiling", path, exc)
        return None


def _save_aot(path: str, compiled) -> None:
    try:
        from jax.experimental import serialize_executable as se

        blob, in_tree, out_tree = se.serialize(compiled)
        trees = pickle.dumps((in_tree, out_tree))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(len(trees).to_bytes(8, "big"))
            fh.write(trees)
            fh.write(blob)
        os.replace(tmp, path)  # atomic: a killed writer leaves no torn file
    except Exception as exc:  # noqa: BLE001 - best-effort cache write
        logger.warning("AOT artifact %s not written (%s)", path, exc)


# ---------------------------------------------------------------------------
# The CI-able demo ladder: real ops.bignum limb math, small graph
# ---------------------------------------------------------------------------


def demo_limb_program():
    """(fn, shapes_for) for a small-but-real limb program: Montgomery
    exponentiation x^65537 mod P-256's p over a (NLIMBS, bucket) lane
    batch — the exact CIOS kernels the verify program is made of, in a
    graph that compiles in seconds on the CI box.  Used by the
    serve_gate smoke, the warm-restart test, and bench's cold-vs-warm
    compile column when no accelerator is reachable."""
    import jax
    import jax.numpy as jnp

    from fabric_tpu.common import p256
    from fabric_tpu.ops import bignum as bn

    ctx = bn.MontCtx(p256.P)

    def fn(x):
        xm = bn.to_mont(ctx, x)
        y = bn.mont_pow(ctx, xm, 65537)
        return bn.from_mont(ctx, y)

    def shapes_for(bucket: int):
        return (jax.ShapeDtypeStruct((bn.NLIMBS, bucket), jnp.uint32),)

    return fn, shapes_for


def verify_limb_program():
    """(fn, shapes_for) for the REAL device program: the batched ECDSA
    limb-matrix verify kernel.  Minutes of XLA compile cold (NOTES_BUILD)
    — which is the whole point of warming it once in a resident process
    and serializing the executable."""
    import jax
    import jax.numpy as jnp

    from fabric_tpu.ops import bignum as bn
    from fabric_tpu.ops.p256_kernel import verify_batch_device

    def shapes_for(bucket: int):
        limbs = jax.ShapeDtypeStruct((bn.NLIMBS, bucket), jnp.uint32)
        ok = jax.ShapeDtypeStruct((bucket,), jnp.bool_)
        return (limbs, limbs, limbs, limbs, limbs, ok)

    return verify_batch_device, shapes_for
