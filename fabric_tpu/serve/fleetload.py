"""Fleet load generator: one "peer" process driving a sidecar fleet.

The ROADMAP's fleet-scale acceptance needs N *processes* (not threads)
multiplexing one warm sidecar — real sockets, real process isolation,
zipf channel skew.  This module is that peer: it signs a mixed
valid/invalid lane set once, then drives ``--requests`` batches through
the ``SidecarProvider`` (or the ``SidecarRouter`` when ``--endpoints``
lists a fleet) under one channel + admission class, asserting every
mask against the by-construction ground truth, and prints ONE JSON
summary line (requests, ok, mask_mismatches, busy_rejects, degraded,
p50/p99 ms, lanes/s) — the contract ``bench.py configs.fleet`` and
``tests/test_fleet.py`` drive as subprocesses::

    python -m fabric_tpu.serve.fleetload --address /tmp/s.sock \
        --channel paychan --qos high --requests 16 --lanes 256 --seed 3
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.common import p256
from fabric_tpu.serve import protocol as proto

LANE_KINDS = ("good", "bad_sig", "high_s", "garbage")


def build_lanes(
    n: int, seed: int
) -> Tuple[List, List[bytes], List[bytes], List[bool]]:
    """Mixed valid/invalid lanes with exact expected verdicts (the
    serve_gate corruption recipe, seeded per peer)."""
    from fabric_tpu.crypto import der, hostec
    from fabric_tpu.crypto.bccsp import ECDSAPublicKey

    d_priv = 0xF1EE7 + seed * 7919
    pub = ECDSAPublicKey(*hostec.scalar_base_mult(d_priv))
    keys, sigs, digests, expected = [], [], [], []
    for i in range(n):
        digest = hashlib.sha256(
            b"fleetload lane %d %d" % (seed, i)
        ).digest()
        r, s = hostec.sign_digest(d_priv, digest)
        sig = der.marshal_signature(r, s)
        kind = LANE_KINDS[i % len(LANE_KINDS)]
        if kind == "bad_sig":
            bad = bytearray(sig)
            bad[-1] ^= 0x5A
            sig = bytes(bad)
        elif kind == "high_s":
            sig = der.marshal_signature(r, p256.N - s)
        elif kind == "garbage":
            sig = b"\x00\x01garbage"
        keys.append(pub)
        sigs.append(sig)
        digests.append(digest)
        expected.append(kind == "good")
    return keys, sigs, digests, expected


def _pct(sorted_s: Sequence[float], q: float) -> float:
    if not sorted_s:
        return 0.0
    i = min(len(sorted_s) - 1, max(0, int(round(q * (len(sorted_s) - 1)))))
    return sorted_s[i]


def run(
    address: Optional[str] = None,
    endpoints: Optional[Sequence[str]] = None,
    channel: str = "",
    qos: str = "normal",
    n_requests: int = 8,
    lanes: int = 256,
    seed: int = 0,
) -> dict:
    """Drive the load; returns the summary dict (also usable
    in-process by the tier-1 canary)."""
    qos_class = (
        proto.QOS_NAMES.index(qos) if qos in proto.QOS_NAMES
        else proto.DEFAULT_QOS
    )
    if endpoints:
        from fabric_tpu.serve.router import SidecarRouter

        provider = SidecarRouter(
            endpoints=endpoints, qos_class=qos_class, channel=channel
        )
    else:
        from fabric_tpu.serve.client import SidecarProvider

        provider = SidecarProvider(
            address=address, qos_class=qos_class, channel=channel
        )
    keys, sigs, digests, expected = build_lanes(lanes, seed)
    latencies: List[float] = []
    ok = mismatches = 0
    t_start = time.perf_counter()
    for _ in range(n_requests):
        t0 = time.perf_counter()
        mask = provider.batch_verify(keys, sigs, digests)
        latencies.append(time.perf_counter() - t0)
        if list(mask) == expected:
            ok += 1
        else:
            mismatches += 1
    wall_s = time.perf_counter() - t_start
    lat = sorted(latencies)
    # tail-tolerance counters (fabtail): hedge/eviction counters exist
    # on the router only, deadline expiry on both provider shapes —
    # the soak quantifies TAIL behavior, not just throughput
    per_endpoint = None
    if hasattr(provider, "describe"):
        per_endpoint = [
            {
                "address": ep["address"],
                "p99_ms": ep.get("p99_ms"),
                "ewma_ms": ep.get("ewma_ms"),
                "healthy": ep["healthy"],
            }
            for ep in provider.describe()["endpoints"]
        ]
    summary = {
        "channel": channel,
        "cls": proto.qos_name(qos_class),
        "requests": n_requests,
        "lanes_per_request": lanes,
        "ok": ok,
        "mask_mismatches": mismatches,
        "busy_rejects": provider.busy_rejects,
        "degraded": provider.degraded,
        "deadline_expired": getattr(provider, "deadline_expired", 0),
        "hedges": getattr(provider, "hedges", 0),
        "hedge_wins": getattr(provider, "hedge_wins", 0),
        "slow_evictions": getattr(provider, "slow_evictions", 0),
        "p50_ms": round(_pct(lat, 0.50) * 1e3, 3),
        "p99_ms": round(_pct(lat, 0.99) * 1e3, 3),
        "wall_s": round(wall_s, 3),
        "lanes_per_s": round(n_requests * lanes / max(wall_s, 1e-9), 1),
    }
    if per_endpoint is not None:
        summary["per_endpoint"] = per_endpoint
    provider.stop()
    return summary


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fabric_tpu.serve.fleetload",
        description="one peer process of a multi-peer sidecar soak",
    )
    ap.add_argument("--address", default="")
    ap.add_argument(
        "--endpoints", default="",
        help="comma-separated fleet addresses (routes via SidecarRouter)",
    )
    ap.add_argument("--channel", default="")
    ap.add_argument("--qos", default="normal", choices=proto.QOS_NAMES)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--lanes", type=int, default=256)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    endpoints = [a.strip() for a in args.endpoints.split(",") if a.strip()]
    summary = run(
        address=args.address or None,
        endpoints=endpoints or None,
        channel=args.channel,
        qos=args.qos,
        n_requests=args.requests,
        lanes=args.lanes,
        seed=args.seed,
    )
    print(json.dumps(summary, sort_keys=True), flush=True)
    # a peer that could not hold the mask contract is a failed worker
    return 0 if summary["mask_mismatches"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
