"""Per-class admission budgets for the shared sidecar (protocol rev 2).

PR 8's admission control was one global lane budget: a zipf-skewed spam
channel could occupy every lane and starve a paying channel behind the
same ``ST_BUSY`` — exactly the multi-tenant failure the Blockchain
Machine sidesteps by attaching one validator to a *network* of peers
(PAPERS.md 2104.06968).  :class:`ClassLedger` splits the budget into
weighted per-class quotas with **work-conserving borrowing**:

- a class may always use up to its reserved quota (``share * total``);
- beyond its quota it may borrow idle lanes, but ONLY while every
  *demanding* other class's unused reservation stays coverable — after
  an admission, the free-lane count must still cover
  ``sum(max(0, quota_o - used_o))`` over the other classes that have
  demand (lanes in flight, or a rejection not yet followed by an
  admission: the ``waiting`` latch);
- a class with no demand protects nothing — a single-tenant deployment
  uses the whole machine (fully work-conserving).

The invariant that buys the QoS guarantee: a burst of bulk traffic can
fill the whole machine while high-priority is idle, yet after at most
ONE rejection a high-priority channel's full quota is protected from
further borrowing until it is served — bulk drains, high admits, spam
never re-occupies the reservation.  Shedding stays protocol-explicit:
a rejected acquisition becomes an ``ST_BUSY`` with a per-class
``retry_after_ms``, never a silent drop.

The ledger is a leaf (one lock around counters, no I/O, no imports
upward) so the server can hold it on the request path and fabchaos can
drive it deterministically.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from fabric_tpu.serve import protocol as proto

#: default lane shares per class (must sum to <= 1.0; the remainder is
#: borrowable-only headroom).  High-priority traffic owns half the
#: machine even under a 10:1 spam skew.
DEFAULT_SHARES: Dict[str, float] = {"high": 0.5, "normal": 0.35, "bulk": 0.15}


def parse_shares(text: str) -> Dict[str, float]:
    """``high=0.5,normal=0.35,bulk=0.15`` -> share map.  Malformed
    entries raise ValueError (the CLI surfaces it; env consumers catch
    and fall back — the shared envreg read discipline)."""
    out: Dict[str, float] = {}
    for raw in text.replace(";", ",").split(","):
        entry = raw.strip()
        if not entry:
            continue
        name, sep, value = entry.partition("=")
        name = name.strip()
        if not sep or name not in proto.QOS_NAMES:
            raise ValueError(
                f"qos share entry {entry!r} is not class=fraction "
                f"(classes: {proto.QOS_NAMES})"
            )
        share = float(value)
        if not 0.0 <= share <= 1.0:
            raise ValueError(f"qos share {share!r} not in [0, 1]")
        out[name] = share
    if sum(out.values()) > 1.0 + 1e-9:
        raise ValueError(f"qos shares sum to {sum(out.values())} > 1")
    return out


class ClassLedger:
    """Per-class in-flight lane accounting with weighted quotas and
    work-conserving borrowing (module docstring has the invariant)."""

    def __init__(
        self,
        total_lanes: int,
        shares: Optional[Dict[str, float]] = None,
    ):
        self.total = max(1, int(total_lanes))
        share_map = dict(DEFAULT_SHARES)
        share_map.update(shares or {})
        self.quota: Tuple[int, ...] = tuple(
            int(self.total * share_map.get(name, 0.0))
            for name in proto.QOS_NAMES
        )
        self._lock = threading.Lock()
        self._used: List[int] = [0] * len(proto.QOS_NAMES)
        # the demand latch: set on a rejection, cleared by the class's
        # next admission — a rejected class's reservation is protected
        # from borrowing until it has been served (no clocks, so the
        # chaos scorecard replays bit-identically)
        self._waiting: List[bool] = [False] * len(proto.QOS_NAMES)
        # protocol-level accounting: every shed is an explicit ST_BUSY,
        # and these counters are how a scorecard proves none were silent
        self.admitted: List[int] = [0] * len(proto.QOS_NAMES)
        self.rejected: List[int] = [0] * len(proto.QOS_NAMES)
        # lifetime lane flow: acquired must equal released + in-flight
        # at every instant — hedge_storm's balance proof that hedged
        # lanes are neither leaked nor double-released (release() would
        # otherwise clamp a double-free invisibly at zero)
        self.lanes_acquired = 0
        self.lanes_released = 0

    def _clamped(self, qos_class: int) -> int:
        return qos_class if 0 <= qos_class < len(self._used) else proto.QOS_BULK

    def try_acquire(self, qos_class: int, lanes: int) -> bool:
        """Admit ``lanes`` for ``qos_class`` NOW or refuse (never
        blocks — the caller turns False into an ST_BUSY reply)."""
        c = self._clamped(qos_class)
        n = min(max(1, lanes), self.total)
        with self._lock:
            used_total = sum(self._used)
            if used_total + n > self.total:
                self.rejected[c] += 1
                self._waiting[c] = True
                return False
            if self._used[c] + n > self.quota[c]:
                # borrowing leg: admit only while every DEMANDING other
                # class's unused reservation stays coverable afterwards
                # (demand = lanes in flight or the waiting latch; an
                # idle class protects nothing — work-conserving)
                reserved_unused = sum(
                    max(0, self.quota[o] - self._used[o])
                    for o in range(len(self._used))
                    if o != c and (self._used[o] > 0 or self._waiting[o])
                )
                if self.total - used_total - n < reserved_unused:
                    self.rejected[c] += 1
                    self._waiting[c] = True
                    return False
            self._used[c] += n
            self._waiting[c] = False
            self.admitted[c] += 1
            self.lanes_acquired += n
            return True

    def release(self, qos_class: int, lanes: int) -> None:
        c = self._clamped(qos_class)
        n = min(max(1, lanes), self.total)
        with self._lock:
            self._used[c] = max(0, self._used[c] - n)
            self.lanes_released += n

    def balance(self) -> Dict[str, int]:
        """Lifetime lane-flow balance: ``leaked`` must be 0 at quiesce
        and can never go negative unless a release was double-fired —
        the hedge/cancel bookkeeping proof the det scorecard pins."""
        with self._lock:
            return {
                "acquired": self.lanes_acquired,
                "released": self.lanes_released,
                "in_flight": sum(self._used),
                "leaked": self.lanes_acquired - self.lanes_released
                - sum(self._used),
            }

    def fill(self, qos_class: Optional[int] = None) -> float:
        """Queue-fill fraction: the class's used/quota when given (the
        per-class retry_after signal), else the global used/total."""
        with self._lock:
            if qos_class is None:
                return sum(self._used) / self.total
            c = self._clamped(qos_class)
            return self._used[c] / max(self.quota[c], 1)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                name: {
                    "quota": self.quota[i],
                    "used": self._used[i],
                    "waiting": self._waiting[i],
                    "admitted": self.admitted[i],
                    "rejected": self.rejected[i],
                }
                for i, name in enumerate(proto.QOS_NAMES)
            }


# ---------------------------------------------------------------------------
# Channel -> class mapping (client side; FABRIC_TPU_SERVE_QOS)
# ---------------------------------------------------------------------------


def parse_qos_map(text: str) -> Dict[str, int]:
    """``paychan=high;spam*=bulk;*=normal`` -> {pattern: class id}.
    Patterns are exact channel ids or a trailing-``*`` prefix match;
    ``*`` alone is the default.  Malformed entries raise ValueError."""
    out: Dict[str, int] = {}
    for raw in text.replace(",", ";").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        pattern, sep, cls_name = entry.partition("=")
        pattern, cls_name = pattern.strip(), cls_name.strip()
        if not sep or not pattern or cls_name not in proto.QOS_NAMES:
            raise ValueError(
                f"qos map entry {entry!r} is not channel=class "
                f"(classes: {proto.QOS_NAMES})"
            )
        out[pattern] = proto.QOS_NAMES.index(cls_name)
    return out


def class_for_channel(
    channel: Optional[str], qos_map: Dict[str, int]
) -> int:
    """Resolve a channel to its admission class: exact match, then the
    longest ``prefix*`` match, then ``*``, then the protocol default."""
    if channel and channel in qos_map:
        return qos_map[channel]
    if channel:
        best: Optional[Tuple[int, int]] = None  # (prefix_len, class)
        for pattern, cls in qos_map.items():
            if pattern.endswith("*") and pattern != "*":
                prefix = pattern[:-1]
                if channel.startswith(prefix):
                    if best is None or len(prefix) > best[0]:
                        best = (len(prefix), cls)
        if best is not None:
            return best[1]
    if "*" in qos_map:
        return qos_map["*"]
    return proto.DEFAULT_QOS


def qos_map_from_env() -> Dict[str, int]:
    """The ``FABRIC_TPU_SERVE_QOS`` channel->class map (shared read
    discipline: a malformed map warns and resolves everything to the
    default class — an env typo must never break a verify path)."""
    import os

    raw = os.environ.get("FABRIC_TPU_SERVE_QOS", "")
    if not raw:
        return {}
    try:
        return parse_qos_map(raw)
    except ValueError as exc:
        import warnings

        warnings.warn(
            f"FABRIC_TPU_SERVE_QOS ignored (malformed: {exc})",
            RuntimeWarning,
            stacklevel=2,
        )
        return {}
