"""Multi-sidecar router: peer-side load balancing with failover.

One sidecar is a warm appliance; a fleet needs several behind every
peer so a single sidecar death is a *routing* event, not a degrade-to-
inline event.  :class:`SidecarRouter` presents the same Provider SPI as
``SidecarProvider`` and spreads a peer's batches across N endpoints:

- **bucket-aware placement**: a batch's lane-bucket picks its endpoint
  by rendezvous hash (``sha256(bucket | address)``), so each sidecar
  sees a stable subset of shapes and its warm executables stay hot —
  while any endpoint can serve any bucket when its preferred one dies;
- **health-probe eviction**: every endpoint carries its own
  ``CooldownGate`` (the serve client's dial-circuit discipline, lifted
  to serving failures) — a dead endpoint is skipped for exponentially
  longer cooldowns and re-probed with a cheap PING before it gets a
  real batch again, so one blackholed sidecar never slows dials to the
  healthy ones;
- **re-verify-on-kill, across endpoints**: the PR 8 ST_STOPPING
  discipline (never trust a dying sidecar's settlement) now fails over
  — a kill/drain mid-batch re-verifies on the next healthy endpoint,
  and only when EVERY endpoint has refused does the router degrade to
  the in-process ladder (bit-exact masks, all-False only on a double
  fault: the client shim's mask contract verbatim);
- **rolling-restart support**: a draining sidecar answers ST_STOPPING
  and flips its /healthz, the router routes around it, and the restart
  finds its way back in after one successful probe — restarting every
  sidecar in sequence under sustained load never breaks mask
  bit-exactness (fabchaos ``router_flap`` proves it).

``fault_point("serve.route")`` arms each dispatch attempt for chaos.
Endpoint health transitions drive the ``fabric_serve_endpoint_healthy``
gauge.  Addresses come from the constructor, ``BCCSP SERVE.Endpoints``,
or ``FABRIC_TPU_SERVE_ENDPOINTS`` (comma-separated).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.retry import Backoff, CooldownGate, RetryPolicy
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.client import (
    BUSY_POLICY,
    SidecarClient,
    SidecarUnavailable,
    encode_lanes,
)

logger = must_get_logger("serve.router")

#: endpoint serving-failure circuit: faster ramp than the default
#: rebuild gate — a routing decision is cheap, a wrong one costs one
#: failed request, and a restarted sidecar should be back in rotation
#: within seconds
ENDPOINT_GATE_POLICY = RetryPolicy(
    base_s=0.25, multiplier=2.0, cap_s=5.0, deadline_s=float("inf")
)

#: lane-bucket ladder for placement (the registry's shape discipline;
#: placement only needs stability, not agreement with any one sidecar's
#: configured ladder)
ROUTE_BUCKETS = (128, 256, 512, 1024, 2048, 4096, 8192, 16384)


def _route_bucket(n: int) -> int:
    for b in ROUTE_BUCKETS:
        if n <= b:
            return b
    return ROUTE_BUCKETS[-1]


class _Endpoint:
    """One sidecar endpoint: pipelined client + serving-failure gate.
    All mutable health state is guarded by the endpoint's lock."""

    def __init__(self, address: str, gate_policy: RetryPolicy,
                 clock: Callable[[], float] = time.monotonic):
        self.address = address
        self.client = SidecarClient(address)
        self.gate = CooldownGate(policy=gate_policy, clock=clock)
        self._lock = threading.Lock()
        self._healthy = True
        fabobs.obs_gauge(
            "fabric_serve_endpoint_healthy", 1.0, endpoint=address
        )

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    def mark_up(self) -> None:
        self.gate.record_success()
        with self._lock:
            flipped = not self._healthy
            self._healthy = True
        if flipped:
            logger.info("sidecar endpoint %s is healthy again", self.address)
            fabobs.obs_gauge(
                "fabric_serve_endpoint_healthy", 1.0, endpoint=self.address
            )

    def mark_down(self, why: object) -> None:
        self.gate.record_failure()
        with self._lock:
            flipped = self._healthy
            self._healthy = False
        if flipped:
            logger.warning(
                "sidecar endpoint %s evicted (%s); cooling down",
                self.address, why,
            )
            fabobs.obs_gauge(
                "fabric_serve_endpoint_healthy", 0.0, endpoint=self.address
            )


def endpoints_from_env() -> List[str]:
    """``FABRIC_TPU_SERVE_ENDPOINTS`` -> address list (shared read
    discipline: an empty/whitespace value is simply no endpoints)."""
    raw = os.environ.get("FABRIC_TPU_SERVE_ENDPOINTS", "")
    return [a.strip() for a in raw.split(",") if a.strip()]


class SidecarRouter:
    """Provider SPI over N sidecar endpoints with peer-side failover.

    Single verify/sign/hash/key ops run in-process (the sidecar fleet
    exists for the batch plane), exactly like ``SidecarProvider``."""

    def __init__(
        self,
        endpoints: Optional[Sequence[str]] = None,
        fallback=None,
        busy_policy: RetryPolicy = BUSY_POLICY,
        sleeper: Callable[[float], None] = time.sleep,
        qos_class: Optional[int] = None,
        channel: str = "",
        gate_policy: RetryPolicy = ENDPOINT_GATE_POLICY,
        clock: Callable[[], float] = time.monotonic,
    ):
        if endpoints is None:
            endpoints = endpoints_from_env()
        if isinstance(endpoints, str):
            endpoints = [a.strip() for a in endpoints.split(",") if a.strip()]
        if not endpoints:
            raise ValueError(
                "router needs at least one sidecar endpoint "
                "(FABRIC_TPU_SERVE_ENDPOINTS or BCCSP SERVE.Endpoints)"
            )
        self.endpoints: List[_Endpoint] = [
            _Endpoint(addr, gate_policy, clock=clock) for addr in endpoints
        ]
        self.busy_policy = busy_policy
        self._sleeper = sleeper
        self._fallback = fallback
        self._fallback_lock = threading.Lock()
        self.degraded = False  # latched: any batch served in-process
        self.busy_rejects = 0
        self.channel = channel
        if qos_class is None:
            from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

            qos_class = class_for_channel(channel, qos_map_from_env())
        self.qos_class = qos_class

    # -- placement ---------------------------------------------------------
    def _order(self, lanes: int) -> List[_Endpoint]:
        """Endpoint preference for a batch: rendezvous-hashed on the
        lane bucket over SELECTABLE endpoints (gate ready), so buckets
        spread across the fleet and a cooling endpoint is skipped
        without a dial.  Every selectable endpoint stays in the list —
        positions 2..N are the failover ladder."""
        bucket = _route_bucket(lanes)
        ready = [e for e in self.endpoints if e.gate.ready()]
        if not ready:
            return []

        def score(e: _Endpoint) -> bytes:
            return hashlib.sha256(
                f"{bucket}|{e.address}".encode("utf-8", "backslashreplace")
            ).digest()

        return sorted(ready, key=score)

    def _probe_ok(self, e: _Endpoint) -> bool:
        """A previously-evicted endpoint earns a real batch back with a
        cheap PING first — a probe failure costs microseconds, a routed
        batch failure costs a re-verify."""
        if e.healthy:
            return True
        try:
            if e.client.ping():
                e.mark_up()
                return True
        except (SidecarUnavailable, proto.ProtocolError) as exc:
            e.mark_down(exc)
        return False

    # -- in-process fallback ----------------------------------------------
    def fallback_provider(self):
        with self._fallback_lock:
            if self._fallback is None:
                from fabric_tpu.crypto.bccsp import probe_provider

                self._fallback = probe_provider()
            return self._fallback

    def _degrade(self, keys, signatures, digests, why) -> List[bool]:
        """Every endpoint refused: in-process verification (bit-exact
        masks), all-False only if the local ladder ALSO fails."""
        if not self.degraded:
            logger.warning(
                "all %d sidecar endpoints unavailable (%s); degrading "
                "to in-process verification", len(self.endpoints), why,
            )
            fabobs.obs_count("fabric_degrade_total", seam="serve.router")
            fabobs.obs_trigger("serve.router_degraded")
        self.degraded = True
        try:
            mask = self.fallback_provider().batch_verify(
                keys, signatures, digests
            )
            return list(mask)
        except Exception as exc:  # noqa: BLE001 - double fault: fail closed
            logger.error(
                "in-process fallback failed too (%s): batch fails closed",
                exc,
            )
            return [False] * len(keys)

    # -- one endpoint, one attempt ----------------------------------------
    def _try_endpoint(
        self, e: _Endpoint, keys, signatures, digests, attempt: int
    ) -> Tuple[str, Optional[List[bool]]]:
        """('ok', mask) | ('busy', None) | ('dead', None).  BUSY is
        admission control, not endpoint failure — the gate only records
        failures that mean the endpoint cannot serve."""
        n = len(keys)
        try:
            # chaos seam: an injected routing fault fails THIS attempt
            # on THIS endpoint — the ladder below must absorb it
            fault_point("serve.route", key=(e.address, attempt))
            e.client.ensure_connected()
            if e.client.version >= 2:
                payload = encode_lanes(
                    keys, signatures, digests,
                    qos_class=self.qos_class, channel=self.channel,
                )
            else:
                payload = encode_lanes(keys, signatures, digests,
                                       qos_class=None)
            status, _retry_ms, mask, message = proto.decode_verify_response(
                e.client.request(proto.OP_VERIFY, payload)
            )
        except Exception as exc:  # noqa: BLE001 - endpoint failure (incl. injected) routes to the next rung, never past the mask contract
            logger.debug("endpoint %s verify attempt failed: %s", e.address, exc)
            e.mark_down(exc)
            return "dead", None
        if status == proto.ST_OK and mask is not None and len(mask) == n:
            e.mark_up()
            return "ok", mask
        if status == proto.ST_BUSY:
            self.busy_rejects += 1  # GIL-atomic add, stats only
            return "busy", None
        # ST_STOPPING / ST_ERROR / malformed OK: the re-verify-on-kill
        # discipline across endpoints — never trust this settlement,
        # route the batch to the next endpoint
        e.mark_down(message or f"status {status}")
        return "dead", None

    # -- the batch plane ---------------------------------------------------
    def batch_verify(self, keys, signatures, digests) -> List[bool]:
        n = len(keys)
        if n == 0:
            return []
        t0 = time.perf_counter()
        bo = Backoff(self.busy_policy, sleeper=self._sleeper)
        attempt = 0
        while True:
            any_busy = False
            for e in self._order(n):
                if not self._probe_ok(e):
                    continue
                attempt += 1
                outcome, mask = self._try_endpoint(
                    e, keys, signatures, digests, attempt
                )
                if outcome == "ok":
                    assert mask is not None
                    fabobs.obs_count(
                        "fabric_verify_lanes_total", n, rung="serve"
                    )
                    fabobs.obs_observe(
                        "fabric_verify_seconds",
                        time.perf_counter() - t0, rung="serve",
                    )
                    return mask
                if outcome == "busy":
                    any_busy = True
            if any_busy and bo.sleep():
                continue  # every live endpoint is shedding: pace + retry
            return self._degrade(
                keys, signatures, digests,
                "every endpoint busy (budget spent)" if any_busy
                else "no healthy endpoint",
            )

    def batch_verify_async(self, keys, signatures, digests):
        """Pipelined dispatch through the preferred endpoint; ANY
        failure at resolve time re-routes through the sync failover
        ladder (which owns the degrade contract)."""
        n = len(keys)
        if n == 0:
            return list
        t0 = time.perf_counter()
        chosen: Optional[_Endpoint] = None
        token = None
        for e in self._order(n):
            if not self._probe_ok(e):
                continue
            try:
                fault_point("serve.route", key=(e.address, 0))
                e.client.ensure_connected()
                if e.client.version >= 2:
                    payload = encode_lanes(
                        keys, signatures, digests,
                        qos_class=self.qos_class, channel=self.channel,
                    )
                else:
                    payload = encode_lanes(keys, signatures, digests,
                                           qos_class=None)
                token = e.client.submit(proto.OP_VERIFY, payload)
                chosen = e
                break
            except Exception as exc:  # noqa: BLE001 - submit failure (incl. injected): next endpoint
                logger.debug("endpoint %s submit failed: %s", e.address, exc)
                e.mark_down(exc)

        def resolve() -> List[bool]:
            if chosen is None or token is None:
                return self.batch_verify(keys, signatures, digests)
            try:
                status, _, mask, _ = proto.decode_verify_response(
                    chosen.client.await_reply(token)
                )
            except (SidecarUnavailable, proto.ProtocolError) as exc:
                chosen.mark_down(exc)
                return self.batch_verify(keys, signatures, digests)
            if status == proto.ST_OK and mask is not None and len(mask) == n:
                chosen.mark_up()
                fabobs.obs_count("fabric_verify_lanes_total", n, rung="serve")
                fabobs.obs_observe(
                    "fabric_verify_seconds",
                    time.perf_counter() - t0, rung="serve",
                )
                return mask
            if status != proto.ST_BUSY:
                chosen.mark_down(f"status {status}")
            return self.batch_verify(keys, signatures, digests)

        return resolve

    # -- fleet operations --------------------------------------------------
    def drain_endpoint(self, address: str) -> bool:
        """Ask one sidecar to drain (rolling restart step): True when
        the endpoint acknowledged the OP_DRAIN.  The router marks it
        down immediately so no new batch races the drain."""
        for e in self.endpoints:
            if e.address != address:
                continue
            try:
                reply = e.client.request(proto.OP_DRAIN)
                status, _, _, _ = proto.decode_verify_response(reply)
                e.mark_down("draining (rolling restart)")
                return status == proto.ST_OK
            except (SidecarUnavailable, proto.ProtocolError) as exc:
                e.mark_down(exc)
                return False
        return False

    def for_channel(self, channel_id: str) -> "SidecarRouter":
        """Channel-bound view sharing the endpoint clients and gates
        (one fleet, per-class traffic) — the SidecarProvider.for_channel
        contract over the router."""
        import copy

        from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

        cls = class_for_channel(channel_id, qos_map_from_env())
        if channel_id == self.channel and cls == self.qos_class:
            return self
        bound = copy.copy(self)
        bound.channel = channel_id
        bound.qos_class = cls
        return bound

    def describe(self) -> dict:
        return {
            "endpoints": [
                {
                    "address": e.address,
                    "healthy": e.healthy,
                    "selectable": e.gate.ready(),
                    "version": e.client.version,
                }
                for e in self.endpoints
            ],
            "qos_class": proto.qos_name(self.qos_class),
            "channel": self.channel,
            "degraded": self.degraded,
            "busy_rejects": self.busy_rejects,
        }

    # -- pass-through SPI --------------------------------------------------
    def verify(self, key, signature: bytes, digest: bytes) -> bool:
        return self.fallback_provider().verify(key, signature, digest)

    def batch_hash(self, msgs):
        return self.fallback_provider().batch_hash(msgs)

    def hash(self, msg: bytes) -> bytes:
        return self.fallback_provider().hash(msg)

    def key_import(self, raw: bytes):
        return self.fallback_provider().key_import(raw)

    def key_gen(self):
        return self.fallback_provider().key_gen()

    def sign(self, key, digest: bytes) -> bytes:
        return self.fallback_provider().sign(key, digest)

    def describe_backend(self) -> str:
        if self.degraded:
            return (
                "router-degraded("
                f"{self.fallback_provider().describe_backend()})"
            )
        return "serve-router:" + ",".join(e.address for e in self.endpoints)

    def stop(self) -> None:
        for e in self.endpoints:
            e.client.close()
