"""Multi-sidecar router: peer-side load balancing with failover and
tail tolerance.

One sidecar is a warm appliance; a fleet needs several behind every
peer so a single sidecar death is a *routing* event, not a degrade-to-
inline event.  :class:`SidecarRouter` presents the same Provider SPI as
``SidecarProvider`` and spreads a peer's batches across N endpoints:

- **bucket-aware placement**: a batch's lane-bucket picks its endpoint
  by rendezvous hash (``sha256(bucket | address)``), so each sidecar
  sees a stable subset of shapes and its warm executables stay hot —
  while any endpoint can serve any bucket when its preferred one dies;
- **health-probe eviction**: every endpoint carries its own
  ``CooldownGate`` (the serve client's dial-circuit discipline, lifted
  to serving failures) — a dead endpoint is skipped for exponentially
  longer cooldowns and re-probed with a cheap short-timeout PING before
  it gets a real batch again, so one blackholed sidecar never slows
  dials to the healthy ones;
- **hedged verification** (fabtail): every endpoint carries a latency
  tracker (EWMA + bounded reservoir); when the preferred endpoint has
  not answered within a hedge delay derived from its own OBSERVED
  quantiles (never a static knob), the router fires the same batch at
  the next-ranked endpoint — first verdict wins, the loser is
  cancelled best-effort over OP_CANCEL, and a global token-bucket
  hedge budget (default <= 5% extra requests) guarantees hedging can
  never amplify an overloaded fleet into collapse.  Verification is
  pure, so first-wins is mask-safe by construction;
- **gray-failure eviction** (fabtail): an endpoint that is alive but a
  latency outlier — its EWMA far above the fleet's best, or it keeps
  losing its own hedges — is evicted through the same CooldownGate
  ladder as a dead one and earns traffic back through probes;
- **wire deadlines** (fabtail): with a per-batch budget configured
  (``deadline_ms`` / ``FABRIC_TPU_SERVE_DEADLINE_MS``), every per-hop
  wait derives from the REMAINING budget; an expired budget hands the
  batch to the in-process ladder instead of parking on a slow socket;
- **re-verify-on-kill, across endpoints**: the PR 8 ST_STOPPING
  discipline (never trust a dying sidecar's settlement) now fails over
  — a kill/drain mid-batch re-verifies on the next healthy endpoint,
  and only when EVERY endpoint has refused does the router degrade to
  the in-process ladder (bit-exact masks, all-False only on a double
  fault: the client shim's mask contract verbatim);
- **rolling-restart support**: a draining sidecar answers ST_STOPPING
  and flips its /healthz, the router routes around it, and the restart
  finds its way back in after one successful probe — restarting every
  sidecar in sequence under sustained load never breaks mask
  bit-exactness (fabchaos ``router_flap`` proves it).

``fault_point("serve.route")`` arms each dispatch attempt for chaos.
Endpoint health transitions drive the ``fabric_serve_endpoint_healthy``
gauge; hedges/wins/evictions drive ``fabric_serve_hedges_total``,
``fabric_serve_hedge_wins_total`` and
``fabric_serve_slow_evictions_total``.  Addresses come from the
constructor, ``BCCSP SERVE.Endpoints``, or
``FABRIC_TPU_SERVE_ENDPOINTS`` (comma-separated).
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.retry import Backoff, CooldownGate, RetryPolicy
from fabric_tpu.serve import protocol as proto
from fabric_tpu.serve.client import (
    BUSY_POLICY,
    SidecarClient,
    SidecarUnavailable,
    deadline_ms_from_env,
    encode_lanes,
)

logger = must_get_logger("serve.router")

#: endpoint serving-failure circuit: faster ramp than the default
#: rebuild gate — a routing decision is cheap, a wrong one costs one
#: failed request, and a restarted sidecar should be back in rotation
#: within seconds
ENDPOINT_GATE_POLICY = RetryPolicy(
    base_s=0.25, multiplier=2.0, cap_s=5.0, deadline_s=float("inf")
)

#: lane-bucket ladder for placement (the registry's shape discipline;
#: placement only needs stability, not agreement with any one sidecar's
#: configured ladder)
ROUTE_BUCKETS = (128, 256, 512, 1024, 2048, 4096, 8192, 16384)

#: default global hedge budget: extra (hedged) requests as a fraction
#: of primary requests.  5% bounds the amplification an overloaded
#: fleet can see from its own tail-chasing.
DEFAULT_HEDGE_FRACTION = 0.05

#: floor on the derived hedge delay (ms): below this the hedge would
#: race ordinary jitter, not a gray failure
DEFAULT_HEDGE_MIN_MS = 20.0


def hedge_fraction_from_env() -> float:
    """``FABRIC_TPU_SERVE_HEDGE_FRACTION`` -> budget fraction in
    [0, 1]; 0 disables hedging (shared env read discipline: malformed
    values warn and fall back to the default)."""
    raw = os.environ.get("FABRIC_TPU_SERVE_HEDGE_FRACTION", "")
    if not raw:
        return DEFAULT_HEDGE_FRACTION
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        logger.warning(
            "FABRIC_TPU_SERVE_HEDGE_FRACTION=%r ignored (not a float)", raw
        )
        return DEFAULT_HEDGE_FRACTION


def hedge_min_ms_from_env() -> float:
    """``FABRIC_TPU_SERVE_HEDGE_MIN_MS`` -> hedge-delay floor in ms
    (malformed values warn and fall back)."""
    raw = os.environ.get("FABRIC_TPU_SERVE_HEDGE_MIN_MS", "")
    if not raw:
        return DEFAULT_HEDGE_MIN_MS
    try:
        return max(0.0, float(raw))
    except ValueError:
        logger.warning(
            "FABRIC_TPU_SERVE_HEDGE_MIN_MS=%r ignored (not a float)", raw
        )
        return DEFAULT_HEDGE_MIN_MS


def _route_bucket(n: int) -> int:
    for b in ROUTE_BUCKETS:
        if n <= b:
            return b
    return ROUTE_BUCKETS[-1]


class _LatencyTracker:
    """Per-endpoint observed service latency: EWMA for the outlier
    signal, a bounded newest-win reservoir for quantiles (the hedge
    delay derives from the endpoint's OWN p9x, not a static knob)."""

    WINDOW = 128

    def __init__(self):
        self._lock = threading.Lock()
        self._window: collections.deque = collections.deque(
            maxlen=self.WINDOW
        )
        self.ewma_s: Optional[float] = None
        self.samples = 0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._window.append(seconds)
            self.samples += 1
            self.ewma_s = (
                seconds
                if self.ewma_s is None
                else 0.8 * self.ewma_s + 0.2 * seconds
            )

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._window:
                return None
            xs = sorted(self._window)
            return xs[min(len(xs) - 1, int(q * (len(xs) - 1)))]


class _HedgeBudget:
    """Count-based token bucket bounding hedges to a fraction of
    primary requests: each primary dispatch earns ``fraction`` tokens
    (capped at ``burst``), each hedge spends one.  No clocks — the
    bound holds per request count, so an overloaded fleet cannot be
    amplified past ``burst + fraction * requests`` extra lanes and the
    chaos scorecard replays bit-identically."""

    def __init__(self, fraction: float, burst: float = 2.0):
        self.fraction = max(0.0, fraction)
        self.burst = max(1.0, burst)
        self._lock = threading.Lock()
        self._tokens = min(1.0, self.burst) if self.fraction > 0 else 0.0
        self.earned = 0  # primary requests seen

    def earn(self) -> None:
        if self.fraction <= 0:
            return
        with self._lock:
            self.earned += 1
            self._tokens = min(self.burst, self._tokens + self.fraction)

    def try_spend(self) -> bool:
        if self.fraction <= 0:
            return False
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class _Endpoint:
    """One sidecar endpoint: pipelined client + serving-failure gate +
    latency tracker.  All mutable health state is guarded by the
    endpoint's lock."""

    def __init__(self, address: str, gate_policy: RetryPolicy,
                 clock: Callable[[], float] = time.monotonic):
        self.address = address
        self.client = SidecarClient(address)
        self.gate = CooldownGate(policy=gate_policy, clock=clock)
        self.tracker = _LatencyTracker()
        self._lock = threading.Lock()
        self._healthy = True
        # consecutive hedges this endpoint lost while primary — the
        # gray-failure signal for an endpoint that never answers first
        # (its latencies never land in the tracker at all)
        self.hedge_losses = 0
        fabobs.obs_gauge(
            "fabric_serve_endpoint_healthy", 1.0, endpoint=address
        )

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    def mark_up(self) -> None:
        self.gate.record_success()
        with self._lock:
            flipped = not self._healthy
            self._healthy = True
        if flipped:
            logger.info("sidecar endpoint %s is healthy again", self.address)
            fabobs.obs_gauge(
                "fabric_serve_endpoint_healthy", 1.0, endpoint=self.address
            )

    def mark_down(self, why: object) -> None:
        self.gate.record_failure()
        with self._lock:
            flipped = self._healthy
            self._healthy = False
            self.hedge_losses = 0
        if flipped:
            logger.warning(
                "sidecar endpoint %s evicted (%s); cooling down",
                self.address, why,
            )
            fabobs.obs_gauge(
                "fabric_serve_endpoint_healthy", 0.0, endpoint=self.address
            )

    def hedge_delay_s(self, floor_s: float) -> float:
        """The wait before this endpoint's unanswered batch is hedged:
        2x its own observed p95 (a healthy endpoint almost never takes
        that long, so hedges fire on genuine tail events), floored so
        ordinary jitter never triggers one.  Before any sample exists
        the delay is a multiple of the floor — conservative until the
        endpoint has shown its shape."""
        q95 = self.tracker.quantile(0.95)
        if q95 is None:
            return floor_s * 5.0
        return max(floor_s, 2.0 * q95)


def endpoints_from_env() -> List[str]:
    """``FABRIC_TPU_SERVE_ENDPOINTS`` -> address list (shared read
    discipline: an empty/whitespace value is simply no endpoints)."""
    raw = os.environ.get("FABRIC_TPU_SERVE_ENDPOINTS", "")
    return [a.strip() for a in raw.split(",") if a.strip()]


class SidecarRouter:
    """Provider SPI over N sidecar endpoints with peer-side failover,
    hedging and wire deadlines.

    Single verify/sign/hash/key ops run in-process (the sidecar fleet
    exists for the batch plane), exactly like ``SidecarProvider``."""

    #: health probes get their OWN short budget: a gray endpoint that
    #: answers nothing must cost the probe path seconds, never the full
    #: request timeout
    PROBE_TIMEOUT_S = 2.0
    #: demux poll slice while a hedge race is in flight
    POLL_SLICE_S = 0.02
    #: gray-failure eviction: an endpoint whose EWMA exceeds
    #: SLOW_FACTOR x the best peer EWMA (and the absolute floor) after
    #: SLOW_MIN_SAMPLES, or that loses HEDGE_LOSS_EVICT consecutive
    #: hedges, is evicted through the cooldown ladder
    SLOW_FACTOR = 4.0
    SLOW_FLOOR_S = 0.05
    SLOW_MIN_SAMPLES = 8
    HEDGE_LOSS_EVICT = 2

    def __init__(
        self,
        endpoints: Optional[Sequence[str]] = None,
        fallback=None,
        busy_policy: RetryPolicy = BUSY_POLICY,
        sleeper: Callable[[float], None] = time.sleep,
        qos_class: Optional[int] = None,
        channel: str = "",
        gate_policy: RetryPolicy = ENDPOINT_GATE_POLICY,
        clock: Callable[[], float] = time.monotonic,
        deadline_ms: Optional[int] = None,
        hedge_fraction: Optional[float] = None,
        hedge_min_ms: Optional[float] = None,
    ):
        if endpoints is None:
            endpoints = endpoints_from_env()
        if isinstance(endpoints, str):
            endpoints = [a.strip() for a in endpoints.split(",") if a.strip()]
        if not endpoints:
            raise ValueError(
                "router needs at least one sidecar endpoint "
                "(FABRIC_TPU_SERVE_ENDPOINTS or BCCSP SERVE.Endpoints)"
            )
        self.endpoints: List[_Endpoint] = [
            _Endpoint(addr, gate_policy, clock=clock) for addr in endpoints
        ]
        self.busy_policy = busy_policy
        self._sleeper = sleeper
        self._fallback = fallback
        self._fallback_lock = threading.Lock()
        self.degraded = False  # latched: any batch served in-process
        self.busy_rejects = 0
        self.deadline_expired = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.slow_evictions = 0
        self.deadline_ms = (
            deadline_ms if deadline_ms is not None else deadline_ms_from_env()
        )
        self.hedge_min_s = (
            hedge_min_ms if hedge_min_ms is not None else hedge_min_ms_from_env()
        ) / 1000.0
        self.hedge_budget = _HedgeBudget(
            hedge_fraction
            if hedge_fraction is not None
            else hedge_fraction_from_env()
        )
        self.channel = channel
        if qos_class is None:
            from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

            qos_class = class_for_channel(channel, qos_map_from_env())
        self.qos_class = qos_class

    # -- placement ---------------------------------------------------------
    def _order(self, lanes: int) -> List[_Endpoint]:
        """Endpoint preference for a batch: rendezvous-hashed on the
        lane bucket over SELECTABLE endpoints (gate ready), so buckets
        spread across the fleet and a cooling endpoint is skipped
        without a dial.  Every selectable endpoint stays in the list —
        positions 2..N are the failover (and hedge) ladder."""
        bucket = _route_bucket(lanes)
        ready = [e for e in self.endpoints if e.gate.ready()]  # fablife: disable=pair-imbalance  # selection-filter read, not a guarded attempt: the gate's verdict is recorded by _Endpoint.mark_up/mark_down on the health transitions that own it
        if not ready:
            return []

        def score(e: _Endpoint) -> bytes:
            return hashlib.sha256(
                f"{bucket}|{e.address}".encode("utf-8", "backslashreplace")
            ).digest()

        return sorted(ready, key=score)

    def _probe_ok(
        self, e: _Endpoint, timeout_s: Optional[float] = None
    ) -> bool:
        """A previously-evicted endpoint earns a real batch back with a
        cheap PING first — a probe failure costs microseconds, a routed
        batch failure costs a re-verify.  The probe rides its OWN short
        timeout (one gray endpoint must never stall the health-probe
        path for the duration of a full request timeout), further
        capped by the caller's remaining wire budget when one exists."""
        if e.healthy:
            return True
        probe_s = self.PROBE_TIMEOUT_S
        if timeout_s is not None:
            probe_s = min(probe_s, max(0.0, timeout_s))
        try:
            if e.client.ping(timeout_s=probe_s):
                e.mark_up()
                return True
        except (SidecarUnavailable, proto.ProtocolError) as exc:
            e.mark_down(exc)
        return False

    # -- deadlines ---------------------------------------------------------
    def _deadline(self) -> Optional[float]:
        if not self.deadline_ms:
            return None
        return time.monotonic() + self.deadline_ms / 1000.0

    def _expire(self, keys, signatures, digests, why) -> List[bool]:
        """The batch's wire budget ran out before any endpoint
        answered: hand it to the in-process ladder NOW (bit-exact mask
        through the same degrade path, never a wait past the budget)."""
        self.deadline_expired += 1  # GIL-atomic add, stats only
        fabobs.obs_count(
            "fabric_serve_deadline_expired_total", seam="serve.router"
        )
        return self._degrade(keys, signatures, digests, why)

    # -- gray-failure eviction ---------------------------------------------
    def _note_latency(self, e: _Endpoint, seconds: float) -> None:
        """A served verdict: record the sample, reset the hedge-loss
        streak, and evict the endpoint if its observed latency is an
        outlier against the fleet's best (the sidecar is alive — it
        answered — but too slow to keep in rotation)."""
        e.tracker.record(seconds)
        with e._lock:
            e.hedge_losses = 0
        # the outlier baseline is the best of the endpoints currently
        # IN ROTATION: a dead/evicted peer's EWMA is frozen at its
        # healthy-era values, and judging the survivor against a
        # ghost's baseline would evict the only live endpoint forever
        best: Optional[float] = None
        for other in self.endpoints:
            if (
                other is e
                or other.tracker.ewma_s is None
                or not other.healthy
                or not other.gate.ready()  # fablife: disable=pair-imbalance  # selection-filter read: verdicts are recorded by _Endpoint.mark_up/mark_down, the health transitions that own the gate
            ):
                continue
            if best is None or other.tracker.ewma_s < best:
                best = other.tracker.ewma_s
        if (
            best is not None
            and e.tracker.samples >= self.SLOW_MIN_SAMPLES
            and e.tracker.ewma_s is not None
            and e.tracker.ewma_s > max(self.SLOW_FLOOR_S,
                                       self.SLOW_FACTOR * best)
        ):
            self._evict_slow(
                e,
                f"latency outlier: ewma {e.tracker.ewma_s * 1e3:.1f}ms vs "
                f"fleet best {best * 1e3:.1f}ms",
            )

    def _note_hedge_loss(self, e: _Endpoint) -> None:
        """The primary lost its own hedge: the endpoint is alive (the
        socket is fine) but did not answer inside 2x its own p95 — the
        gray-failure signature.  A short streak evicts it."""
        with e._lock:
            e.hedge_losses += 1
            streak = e.hedge_losses
        if streak >= self.HEDGE_LOSS_EVICT:
            self._evict_slow(
                e, f"lost {streak} consecutive hedges (gray failure)"
            )

    def _evict_slow(self, e: _Endpoint, why: str) -> None:
        # never slow-evict the LAST endpoint in rotation: a slow
        # verdict still beats degrading the whole fleet in-process —
        # gray eviction is a relative judgment and needs a peer to
        # route to (death eviction has no such choice and keeps its
        # own path through mark_down)
        if not any(
            other.healthy and other.gate.ready()  # fablife: disable=pair-imbalance  # selection-filter read: verdicts are recorded by _Endpoint.mark_up/mark_down, the health transitions that own the gate
            for other in self.endpoints
            if other is not e
        ):
            logger.warning(
                "endpoint %s is a latency outlier (%s) but the only "
                "one in rotation; keeping it", e.address, why,
            )
            return
        self.slow_evictions += 1  # GIL-atomic add, stats only
        fabobs.obs_count(
            "fabric_serve_slow_evictions_total", endpoint=e.address
        )
        e.mark_down(why)

    # -- in-process fallback ----------------------------------------------
    def fallback_provider(self):
        with self._fallback_lock:
            if self._fallback is None:
                from fabric_tpu.crypto.bccsp import probe_provider

                self._fallback = probe_provider()
            return self._fallback

    def _degrade(self, keys, signatures, digests, why) -> List[bool]:
        """Every endpoint refused (or the budget expired): in-process
        verification (bit-exact masks), all-False only if the local
        ladder ALSO fails."""
        if not self.degraded:
            logger.warning(
                "all %d sidecar endpoints unavailable (%s); degrading "
                "to in-process verification", len(self.endpoints), why,
            )
            fabobs.obs_count("fabric_degrade_total", seam="serve.router")
            fabobs.obs_trigger("serve.router_degraded")
        self.degraded = True
        try:
            mask = self.fallback_provider().batch_verify(
                keys, signatures, digests
            )
            return list(mask)
        except Exception as exc:  # noqa: BLE001 - double fault: fail closed
            logger.error(
                "in-process fallback failed too (%s): batch fails closed",
                exc,
            )
            return [False] * len(keys)

    # -- one endpoint, one (hedged) attempt --------------------------------
    def _payload_for(
        self, e: _Endpoint, keys, signatures, digests,
        deadline: Optional[float],
    ) -> bytes:
        """Lane payload at THIS endpoint's negotiated revision, with
        the budget REMAINING at encode time when both ends speak v3
        (0 = no budget; the body layout is keyed to the frame rev)."""
        return encode_lanes(  # fabdet: disable=wallclock-in-det  # per-endpoint re-encode with the budget REMAINING: deadline_ms is a semantically time-derived wire field by contract (masks, not deadlines, are the replay surface)
            keys, signatures, digests,
            qos_class=self.qos_class, channel=self.channel,
            deadline_ms=(
                max(1, int((deadline - time.monotonic()) * 1000.0))
                if deadline is not None else 0
            ),
            version=e.client.version,
        )

    def _submit_to(
        self, e: _Endpoint, keys, signatures, digests, attempt: int,
        deadline: Optional[float],
    ) -> Optional[int]:
        """One pipelined dispatch; the token, or None with the endpoint
        marked down (the ladder owns what happens next)."""
        try:
            # chaos seam: an injected routing fault fails THIS attempt
            # on THIS endpoint — the ladder must absorb it
            fault_point("serve.route", key=(e.address, attempt))
            e.client.ensure_connected()
            payload = self._payload_for(e, keys, signatures, digests, deadline)
            return e.client.submit(proto.OP_VERIFY, payload)
        except Exception as exc:  # noqa: BLE001 - endpoint failure (incl. injected) routes to the next rung, never past the mask contract
            logger.debug("endpoint %s submit failed: %s", e.address, exc)
            e.mark_down(exc)
            return None

    def _interpret(
        self, e: _Endpoint, payload: bytes, n: int, t_submit: float,
    ) -> Tuple[str, Optional[List[bool]]]:
        """One reply payload -> ('ok', mask) | ('busy', None) |
        ('dead', None), with health/latency bookkeeping applied."""
        try:
            status, _retry_ms, mask, message = proto.decode_verify_response(
                payload
            )
        except proto.ProtocolError as exc:
            e.mark_down(exc)
            return "dead", None
        if status == proto.ST_OK and mask is not None and len(mask) == n:
            e.mark_up()
            self._note_latency(e, time.monotonic() - t_submit)
            return "ok", mask
        if status == proto.ST_BUSY:
            self.busy_rejects += 1  # GIL-atomic add, stats only
            return "busy", None
        # ST_STOPPING / ST_ERROR / malformed OK: the re-verify-on-kill
        # discipline across endpoints — never trust this settlement,
        # route the batch to the next endpoint
        e.mark_down(message or f"status {status}")
        return "dead", None

    def _try_endpoint(
        self, e: _Endpoint, keys, signatures, digests, attempt: int,
        deadline: Optional[float] = None,
    ) -> Tuple[str, Optional[List[bool]]]:
        """One UN-hedged attempt at one endpoint — the failover
        ladder's unit: ('ok', mask) | ('busy', None) | ('dead', None)
        | ('expired', None).  BUSY is admission control, not endpoint
        failure — the gate only records failures that mean the
        endpoint cannot serve."""
        token = self._submit_to(e, keys, signatures, digests, attempt,
                                deadline)
        if token is None:
            return "dead", None
        return self._await_hedged(
            e, token, time.monotonic(), (), keys, signatures, digests,
            attempt, deadline,
        )

    def _await_hedged(
        self,
        primary: _Endpoint,
        token: int,
        t_submit: float,
        alternates: Sequence[_Endpoint],
        keys, signatures, digests,
        attempt: int,
        deadline: Optional[float],
    ) -> Tuple[str, Optional[List[bool]]]:
        """Wait for the primary's verdict, firing at most ONE hedge at
        the next-ranked endpoint once the primary has been silent for
        its learned hedge delay.  First verdict wins; the loser is
        cancelled best-effort (OP_CANCEL + local demux drop), so a
        verdict from a lost race can never be seen — mask-safety does
        not even depend on verification being pure, though it is.

        Returns ('ok', mask) | ('busy', None) | ('dead', None) |
        ('expired', None)."""
        n = len(keys)
        # overall wall cap: the request timeout (the legacy bound) or
        # the remaining wire budget, whichever is tighter
        stop_at = t_submit + primary.client.request_timeout_s
        if deadline is not None:
            stop_at = min(stop_at, deadline)
        hedge_delay = primary.hedge_delay_s(self.hedge_min_s)
        hedge: Optional[_Endpoint] = None
        hedge_token: Optional[int] = None
        hedge_t0 = 0.0
        hedge_tried = False
        prim_alive = True
        saw_busy = False

        def _drop(e: Optional[_Endpoint], tok: Optional[int]) -> None:
            if e is not None and tok is not None:
                e.client.cancel(tok)

        while True:
            now = time.monotonic()
            if now >= stop_at:
                # walk away from every outstanding socket: the budget
                # (or the request timeout) is the contract, not hope
                _drop(primary if prim_alive else None, token)
                _drop(hedge, hedge_token)
                if deadline is not None and now >= deadline:
                    return "expired", None
                if prim_alive:
                    primary.mark_down("request timeout")
                return ("busy" if saw_busy else "dead"), None
            if not prim_alive and hedge is None:
                return ("busy" if saw_busy else "dead"), None
            # fire the hedge once the primary has been silent too long
            if (
                prim_alive
                and hedge is None
                and not hedge_tried
                and alternates
                and now - t_submit >= hedge_delay
                and (deadline is None or now < deadline)
            ):
                hedge_tried = True
                if self.hedge_budget.try_spend():
                    for alt in alternates:
                        if not alt.healthy:
                            # a hedge goes only to a known-good peer:
                            # dialing a cold/unhealthy alternate here
                            # would stall THIS loop (and the primary's
                            # reply sitting in its socket) for a
                            # connect timeout — the exact tail event
                            # hedging exists to cut
                            continue
                        tok = self._submit_to(
                            alt, keys, signatures, digests, attempt, deadline
                        )
                        if tok is not None:
                            hedge, hedge_token, hedge_t0 = alt, tok, now
                            self.hedges += 1  # GIL-atomic add, stats only
                            fabobs.obs_count("fabric_serve_hedges_total")
                            logger.info(
                                "hedging %d-lane batch: %s silent for "
                                "%.0fms, firing at %s",
                                n, primary.address,
                                (now - t_submit) * 1e3, alt.address,
                            )
                            break
            # poll the primary
            if prim_alive:
                slice_s = min(self.POLL_SLICE_S, max(0.0, stop_at - now))
                if hedge is None:
                    # no race yet: wait in one chunk up to the hedge
                    # fire moment (or the wall cap)
                    slice_s = max(
                        slice_s,
                        min(
                            (t_submit + hedge_delay) - now
                            if alternates and not hedge_tried
                            else self.POLL_SLICE_S * 5,
                            stop_at - now,
                        ),
                    )
                try:
                    payload = primary.client.poll_reply(token, slice_s)
                except SidecarUnavailable as exc:
                    prim_alive = False
                    primary.mark_down(exc)
                    payload = None
                if payload is not None:
                    outcome = self._interpret(primary, payload, n, t_submit)
                    if outcome[0] == "ok":
                        _drop(hedge, hedge_token)
                        return outcome
                    prim_alive = False
                    if outcome[0] == "busy":
                        saw_busy = True
                    if hedge is None:
                        return outcome
            # poll the hedge
            if hedge is not None and hedge_token is not None:
                try:
                    payload = hedge.client.poll_reply(
                        hedge_token, self.POLL_SLICE_S
                    )
                except SidecarUnavailable as exc:
                    hedge.mark_down(exc)
                    hedge, hedge_token = None, None
                    payload = None
                if payload is not None and hedge is not None:
                    outcome = self._interpret(
                        hedge, payload, n, hedge_t0
                    )
                    if outcome[0] == "ok":
                        self.hedge_wins += 1  # GIL-atomic add, stats only
                        fabobs.obs_count("fabric_serve_hedge_wins_total")
                        # the primary lost a race it should have won:
                        # cancel it and score the gray-failure streak
                        if prim_alive:
                            _drop(primary, token)
                            self._note_hedge_loss(primary)
                        return outcome
                    if outcome[0] == "busy":
                        saw_busy = True
                    hedge, hedge_token = None, None

    # -- the batch plane ---------------------------------------------------
    def batch_verify(self, keys, signatures, digests) -> List[bool]:
        return self._batch_verify(keys, signatures, digests,  # fabdet: disable=wallclock-in-det  # wire deadline budget: deadline_ms carries the budget REMAINING at encode time — semantically time-derived protocol field (masks are the replay contract)
                                  self._deadline())

    def _batch_verify(
        self, keys, signatures, digests, deadline: Optional[float]
    ) -> List[bool]:
        """The sync ladder against an ALREADY-STARTED budget: the async
        resolver re-enters here with its original deadline, so a
        busy/dead resolve can never restart the per-batch clock."""
        n = len(keys)
        if n == 0:
            return []
        t0 = time.perf_counter()
        bo = Backoff(self.busy_policy, sleeper=self._sleeper)
        attempt = 0
        while True:
            any_busy = False
            for e in self._order(n):
                remaining: Optional[float] = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return self._expire(
                            keys, signatures, digests,
                            "deadline budget expired",
                        )
                # the probe is capped by the remaining budget; the dial
                # inside a submit still rides connect_timeout_s, but a
                # blackholed endpoint pays that once and then cools
                # behind its dial gate, never per batch
                if not self._probe_ok(e, timeout_s=remaining):
                    continue
                attempt += 1
                token = self._submit_to(
                    e, keys, signatures, digests, attempt, deadline
                )
                if token is None:
                    continue
                self.hedge_budget.earn()
                # hedge alternates: the rest of the failover ladder, in
                # preference order (already gate-selected; probed when
                # the hedge actually fires costs a dial we skip — a
                # submit failure just walks to the next alternate)
                outcome, mask = self._await_hedged(
                    e, token, time.monotonic(),
                    [a for a in self._order(n) if a is not e],
                    keys, signatures, digests, attempt, deadline,
                )
                if outcome == "ok":
                    assert mask is not None
                    fabobs.obs_count(
                        "fabric_verify_lanes_total", n, rung="serve"
                    )
                    fabobs.obs_observe(
                        "fabric_verify_seconds",
                        time.perf_counter() - t0, rung="serve",
                    )
                    return mask
                if outcome == "expired":
                    return self._expire(
                        keys, signatures, digests, "deadline budget expired"
                    )
                if outcome == "busy":
                    any_busy = True
            if any_busy:
                delay = bo.next_delay()
                if delay is not None and deadline is not None:
                    # the pacing budget is capped by the remaining wire
                    # budget — fail over/degrade instead of sleeping
                    # past it (the client shim's discipline, fleetwide)
                    if delay >= deadline - time.monotonic():
                        return self._expire(
                            keys, signatures, digests,
                            "deadline expired during admission backoff",
                        )
                if bo.sleep():
                    continue  # every live endpoint is shedding: pace + retry
            return self._degrade(
                keys, signatures, digests,
                "every endpoint busy (budget spent)" if any_busy
                else "no healthy endpoint",
            )

    def batch_verify_async(self, keys, signatures, digests):
        """Pipelined dispatch through the preferred endpoint; the
        resolver waits with the SAME hedged ladder as the sync path,
        and ANY failure re-routes through sync failover (which owns
        the degrade contract)."""
        n = len(keys)
        if n == 0:
            return list
        t0 = time.perf_counter()
        deadline = self._deadline()
        chosen: Optional[_Endpoint] = None
        token = None
        t_submit = 0.0
        for e in self._order(n):
            if not self._probe_ok(e):
                continue
            token = self._submit_to(e, keys, signatures, digests, 0, deadline)  # fabdet: disable=wallclock-in-det  # failover submit with the remaining budget: deadline_ms is a semantically time-derived wire field by contract (masks are the det surface)
            if token is not None:
                chosen = e
                t_submit = time.monotonic()
                self.hedge_budget.earn()
                break

        def resolve() -> List[bool]:
            if chosen is None or token is None:
                return self._batch_verify(keys, signatures, digests,
                                          deadline)
            outcome, mask = self._await_hedged(
                chosen, token, t_submit,
                [a for a in self._order(n) if a is not chosen],
                keys, signatures, digests, 0, deadline,
            )
            if outcome == "ok":
                assert mask is not None
                fabobs.obs_count("fabric_verify_lanes_total", n, rung="serve")
                fabobs.obs_observe(
                    "fabric_verify_seconds",
                    time.perf_counter() - t0, rung="serve",
                )
                return mask
            if outcome == "expired":
                return self._expire(
                    keys, signatures, digests, "deadline budget expired"
                )
            # busy/dead at resolve time: the sync ladder owns retries,
            # failover and the degrade contract — on the ORIGINAL
            # budget, never a fresh one
            return self._batch_verify(keys, signatures, digests, deadline)

        return resolve

    # -- fleet operations --------------------------------------------------
    def drain_endpoint(self, address: str) -> bool:
        """Ask one sidecar to drain (rolling restart step): True when
        the endpoint acknowledged the OP_DRAIN.  The router marks it
        down immediately so no new batch races the drain."""
        for e in self.endpoints:
            if e.address != address:
                continue
            try:
                reply = e.client.request(proto.OP_DRAIN)
                status, _, _, _ = proto.decode_verify_response(reply)
                e.mark_down("draining (rolling restart)")
                return status == proto.ST_OK
            except (SidecarUnavailable, proto.ProtocolError) as exc:
                e.mark_down(exc)
                return False
        return False

    def for_channel(self, channel_id: str) -> "SidecarRouter":
        """Channel-bound view sharing the endpoint clients, gates and
        hedge budget (one fleet, per-class traffic) — the
        SidecarProvider.for_channel contract over the router."""
        import copy

        from fabric_tpu.serve.qos import class_for_channel, qos_map_from_env

        cls = class_for_channel(channel_id, qos_map_from_env())
        if channel_id == self.channel and cls == self.qos_class:
            return self
        bound = copy.copy(self)
        bound.channel = channel_id
        bound.qos_class = cls
        return bound

    def describe(self) -> dict:
        return {
            "endpoints": [
                {
                    "address": e.address,
                    "healthy": e.healthy,
                    "selectable": e.gate.ready(),
                    "version": e.client.version,
                    "ewma_ms": (
                        round(e.tracker.ewma_s * 1e3, 3)
                        if e.tracker.ewma_s is not None else None
                    ),
                    "p99_ms": (
                        round((e.tracker.quantile(0.99) or 0.0) * 1e3, 3)
                        if e.tracker.samples else None
                    ),
                    "samples": e.tracker.samples,
                }
                for e in self.endpoints
            ],
            "qos_class": proto.qos_name(self.qos_class),
            "channel": self.channel,
            "degraded": self.degraded,
            "busy_rejects": self.busy_rejects,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "slow_evictions": self.slow_evictions,
            "deadline_expired": self.deadline_expired,
        }

    # -- pass-through SPI --------------------------------------------------
    def verify(self, key, signature: bytes, digest: bytes) -> bool:
        return self.fallback_provider().verify(key, signature, digest)

    def batch_hash(self, msgs):
        return self.fallback_provider().batch_hash(msgs)

    def hash(self, msg: bytes) -> bytes:
        return self.fallback_provider().hash(msg)

    def key_import(self, raw: bytes):
        return self.fallback_provider().key_import(raw)

    def key_gen(self):
        return self.fallback_provider().key_gen()

    def sign(self, key, digest: bytes) -> bytes:
        return self.fallback_provider().sign(key, digest)

    def describe_backend(self) -> str:
        if self.degraded:
            return (
                "router-degraded("
                f"{self.fallback_provider().describe_backend()})"
            )
        return "serve-router:" + ",".join(e.address for e in self.endpoints)

    def stop(self) -> None:
        for e in self.endpoints:
            e.client.close()
