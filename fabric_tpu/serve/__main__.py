"""``python -m fabric_tpu.serve`` — run the resident validation sidecar."""

import sys

from fabric_tpu.serve.server import main

if __name__ == "__main__":
    sys.exit(main())
