"""configtxlator — proto <-> JSON translation + config update computation
(reference cmd/configtxlator: proto_encode/proto_decode/compute_update,
minus the REST server — stdin/stdout like its CLI mode).

  python -m fabric_tpu.cli.configtxlator proto_decode \
      --type common.Block --input block.pb [--output block.json]
  python -m fabric_tpu.cli.configtxlator proto_encode \
      --type common.Config --input config.json --output config.pb
  python -m fabric_tpu.cli.configtxlator compute_update \
      --channel_id ch --original orig.pb --updated new.pb --output delta.pb
"""

from __future__ import annotations

import argparse
import sys

from google.protobuf import json_format

from fabric_tpu.protos import ab_pb2, common_pb2, configtx_pb2, peer_pb2

_TYPES = {
    "common.Block": common_pb2.Block,
    "common.Envelope": common_pb2.Envelope,
    "common.Payload": common_pb2.Payload,
    "common.Config": configtx_pb2.Config,
    "common.ConfigUpdate": configtx_pb2.ConfigUpdate,
    "common.ConfigEnvelope": configtx_pb2.ConfigEnvelope,
    "orderer.SeekInfo": ab_pb2.SeekInfo,
    "protos.Transaction": peer_pb2.Transaction,
    "protos.ProposalResponse": peer_pb2.ProposalResponse,
}


def _read(path):
    if path == "-" or path is None:
        return sys.stdin.buffer.read()
    with open(path, "rb") as f:
        return f.read()


def _write(path, data: bytes):
    if path == "-" or path is None:
        sys.stdout.buffer.write(data)
    else:
        with open(path, "wb") as f:
            f.write(data)


def compute_update(
    channel_id: str, original: configtx_pb2.Config, updated: configtx_pb2.Config
) -> configtx_pb2.ConfigUpdate:
    """Minimal update computation (reference configtxlator/update): write
    set = changed/new elements with bumped versions; read set = their
    original versions. Group-level granularity."""
    update = configtx_pb2.ConfigUpdate()
    update.channel_id = channel_id
    update.read_set.CopyFrom(original.channel_group)
    update.write_set.CopyFrom(updated.channel_group)
    _bump_changed(original.channel_group, updated.channel_group, update.write_set)
    return update


def _bump_changed(orig, new, out) -> None:
    """Recursively bump versions of changed values/groups in the write
    set (simplified: bumps at the site of each changed value)."""
    for name, value in new.values.items():
        if name not in orig.values:
            continue
        if orig.values[name].value != value.value:
            out.values[name].version = orig.values[name].version + 1
    for name, group in new.groups.items():
        if name in orig.groups:
            _bump_changed(orig.groups[name], group, out.groups[name])


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="configtxlator")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("version")
    for cmd in ("proto_decode", "proto_encode"):
        p = sub.add_parser(cmd)
        p.add_argument("--type", required=True, choices=sorted(_TYPES))
        p.add_argument("--input", default="-")
        p.add_argument("--output", default="-")
    cu = sub.add_parser("compute_update")
    cu.add_argument("--channel_id", required=True)
    cu.add_argument("--original", required=True)
    cu.add_argument("--updated", required=True)
    cu.add_argument("--output", default="-")
    args = parser.parse_args(argv)
    if args.cmd == "version":
        from fabric_tpu.cli.peer import _version_cmd

        return _version_cmd("configtxlator")

    if args.cmd == "proto_decode":
        msg = _TYPES[args.type]()
        msg.ParseFromString(_read(args.input))
        _write(args.output, json_format.MessageToJson(msg).encode())
    elif args.cmd == "proto_encode":
        msg = json_format.Parse(_read(args.input).decode(), _TYPES[args.type]())
        _write(args.output, msg.SerializeToString())
    elif args.cmd == "compute_update":
        orig = configtx_pb2.Config()
        orig.ParseFromString(_read(args.original))
        new = configtx_pb2.Config()
        new.ParseFromString(_read(args.updated))
        _write(
            args.output,
            compute_update(args.channel_id, orig, new).SerializeToString(),
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
