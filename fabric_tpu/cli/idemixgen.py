"""idemixgen — Idemix crypto-material generator (reference cmd/idemixgen:
ca-keygen + signerconfig, directory layout per idemixmsp docs).

  python -m fabric_tpu.cli.idemixgen ca-keygen [--output idemix-dir]
  python -m fabric_tpu.cli.idemixgen signerconfig [--output idemix-dir] \
      [-u OU] [-e enrollmentId] [--admin]

Layout written (matching the reference tool):

  <output>/ca/IssuerSecretKey            full issuer key (proto)
  <output>/ca/RevocationKey              long-term revocation key (PEM)
  <output>/msp/IssuerPublicKey           issuer public key (proto)
  <output>/msp/RevocationPublicKey       revocation public key (PEM)
  <output>/user/SignerConfig             IdemixMSPSignerConfig (proto)
"""

from __future__ import annotations

import argparse
import os
import sys

from fabric_tpu.msp.idemix_msp import (
    ROLE_ADMIN,
    ROLE_MEMBER,
    generate_issuer,
    generate_signer_config,
)
from fabric_tpu.protos import idemix_pb2


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def ca_keygen(output: str) -> None:
    from cryptography.hazmat.primitives import serialization

    ikey, rev_key = generate_issuer()
    _write(os.path.join(output, "ca", "IssuerSecretKey"), ikey.SerializeToString())
    _write(
        os.path.join(output, "ca", "RevocationKey"),
        rev_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
    )
    _write(
        os.path.join(output, "msp", "IssuerPublicKey"),
        ikey.ipk.SerializeToString(),
    )
    _write(
        os.path.join(output, "msp", "RevocationPublicKey"),
        rev_key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        ),
    )
    print(f"wrote issuer key material under {output}/")


def signerconfig(output: str, ou: str, enrollment: str, admin: bool) -> None:
    from cryptography.hazmat.primitives import serialization

    ikey_path = os.path.join(output, "ca", "IssuerSecretKey")
    rev_path = os.path.join(output, "ca", "RevocationKey")
    if not (os.path.exists(ikey_path) and os.path.exists(rev_path)):
        raise SystemExit(f"run ca-keygen first (no issuer key under {output}/ca)")
    ikey = idemix_pb2.IssuerKey()
    with open(ikey_path, "rb") as f:
        ikey.ParseFromString(f.read())
    with open(rev_path, "rb") as f:
        rev_key = serialization.load_pem_private_key(f.read(), password=None)

    signer = generate_signer_config(
        ikey,
        rev_key,
        ou,
        ROLE_ADMIN if admin else ROLE_MEMBER,
        enrollment,
    )
    _write(
        os.path.join(output, "user", "SignerConfig"),
        signer.SerializeToString(),
    )
    print(f"wrote {output}/user/SignerConfig")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="idemixgen")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("version")
    ca = sub.add_parser("ca-keygen")
    ca.add_argument("--output", default="idemix-config")
    sc = sub.add_parser("signerconfig")
    sc.add_argument("--output", default="idemix-config")
    sc.add_argument("-u", "--org-unit", default="OU1")
    sc.add_argument("-e", "--enrollment-id", default="user1")
    sc.add_argument("--admin", action="store_true")
    args = parser.parse_args(argv)
    if args.cmd == "version":
        from fabric_tpu.cli.peer import _version_cmd

        return _version_cmd("idemixgen")
    if args.cmd == "ca-keygen":
        ca_keygen(args.output)
    else:
        signerconfig(args.output, args.org_unit, args.enrollment_id, args.admin)
    return 0


if __name__ == "__main__":
    sys.exit(main())
