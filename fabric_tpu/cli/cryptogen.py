"""cryptogen — test crypto material generator (reference cmd/cryptogen +
usable-inter-nal/cryptogen).

  python -m fabric_tpu.cli.cryptogen generate \
      --config crypto-config.yaml --output crypto-config

crypto-config.yaml (reference schema subset):

  PeerOrgs:
    - Name: Org1
      Domain: org1.example.com
      MSPID: Org1MSP          # optional, default <Name>MSP
      Template: {Count: 2}    # peers
      Users:    {Count: 1}
  OrdererOrgs:
    - Name: Orderer
      Domain: orderer.example.com
"""

from __future__ import annotations

import argparse
import sys

import yaml

from fabric_tpu.msp.configbuilder import write_org_dir
from fabric_tpu.msp.cryptogen import generate_org


def generate(config_path: str, output: str) -> int:
    with open(config_path) as f:
        cfg = yaml.safe_load(f) or {}
    import os

    for section, sub in (("PeerOrgs", "peerOrganizations"), ("OrdererOrgs", "ordererOrganizations")):
        for spec in cfg.get(section) or []:
            org = generate_org(
                spec["Domain"],
                spec.get("MSPID") or f"{spec['Name']}MSP",
                num_peers=(spec.get("Template") or {}).get("Count", 1),
                num_users=(spec.get("Users") or {}).get("Count", 1),
            )
            out = write_org_dir(org, os.path.join(output, sub))
            print(f"generated {out}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="cryptogen")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("version")
    gen = sub.add_parser("generate")
    gen.add_argument("--config", required=True)
    gen.add_argument("--output", default="crypto-config")
    args = parser.parse_args(argv)
    if args.cmd == "version":
        from fabric_tpu.cli.peer import _version_cmd

        return _version_cmd("cryptogen")
    if args.cmd == "generate":
        return generate(args.config, args.output)
    return 2


if __name__ == "__main__":
    sys.exit(main())
