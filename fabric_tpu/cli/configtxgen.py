"""configtxgen — genesis block / channel-creation tx generator
(reference cmd/configtxgen + usable-inter-nal/configtxgen).

  python -m fabric_tpu.cli.configtxgen \
      -profile TwoOrgsChannel -channelID mychannel \
      -configPath configtx.yaml \
      [-outputBlock genesis.block | -outputCreateChannelTx ch.tx]

configtx.yaml (reference schema subset):

  Organizations:          # anchors referenced by profiles
    - &Org1 {Name: Org1MSP, MSPDir: crypto-config/.../msp, MSPID: Org1MSP,
             AnchorPeers: [{Host: peer0, Port: 7051}]}
  Profiles:
    TwoOrgsOrdererGenesis:
      Orderer: {OrdererType: solo, Addresses: [...], Organizations: [...]}
      Consortiums: {SampleConsortium: {Organizations: [*Org1, ...]}}
    TwoOrgsChannel:
      Consortium: SampleConsortium
      Application: {Organizations: [*Org1, ...]}
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List, Optional

import yaml

from fabric_tpu.channelconfig import encoder
from fabric_tpu.msp.configbuilder import load_msp_config
from fabric_tpu.protos import configtx_pb2, protoutil


def _org_profile(spec: Dict) -> encoder.OrganizationProfile:
    msp_id = spec.get("MSPID") or spec["Name"]
    msp_cfg = load_msp_config(spec["MSPDir"], msp_id)
    anchors = [
        (a["Host"], int(a["Port"])) for a in spec.get("AnchorPeers") or []
    ]
    endpoints = list(spec.get("OrdererEndpoints") or [])
    return encoder.OrganizationProfile(
        name=spec["Name"],
        msp=msp_cfg,
        anchor_peers=anchors,
        orderer_endpoints=endpoints,
    )


def load_profile(config_path: str, profile_name: str) -> encoder.Profile:
    with open(config_path) as f:
        cfg = yaml.safe_load(f)
    profiles = cfg.get("Profiles") or {}
    if profile_name not in profiles:
        raise SystemExit(f"profile {profile_name} not found in {config_path}")
    spec = profiles[profile_name]

    application = None
    if spec.get("Application"):
        application = encoder.ApplicationProfile(
            organizations=[
                _org_profile(o)
                for o in spec["Application"].get("Organizations") or []
            ],
        )
    orderer = None
    if spec.get("Orderer"):
        o = spec["Orderer"]
        batch = o.get("BatchSize") or {}
        orderer = encoder.OrdererProfile(
            orderer_type=o.get("OrdererType", "solo"),
            addresses=list(o.get("Addresses") or []),
            batch_timeout=o.get("BatchTimeout", "2s"),
            max_message_count=batch.get("MaxMessageCount", 500),
            absolute_max_bytes=_size(batch.get("AbsoluteMaxBytes", "10 MB")),
            preferred_max_bytes=_size(batch.get("PreferredMaxBytes", "2 MB")),
            organizations=[
                _org_profile(org) for org in o.get("Organizations") or []
            ],
        )
    consortiums = {
        name: [_org_profile(org) for org in c.get("Organizations") or []]
        for name, c in (spec.get("Consortiums") or {}).items()
    }
    return encoder.Profile(
        consortium=spec.get("Consortium", ""),
        application=application,
        orderer=orderer,
        consortiums=consortiums,
    )


def _size(v) -> int:
    if isinstance(v, int):
        return v
    text = str(v).strip().upper().replace(" ", "")
    for suffix, mult in (("KB", 1024), ("MB", 1024**2), ("GB", 1024**3)):
        if text.endswith(suffix):
            return int(float(text[: -len(suffix)]) * mult)
    return int(text)


def main(argv=None) -> int:
    if argv is None:
        import sys as _sys

        argv = _sys.argv[1:]
    if list(argv) == ["--version"]:
        from fabric_tpu.cli.peer import _version_cmd

        return _version_cmd("configtxgen")
    parser = argparse.ArgumentParser(prog="configtxgen")
    parser.add_argument("-profile", required=True)
    parser.add_argument("-channelID", required=True)
    parser.add_argument("-configPath", default="configtx.yaml")
    parser.add_argument("-outputBlock")
    parser.add_argument("-outputCreateChannelTx")
    parser.add_argument("-inspectBlock")
    args = parser.parse_args(argv)

    if args.inspectBlock:
        from google.protobuf import json_format

        from fabric_tpu.protos import common_pb2

        block = common_pb2.Block()
        with open(args.inspectBlock, "rb") as f:
            block.ParseFromString(f.read())
        print(json_format.MessageToJson(block))
        return 0

    profile = load_profile(args.configPath, args.profile)
    if args.outputBlock:
        block = encoder.genesis_block(profile, args.channelID)
        with open(args.outputBlock, "wb") as f:
            f.write(block.SerializeToString())
        print(f"wrote genesis block {args.outputBlock}")
        return 0
    if args.outputCreateChannelTx:
        if not profile.consortium or profile.application is None:
            raise SystemExit(
                "channel creation requires Consortium + Application"
            )
        update = encoder.channel_creation_config_update(
            args.channelID, profile.consortium, profile.application
        )
        cue = configtx_pb2.ConfigUpdateEnvelope()
        cue.config_update = update.SerializeToString()
        from fabric_tpu.protos import common_pb2

        payload = common_pb2.Payload()
        chdr = protoutil.make_channel_header(
            common_pb2.CONFIG_UPDATE, args.channelID
        )
        payload.header.channel_header = chdr.SerializeToString()
        payload.header.signature_header = (
            common_pb2.SignatureHeader().SerializeToString()
        )
        payload.data = cue.SerializeToString()
        env = common_pb2.Envelope()
        env.payload = payload.SerializeToString()
        with open(args.outputCreateChannelTx, "wb") as f:
            f.write(env.SerializeToString())
        print(f"wrote channel creation tx {args.outputCreateChannelTx}")
        return 0
    raise SystemExit("one of -outputBlock/-outputCreateChannelTx/-inspectBlock required")


if __name__ == "__main__":
    sys.exit(main())
