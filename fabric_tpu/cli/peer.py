"""peer — peer node binary + channel/chaincode client commands
(reference cmd/peer: node start, channel join/list, chaincode
invoke/query over the wire).

  python -m fabric_tpu.cli.peer node start --config core.yaml
  python -m fabric_tpu.cli.peer channel join --config core.yaml -b genesis.block
  python -m fabric_tpu.cli.peer chaincode invoke|query \
      --peerAddresses 127.0.0.1:7051 [...] -o 127.0.0.1:7050 \
      -C mychannel -n mycc -c '{"Args":["put","k","v"]}' \
      --mspDir <user msp dir> --mspID Org1MSP

core.yaml (subset of the reference sampleconfig/core.yaml):

  peer:
    listenAddress: 127.0.0.1:7051
    localMspId: Org1MSP
    mspConfigPath: .../peers/peer0.org1/msp
    fileSystemPath: /var/fabric-tpu/peer0
    orgMspDirs:               # org-level verifying MSPs of the channel
      Org1MSP: .../org1.example.com/msp
      Org2MSP: .../org2.example.com/msp
    ordererEndpoint: 127.0.0.1:7050
    genesisBlocks: [mychannel.block]
    chaincodes:               # endorsement policies (lifecycle analog)
      mycc: "AND('Org1MSP.member','Org2MSP.member')"
  operations:
    listenAddress: 127.0.0.1:9444
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

import yaml

from fabric_tpu.common import flogging
from fabric_tpu.comm.server import channel_to
from fabric_tpu.comm.services import broadcast_envelope, process_proposal
from fabric_tpu.endorser import create_proposal, create_signed_tx
from fabric_tpu.endorser.txbuilder import create_signed_proposal
from fabric_tpu.msp.configbuilder import load_msp, load_signing_identity
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.nodes.peer import PeerNode
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

logger = flogging.must_get_logger("peer.main")


def _load_node(config_path: str) -> PeerNode:
    with open(config_path) as f:
        cfg = yaml.safe_load(f) or {}
    pc = cfg.get("peer") or {}
    msps = [
        load_msp(path, msp_id)
        for msp_id, path in (pc.get("orgMspDirs") or {}).items()
    ]
    mgr = MSPManager(msps)
    signer = load_signing_identity(
        pc["mspConfigPath"], pc.get("localMspId", "DEFAULT")
    )
    cc_policies = {
        name: from_dsl(dsl)
        for name, dsl in (pc.get("chaincodes") or {}).items()
    }

    def registry_factory(channel_id: str) -> ChaincodeRegistry:
        return ChaincodeRegistry(
            [ChaincodeDefinition(n, p) for n, p in cc_policies.items()]
        )

    ops = (cfg.get("operations") or {}).get("listenAddress")
    node = PeerNode(
        pc.get("fileSystemPath", "peer-data"),
        mgr,
        signer,
        registry_factory,
        listen_address=pc.get("listenAddress", "127.0.0.1:0"),
        ops_address=ops,
    )
    # External-builder analog (core/container/externalbuilder): user
    # chaincode loads as python modules, "module.path:ClassName", with
    # optional extra sys.path roots.
    import importlib

    for extra in pc.get("chaincodePath") or []:
        if extra not in sys.path:
            sys.path.insert(0, extra)
    for name, ref in (pc.get("chaincodePlugins") or {}).items():
        mod_name, _, cls_name = ref.partition(":")
        mod = importlib.import_module(mod_name)
        node.support.register(name, getattr(mod, cls_name)())
    for path in pc.get("genesisBlocks") or []:
        block = common_pb2.Block()
        with open(path, "rb") as f:
            block.ParseFromString(f.read())
        node.join_channel(block)
    return node, pc


def node_start(config_path: str, block_until_signal: bool = True) -> PeerNode:
    node, pc = _load_node(config_path)
    addr = node.start()
    orderer = pc.get("ordererEndpoint")
    if orderer:
        for channel_id in list(node.channels):
            node.start_deliver_for_channel(channel_id, orderer)
    logger.info("peer listening on %s", addr)
    print(f"peer listening on {addr}", flush=True)
    if block_until_signal:
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        stop.wait()
        node.stop()
    return node


def _client_signer(args):
    return load_signing_identity(args.mspDir, args.mspID)


def chaincode_cmd(args) -> int:
    signer = _client_signer(args)
    spec = json.loads(args.c)
    cc_args = [a.encode() for a in spec.get("Args", [])]
    bundle = create_proposal(signer, args.C, args.n, cc_args)
    signed = create_signed_proposal(bundle, signer)
    responses = []
    for addr in args.peerAddresses:
        conn = channel_to(addr)
        resp = process_proposal(conn, signed)
        conn.close()
        if resp.response.status != 200:
            print(
                f"endorsement failed on {addr}: {resp.response.message}",
                file=sys.stderr,
            )
            return 1
        responses.append(resp)
    if args.cmd == "query":
        payload = responses[0].response.payload
        if args.b64:
            import base64

            sys.stdout.write(base64.b64encode(payload).decode() + "\n")
        else:
            sys.stdout.buffer.write(payload)
            sys.stdout.flush()
        return 0
    env = create_signed_tx(bundle, signer, responses)
    conn = channel_to(args.o)
    ack = broadcast_envelope(conn, env)
    conn.close()
    if ack.status != common_pb2.SUCCESS:
        print(f"broadcast failed: {ack.info}", file=sys.stderr)
        return 1
    print(f"txid {bundle.tx_id} submitted")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="peer")
    sub = parser.add_subparsers(dest="group", required=True)

    node = sub.add_parser("node")
    node_sub = node.add_subparsers(dest="cmd", required=True)
    st = node_sub.add_parser("start")
    st.add_argument("--config", required=True)

    cc = sub.add_parser("chaincode")
    cc_sub = cc.add_subparsers(dest="cmd", required=True)
    for cmd in ("invoke", "query"):
        p = cc_sub.add_parser(cmd)
        p.add_argument("--peerAddresses", action="append", required=True)
        p.add_argument("-o", default="")
        p.add_argument("-C", required=True)
        p.add_argument("-n", required=True)
        p.add_argument("-c", required=True)
        p.add_argument("--mspDir", required=True)
        p.add_argument("--mspID", required=True)
        p.add_argument("--b64", action="store_true",
                       help="base64-encode query payload output")

    args = parser.parse_args(argv)
    if args.group == "node" and args.cmd == "start":
        node_start(args.config)
        return 0
    if args.group == "chaincode":
        return chaincode_cmd(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
