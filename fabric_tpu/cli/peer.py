"""peer — peer node binary + channel/chaincode client commands
(reference cmd/peer: node start, channel join/list, chaincode
invoke/query over the wire).

  python -m fabric_tpu.cli.peer node start --config core.yaml
  python -m fabric_tpu.cli.peer channel join --config core.yaml -b genesis.block
  python -m fabric_tpu.cli.peer chaincode invoke|query \
      --peerAddresses 127.0.0.1:7051 [...] -o 127.0.0.1:7050 \
      -C mychannel -n mycc -c '{"Args":["put","k","v"]}' \
      --mspDir <user msp dir> --mspID Org1MSP

core.yaml (subset of the reference sampleconfig/core.yaml):

  peer:
    listenAddress: 127.0.0.1:7051
    localMspId: Org1MSP
    mspConfigPath: .../peers/peer0.org1/msp
    fileSystemPath: /var/fabric-tpu/peer0
    orgMspDirs:               # org-level verifying MSPs of the channel
      Org1MSP: .../org1.example.com/msp
      Org2MSP: .../org2.example.com/msp
    ordererEndpoint: 127.0.0.1:7050
    genesisBlocks: [mychannel.block]
    chaincodes:               # endorsement policies (lifecycle analog)
      mycc: "AND('Org1MSP.member','Org2MSP.member')"
  operations:
    listenAddress: 127.0.0.1:9444
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

import yaml

from fabric_tpu.common import flogging
from fabric_tpu.comm.server import channel_to
from fabric_tpu.comm.services import broadcast_envelope, process_proposal
from fabric_tpu.endorser import create_proposal, create_signed_tx
from fabric_tpu.endorser.txbuilder import create_signed_proposal
from fabric_tpu.comm.server import (
    tls_credentials_from_config as _tls_creds,
)
from fabric_tpu.msp.configbuilder import load_msp, load_signing_identity
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.nodes.peer import PeerNode
from fabric_tpu.policy import from_dsl
from fabric_tpu.protos import common_pb2
from fabric_tpu.validation.validator import ChaincodeDefinition, ChaincodeRegistry

logger = flogging.must_get_logger("peer.main")


def _read_pem(path) -> bytes:
    if not path:
        return b""
    with open(path, "rb") as f:
        return f.read()


def _gossip_tls_from_config(tls_cfg):
    """peer.gossip.tls: {cert, key, rootCAs} -> the GossipNode TLS dict
    (mTLS server creds + client pair + own cert DER for the
    ConnEstablish hash binding; require_handshake defaults true when
    TLS is configured)."""
    if not tls_cfg or not tls_cfg.get("cert") or not tls_cfg.get("key"):
        return None
    from cryptography import x509
    from cryptography.hazmat.primitives.serialization import Encoding

    from fabric_tpu.comm.server import tls_server_credentials

    cert_pem = _read_pem(tls_cfg["cert"])
    key_pem = _read_pem(tls_cfg["key"])
    cas = tls_cfg.get("rootCAs") or tls_cfg.get("clientRootCAs")
    if isinstance(cas, str):
        cas = [cas]
    ca_pem = b"".join(_read_pem(p) for p in cas or []) or cert_pem
    return {
        "server_creds": tls_server_credentials(
            cert_pem, key_pem, client_ca_pem=ca_pem
        ),
        "client": (ca_pem, (key_pem, cert_pem)),
        "self_cert_der": x509.load_pem_x509_certificate(
            cert_pem
        ).public_bytes(Encoding.DER),
        "require_handshake": bool(
            tls_cfg.get("requireHandshake", True)
        ),
    }


def _couch_mirror_factory(couch_cfg):
    """ledger.stateCouch: {url} -> per-channel CouchStateAdapter
    factory (None when unconfigured)."""
    if not couch_cfg or not couch_cfg.get("url"):
        return None
    from fabric_tpu.ledger.statecouch import CouchClient, CouchStateAdapter

    client = CouchClient(couch_cfg["url"])

    def factory(channel_id: str):
        return CouchStateAdapter(client, channel_id)

    return factory


def _load_node(config_path: str) -> PeerNode:
    from fabric_tpu.utils.config import apply_env_overrides

    with open(config_path) as f:
        cfg = yaml.safe_load(f) or {}
    # CORE_PEER_LISTENADDRESS=... style overrides (viper behavior,
    # core/peer/config.go)
    apply_env_overrides(cfg, "CORE")
    pc = cfg.get("peer") or {}
    # MSPs + signer default to the software provider (configbuilder)
    # so their setup never probes for an accelerator; the node's BATCH
    # provider below (BCCSP config / default_provider) still does, with
    # the bounded probe + software fallback.
    msps = [
        load_msp(path, msp_id)
        for msp_id, path in (pc.get("orgMspDirs") or {}).items()
    ]
    mgr = MSPManager(msps)
    signer = load_signing_identity(
        pc["mspConfigPath"], pc.get("localMspId", "DEFAULT")
    )
    # chaincode entries are either "name: <policy dsl>" or
    # "name: {policy: <dsl>, plugin: <handler name>}" — the latter binds
    # the namespace to a custom validation plugin from peer.handlers
    cc_defs = {}
    for name, spec in (pc.get("chaincodes") or {}).items():
        if isinstance(spec, dict):
            cc_defs[name] = (
                from_dsl(spec["policy"]),
                spec.get("plugin", "builtin"),
            )
        else:
            cc_defs[name] = (from_dsl(spec), "builtin")

    def registry_factory(channel_id: str) -> ChaincodeRegistry:
        return ChaincodeRegistry(
            [
                ChaincodeDefinition(n, p, plugin=pl)
                for n, (p, pl) in cc_defs.items()
            ]
        )

    # custom validation handlers by module path (reference
    # core/handlers/library/registry.go:134 plugin.Open; here
    # "module.path:Attribute" via dispatcher.PluginRegistry.load)
    from fabric_tpu.validation.dispatcher import PluginRegistry

    plugin_registry = PluginRegistry()
    for extra in pc.get("handlersPath") or []:
        if extra not in sys.path:
            sys.path.insert(0, extra)
    for name, ref in (
        (pc.get("handlers") or {}).get("validation") or {}
    ).items():
        plugin_registry.load(name, ref)

    ops = (cfg.get("operations") or {}).get("listenAddress")
    provider = None
    if cfg.get("BCCSP") or pc.get("BCCSP"):
        from fabric_tpu.crypto.factory import provider_from_config

        provider = provider_from_config(cfg.get("BCCSP") or pc.get("BCCSP"))
    node = PeerNode(
        pc.get("fileSystemPath", "peer-data"),
        mgr,
        signer,
        registry_factory,
        listen_address=pc.get("listenAddress", "127.0.0.1:0"),
        ops_address=ops,
        provider=provider,
        # ledger.deviceMVCC: resolve MVCC on device (SURVEY P5)
        device_mvcc=bool((cfg.get("ledger") or {}).get("deviceMVCC")),
        plugin_registry=plugin_registry,
        tls_credentials=_tls_creds(pc.get("tls")),
        # per-service concurrent-RPC caps (grpc_limiters.go), e.g.
        #   limits: {"protos.Endorser": 50, "protos.Deliver": 25}
        rpc_limits=pc.get("limits"),
        # ledger.stateCouch.url: mirror public state into an external
        # CouchDB in the reference's own doc dialect (statecouchdb)
        state_mirror_factory=_couch_mirror_factory(
            (cfg.get("ledger") or {}).get("stateCouch")
        ),
        orderer_root_ca=_read_pem(pc.get("ordererTLSRootCA")),
    )
    # External-builder analog (core/container/externalbuilder): user
    # chaincode loads as python modules, "module.path:ClassName", with
    # optional extra sys.path roots.
    import importlib

    for extra in pc.get("chaincodePath") or []:
        if extra not in sys.path:
            sys.path.insert(0, extra)
    for name, ref in (pc.get("chaincodePlugins") or {}).items():
        mod_name, _, cls_name = ref.partition(":")
        mod = importlib.import_module(mod_name)
        node.support.register(name, getattr(mod, cls_name)())
    for path in pc.get("genesisBlocks") or []:
        block = common_pb2.Block()
        with open(path, "rb") as f:
            block.ParseFromString(f.read())
        try:
            node.join_channel(block)
        except ValueError as exc:
            if "paused" in str(exc):
                # pause semantics (kvledger pause_resume.go): the peer
                # starts with the paused channel skipped, not down
                logger.warning("skipping paused channel: %s", exc)
                continue
            raise
    return node, pc


def node_start(config_path: str, block_until_signal: bool = True) -> PeerNode:
    node, pc = _load_node(config_path)
    addr = node.start()
    orderer = pc.get("ordererEndpoint")
    gossip_cfg = pc.get("gossip") or {}
    gossip_tls = _gossip_tls_from_config(gossip_cfg.get("tls"))
    if gossip_cfg.get("enabled"):
        # reference peers always run gossip; here it is opt-in config:
        #   gossip:
        #     enabled: true
        #     listenAddress: 127.0.0.1:0     # per-channel port +i
        #     bootstrap: [host:port, ...]    # anchor peers
        # the elected LEADER runs the orderer deliver client and pushes
        # blocks; followers converge via push + pull + anti-entropy
        for channel_id in list(node.channels):
            node.enable_gossip_for_channel(
                channel_id,
                bootstrap=gossip_cfg.get("bootstrap") or [],
                orderer_addr=orderer,
                gossip_listen=gossip_cfg.get(
                    "listenAddress", "127.0.0.1:0"
                ),
                tls=gossip_tls,
            )
            g = node.gossip_nodes[channel_id]
            logger.info(
                "gossip for %s on %s", channel_id, g.addr
            )
            print(f"gossip {channel_id} on {g.addr}", flush=True)
    elif orderer:
        for channel_id in list(node.channels):
            node.start_deliver_for_channel(channel_id, orderer)
    logger.info("peer listening on %s", addr)
    print(f"peer listening on {addr}", flush=True)
    if block_until_signal:
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        stop.wait()
        node.stop()
    return node


def _version_cmd(binary: str) -> int:
    """reference `peer version` (cmd/peer/version): tool, framework
    version, commit, runtime."""
    import platform
    import subprocess

    import fabric_tpu

    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
        ).stdout.strip()
    except Exception:  # noqa: BLE001 - no git in deployment
        commit = "unknown"
    print(f"{binary}:")
    print(f" Version: {fabric_tpu.__version__}")
    print(f" Commit SHA: {commit or 'unknown'}")
    print(f" Go version: n/a (python {platform.python_version()})")
    print(f" OS/Arch: {platform.system().lower()}/{platform.machine()}")
    return 0


def _client_signer(args):
    return load_signing_identity(args.mspDir, args.mspID)


def _dial(args, addr):
    """Client dial honoring --cafile (reference CLI --tls --cafile):
    TLS with the CA when given, plaintext otherwise."""
    ca = getattr(args, "cafile", None)
    if ca:
        with open(ca, "rb") as f:
            return channel_to(addr, f.read())
    return channel_to(addr)


def chaincode_cmd(args) -> int:
    signer = _client_signer(args)
    spec = json.loads(args.c)
    cc_args = [a.encode() for a in spec.get("Args", [])]
    bundle = create_proposal(signer, args.C, args.n, cc_args)
    signed = create_signed_proposal(bundle, signer)
    responses = []
    for addr in args.peerAddresses:
        conn = _dial(args, addr)
        resp = process_proposal(conn, signed)
        conn.close()
        if resp.response.status != 200:
            print(
                f"endorsement failed on {addr}: {resp.response.message}",
                file=sys.stderr,
            )
            return 1
        responses.append(resp)
    if args.cmd == "query":
        payload = responses[0].response.payload
        if args.b64:
            import base64

            sys.stdout.write(base64.b64encode(payload).decode() + "\n")
        else:
            sys.stdout.buffer.write(payload)
            sys.stdout.flush()
        return 0
    env = create_signed_tx(bundle, signer, responses)
    conn = _dial(args, args.o)
    ack = broadcast_envelope(conn, env)
    conn.close()
    if ack.status != common_pb2.SUCCESS:
        print(f"broadcast failed: {ack.info}", file=sys.stderr)
        return 1
    print(f"txid {bundle.tx_id} submitted")
    return 0


def snapshot_cmd(args) -> int:
    """peer snapshot submitrequest/cancelrequest/listpending (reference
    cmd/peer snapshot + snapshotgrpc client): signed requests to the
    peer's /protos.Snapshot service."""
    from fabric_tpu.protos import peer_pb2

    signer = _client_signer(args)
    shdr = common_pb2.SignatureHeader()
    shdr.creator = signer.serialize()
    shdr.nonce = signer.new_nonce()
    if args.cmd == "listpending":
        req = peer_pb2.SnapshotQuery(
            signature_header=shdr.SerializeToString(),
            channel_id=args.channelID,
        )
    else:
        req = peer_pb2.SnapshotRequest(
            signature_header=shdr.SerializeToString(),
            channel_id=args.channelID,
            block_number=args.blockNumber,
        )
    raw = req.SerializeToString()
    signed = peer_pb2.SignedSnapshotRequest(
        request=raw, signature=signer.sign(raw)
    )
    from google.protobuf import empty_pb2

    method, deser = {
        "submitrequest": ("Generate", empty_pb2.Empty.FromString),
        "cancelrequest": ("Cancel", empty_pb2.Empty.FromString),
        "listpending": (
            "QueryPendings",
            peer_pb2.QueryPendingSnapshotsResponse.FromString,
        ),
    }[args.cmd]
    conn = _dial(args, args.peerAddress)
    try:
        stub = conn.unary_unary(
            f"/protos.Snapshot/{method}",
            request_serializer=peer_pb2.SignedSnapshotRequest.SerializeToString,
            response_deserializer=deser,
        )
        resp = stub(signed)
    finally:
        conn.close()
    if args.cmd == "listpending":
        print(
            "Successfully got pending snapshot requests: "
            + json.dumps(sorted(resp.block_numbers))
        )
    elif args.cmd == "submitrequest":
        print("Snapshot request submitted successfully")
    else:
        print("Snapshot request cancelled successfully")
    return 0


def _scc_invoke(addr, signer, channel, cc_name, cc_args, root_ca=b""):
    """One signed proposal to a (system) chaincode; returns the Response
    or exits nonzero on endorsement failure."""
    bundle = create_proposal(signer, channel, cc_name, cc_args)
    signed = create_signed_proposal(bundle, signer)
    conn = channel_to(addr, root_ca or None)
    resp = process_proposal(conn, signed)
    conn.close()
    if resp.response.status != 200:
        print(
            f"{cc_name} call failed on {addr}: {resp.response.message}",
            file=sys.stderr,
        )
        raise SystemExit(1)
    return resp.response


def channel_cmd(args) -> int:
    """peer channel create/join/list/fetch (reference
    usable-inter-nal/peer/channel)."""
    signer = _client_signer(args)
    if args.cmd == "join":
        with open(args.blockpath, "rb") as f:
            block_bytes = f.read()
        _scc_invoke(
            args.peerAddress, signer, "", "cscc",
            [b"JoinChain", block_bytes],
            root_ca=_read_pem(getattr(args, "cafile", None)),
        )
        print("channel joined")
        return 0
    if args.cmd == "joinbysnapshot":
        resp = _scc_invoke(
            args.peerAddress, signer, "", "cscc",
            [b"JoinChainBySnapshot", args.snapshotpath.encode()],
            root_ca=_read_pem(getattr(args, "cafile", None)),
        )
        print(f"channel {resp.payload.decode()} joined from snapshot")
        return 0
    if args.cmd == "list":
        resp = _scc_invoke(
            args.peerAddress, signer, "", "cscc", [b"GetChannels"],
            root_ca=_read_pem(getattr(args, "cafile", None)),
        )
        from fabric_tpu.protos import peer_pb2 as _peer_pb2

        out = _peer_pb2.ChannelQueryResponse()
        out.ParseFromString(resp.payload)
        print("Channels peers has joined: ")
        for ch in out.channels:
            print(ch.channel_id)
        return 0
    if args.cmd == "create":
        from fabric_tpu.channelconfig import configtx as configtx_mod
        from fabric_tpu.protos import configtx_pb2, protoutil

        env = common_pb2.Envelope()
        with open(args.file, "rb") as f:
            env.ParseFromString(f.read())
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        cue = protoutil.unmarshal(configtx_pb2.ConfigUpdateEnvelope, payload.data)
        # sign the config update AND the outer envelope (reference
        # channel create sanitizes + signs with the client identity)
        configtx_mod.sign_config_update(cue, signer)
        payload.data = cue.SerializeToString()
        shdr = protoutil.make_signature_header(
            signer.serialize(), signer.new_nonce()
        )
        payload.header.signature_header = shdr.SerializeToString()
        env.payload = payload.SerializeToString()
        env.signature = signer.sign(env.payload)
        conn = _dial(args, args.orderer)
        ack = broadcast_envelope(conn, env)
        if ack.status != common_pb2.SUCCESS:
            conn.close()
            print(f"channel create failed: {ack.info}", file=sys.stderr)
            return 1
        # fetch the new channel's genesis block (reference: create then
        # deliver block 0)
        out_path = args.outputBlock or f"{args.channelID}.block"
        rc = _fetch_block(conn, signer, args.channelID, 0, out_path)
        conn.close()
        if rc == 0:
            print(f"wrote channel genesis block {out_path}")
        return rc
    if args.cmd == "fetch":
        # like the reference: with -o fetch from the orderer, otherwise
        # from the peer's own deliver service (CORE_PEER_ADDRESS,
        # usable-inter-nal/peer/channel/fetch.go)
        if args.orderer:
            conn, service = _dial(args, args.orderer), "orderer.AtomicBroadcast"
        elif args.peerAddress:
            conn, service = _dial(args, args.peerAddress), "protos.Deliver"
        else:
            print("fetch needs --orderer or --peerAddress", file=sys.stderr)
            return 2
        # oldest | newest | config | <number> (fetch.go selectors)
        if args.block == "oldest":
            number = 0
        elif args.block in ("newest", "config"):
            number = "newest"
        else:
            number = int(args.block)
        rc = _fetch_block(
            conn, signer, args.channelID, number, args.output, service,
            want_config=args.block == "config",
        )
        conn.close()
        if rc == 0:
            print(f"wrote block {args.output}")
        return rc
    return 2


def _last_config_number(block) -> int:
    """LastConfig.index from the SIGNATURES metadata (fetch.go `config`
    selector: newest block points at the latest config block).
    Malformed metadata falls back to 0, like the block writer's own
    recovery parse."""
    from fabric_tpu.orderer.raft_chain import _last_config_index

    return _last_config_index(block)


def _fetch_block(
    conn, signer, channel_id, number, out_path,
    service: str = "orderer.AtomicBroadcast",
    want_config: bool = False,
) -> int:
    from fabric_tpu.comm.services import deliver_stream
    from fabric_tpu.deliver.client import seek_envelope

    env = seek_envelope(channel_id, start=number, stop=number, signer=signer)
    for resp in deliver_stream(conn, env, service=service):
        kind = resp.WhichOneof("Type")
        if kind == "block":
            if want_config:
                # hop from the newest block to the config block it cites
                return _fetch_block(
                    conn,
                    signer,
                    channel_id,
                    _last_config_number(resp.block),
                    out_path,
                    service,
                )
            with open(out_path, "wb") as f:
                f.write(resp.block.SerializeToString())
            return 0
        if kind == "status" and resp.status != common_pb2.SUCCESS:
            print(f"fetch failed: status {resp.status}", file=sys.stderr)
            return 1
    print("fetch failed: no block", file=sys.stderr)
    return 1


def lifecycle_cmd(args) -> int:
    """peer lifecycle chaincode ... (reference
    usable-inter-nal/peer/lifecycle)."""
    if args.cmd == "package":
        from fabric_tpu.chaincode.package import package

        import os

        files = {}
        src = args.path
        lang = getattr(args, "lang", None) or "python"
        if os.path.isdir(src):
            for root, dirs, names in os.walk(src):
                # keep build junk out of the content-hashed package bytes
                dirs[:] = [
                    d
                    for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                ]
                for name in names:
                    if name.endswith(".pyc") or name.startswith("."):
                        continue
                    full = os.path.join(root, name)
                    with open(full, "rb") as f:
                        files[os.path.relpath(full, src)] = f.read()
        else:
            with open(src, "rb") as f:
                files[
                    "connection.json" if lang == "ccaas" else "chaincode.py"
                ] = f.read()
        if lang in ("golang", "node", "java"):
            # reference lifecycle layout (core/chaincode/platforms):
            # source rooted under src/ inside code.tar.gz, metadata.json
            # carries the platform path
            files = {f"src/{rel}": data for rel, data in files.items()}
        raw = package(args.label, files, cc_type=lang, path=src)
        with open(args.outputFile, "wb") as f:
            f.write(raw)
        print(f"wrote chaincode package {args.outputFile}")
        return 0

    signer = _client_signer(args)
    if args.cmd == "install":
        with open(args.packageFile, "rb") as f:
            raw = f.read()
        resp = _scc_invoke(
            args.peerAddress, signer, "", "_lifecycle",
            [b"InstallChaincode", raw],
            root_ca=_read_pem(getattr(args, "cafile", None)),
        )
        print(f"installed package: {resp.payload.decode()}")
        return 0
    if args.cmd == "queryinstalled":
        resp = _scc_invoke(
            args.peerAddress, signer, "", "_lifecycle",
            [b"QueryInstalledChaincodes"],
            root_ca=_read_pem(getattr(args, "cafile", None)),
        )
        for entry in json.loads(resp.payload or b"[]"):
            print(
                f"Package ID: {entry['package_id']}, Label: {entry['label']}"
            )
        return 0
    if args.cmd == "approveformyorg":
        req = json.dumps(
            {
                "channel": args.channelID,
                "name": args.name,
                "package_id": args.package_id,
            }
        ).encode()
        _scc_invoke(
            args.peerAddress, signer, "", "_lifecycle",
            [b"ApproveChaincodeDefinitionForOrg", req],
            root_ca=_read_pem(getattr(args, "cafile", None)),
        )
        print("chaincode definition approved for org")
        return 0
    return 2


def node_admin_cmd(args) -> int:
    """Offline ledger administration (reference usable-inter-nal/peer/
    node pause/resume/rollback/reset/rebuild-dbs): run while the peer
    process is DOWN; the reference enforces that with a file lock, here
    it is the operator's contract."""
    import os

    import yaml as _yaml

    from fabric_tpu.ledger.kvledger import KVLedger

    with open(args.config) as f:
        cfg = _yaml.safe_load(f) or {}
    fs_path = (cfg.get("peer") or {}).get("fileSystemPath", "peer-data")

    def channel_dirs():
        if not os.path.isdir(fs_path):
            return []
        return sorted(
            name
            for name in os.listdir(fs_path)
            if os.path.exists(os.path.join(fs_path, name, f"{name}.chain"))
        )

    if args.cmd == "pause":
        chan_dir = os.path.join(fs_path, args.channelID)
        os.makedirs(chan_dir, exist_ok=True)
        with open(os.path.join(chan_dir, "PAUSED"), "w") as f:
            f.write("paused\n")
        print(f"channel {args.channelID} paused")
        return 0
    if args.cmd == "resume":
        marker = os.path.join(fs_path, args.channelID, "PAUSED")
        if os.path.exists(marker):
            os.remove(marker)
        print(f"channel {args.channelID} resumed")
        return 0
    if args.cmd == "rollback":
        ledger = KVLedger(
            os.path.join(fs_path, args.channelID), args.channelID
        )
        try:
            ledger.rollback(args.blockNumber)
        except ValueError as exc:
            print(f"rollback failed: {exc}", file=sys.stderr)
            return 1
        finally:
            ledger.close()
        print(
            f"channel {args.channelID} rolled back to block "
            f"{args.blockNumber}"
        )
        return 0
    if args.cmd == "rebuild-dbs":
        ledger = KVLedger(
            os.path.join(fs_path, args.channelID), args.channelID
        )
        try:
            ledger.rebuild_dbs()
        except ValueError as exc:
            print(f"rebuild-dbs failed: {exc}", file=sys.stderr)
            return 1
        finally:
            ledger.close()
        print(f"channel {args.channelID} state/history rebuilt")
        return 0
    if args.cmd == "reset":
        # reset.go: every channel back to its genesis block
        for channel_id in channel_dirs():
            ledger = KVLedger(os.path.join(fs_path, channel_id), channel_id)
            try:
                ledger.rollback(0)
            except ValueError as exc:
                print(f"reset {channel_id} failed: {exc}", file=sys.stderr)
                return 1
            finally:
                ledger.close()
            print(f"channel {channel_id} reset to genesis")
        return 0
    return 2


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="peer")
    sub = parser.add_subparsers(dest="group", required=True)

    node = sub.add_parser("node")
    node_sub = node.add_subparsers(dest="cmd", required=True)
    st = node_sub.add_parser("start")
    st.add_argument("--config", required=True)
    # offline ledger admin (reference usable-inter-nal/peer/node:
    # pause.go resume.go rollback.go reset.go rebuilddbs.go)
    for name in ("pause", "resume", "rollback", "rebuild-dbs"):
        p = node_sub.add_parser(name)
        p.add_argument("--config", required=True)
        p.add_argument("-c", "--channelID", required=True)
        if name == "rollback":
            p.add_argument("-b", "--blockNumber", type=int, required=True)
    rs = node_sub.add_parser("reset")
    rs.add_argument("--config", required=True)

    cc = sub.add_parser("chaincode")
    cc_sub = cc.add_subparsers(dest="cmd", required=True)
    for cmd in ("invoke", "query"):
        p = cc_sub.add_parser(cmd)
        p.add_argument("--peerAddresses", action="append", required=True)
        p.add_argument("-o", default="")
        p.add_argument("-C", required=True)
        p.add_argument("-n", required=True)
        p.add_argument("-c", required=True)
        p.add_argument("--mspDir", required=True)
        p.add_argument("--mspID", required=True)
        p.add_argument("--b64", action="store_true",
                       help="base64-encode query payload output")
        p.add_argument("--cafile", default="",
                       help="TLS root CA PEM for peer/orderer dials")

    chan = sub.add_parser("channel")
    chan_sub = chan.add_subparsers(dest="cmd", required=True)
    cj = chan_sub.add_parser("join")
    cj.add_argument("-b", "--blockpath", required=True)
    cjs = chan_sub.add_parser("joinbysnapshot")
    cjs.add_argument("--snapshotpath", required=True)
    cl = chan_sub.add_parser("list")
    ccr = chan_sub.add_parser("create")
    ccr.add_argument("-o", "--orderer", required=True)
    ccr.add_argument("-c", "--channelID", required=True)
    ccr.add_argument("-f", "--file", required=True)
    ccr.add_argument("--outputBlock", default="")
    cf = chan_sub.add_parser("fetch")
    cf.add_argument("block", help="oldest | newest | config | <number>")
    cf.add_argument("output")
    cf.add_argument("-o", "--orderer", default="")
    cf.add_argument("-c", "--channelID", required=True)
    for p in (cj, cjs, cl):
        p.add_argument("--peerAddress", required=True)
    for p in (ccr, cf):
        p.add_argument("--peerAddress", default="")
    for p in (cj, cjs, cl, ccr, cf):
        p.add_argument("--mspDir", required=True)
        p.add_argument("--mspID", required=True)
        p.add_argument("--cafile", default="")

    snap = sub.add_parser("snapshot")
    snap_sub = snap.add_subparsers(dest="cmd", required=True)
    ss = snap_sub.add_parser("submitrequest")
    ss.add_argument("-b", "--blockNumber", type=int, default=0,
                    help="0 = next committed block")
    sc = snap_sub.add_parser("cancelrequest")
    sc.add_argument("-b", "--blockNumber", type=int, required=True)
    sl = snap_sub.add_parser("listpending")
    for p in (ss, sc, sl):
        p.add_argument("-C", "--channelID", required=True)
        p.add_argument("--peerAddress", required=True)
        p.add_argument("--mspDir", required=True)
        p.add_argument("--mspID", required=True)
        p.add_argument("--cafile", default="")

    lc = sub.add_parser("lifecycle")
    lc_sub0 = lc.add_subparsers(dest="noun", required=True)
    lcc = lc_sub0.add_parser("chaincode")
    lc_sub = lcc.add_subparsers(dest="cmd", required=True)
    lp = lc_sub.add_parser("package")
    lp.add_argument("outputFile")
    lp.add_argument("--path", required=True)
    lp.add_argument("--label", required=True)
    lp.add_argument(
        "--lang",
        default="python",
        choices=["python", "golang", "node", "java", "ccaas"],
        help="platform type written to metadata.json (golang/node/java "
        "source roots under src/, the reference lifecycle layout; ccaas "
        "packages connection.json for chaincode-as-a-service)",
    )
    li = lc_sub.add_parser("install")
    li.add_argument("packageFile")
    lq = lc_sub.add_parser("queryinstalled")
    la = lc_sub.add_parser("approveformyorg")
    la.add_argument("-C", "--channelID", required=True)
    la.add_argument("-n", "--name", required=True)
    la.add_argument("--package-id", required=True)
    for p in (li, lq, la):
        p.add_argument("--peerAddress", required=True)
        p.add_argument("--mspDir", required=True)
        p.add_argument("--mspID", required=True)
        p.add_argument("--cafile", default="")

    ver = sub.add_parser("version")

    args = parser.parse_args(argv)
    if args.group == "version":
        return _version_cmd("peer")
    if args.group == "node" and args.cmd == "start":
        node_start(args.config)
        return 0
    if args.group == "node":
        return node_admin_cmd(args)
    if args.group == "chaincode":
        return chaincode_cmd(args)
    if args.group == "channel":
        return channel_cmd(args)
    if args.group == "snapshot":
        return snapshot_cmd(args)
    if args.group == "lifecycle":
        return lifecycle_cmd(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
