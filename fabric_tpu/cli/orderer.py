"""orderer — ordering node binary (reference cmd/orderer +
orderer/common/server/main.go).

  python -m fabric_tpu.cli.orderer start --config orderer.yaml

orderer.yaml (localconfig subset):

  General:
    ListenAddress: 127.0.0.1
    ListenPort: 7050
    LocalMSPID: OrdererMSP
    LocalMSPDir: crypto-config/.../orderers/orderer.../msp
    BootstrapFile: genesis.block     # per-channel genesis to serve
    WorkDir: /var/fabric-tpu/orderer
  Operations:
    ListenAddress: 127.0.0.1:9443
  Cluster:                           # raft cluster membership
    NodeId: 2                        # this orderer's consenter index (1-based)
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

import yaml

from fabric_tpu.common import flogging
from fabric_tpu.msp.configbuilder import load_signing_identity
from fabric_tpu.nodes.orderer import OrdererNode
from fabric_tpu.protos import common_pb2

logger = flogging.must_get_logger("orderer.main")


def start(config_path: str, block_until_signal: bool = True) -> OrdererNode:
    from fabric_tpu.utils.config import apply_env_overrides

    with open(config_path) as f:
        cfg = yaml.safe_load(f) or {}
    # ORDERER_GENERAL_LISTENPORT=... style overrides (viper behavior,
    # orderer/common/localconfig)
    apply_env_overrides(cfg, "ORDERER")
    general = cfg.get("General") or {}
    signer = None
    if general.get("LocalMSPDir"):
        signer = load_signing_identity(
            general["LocalMSPDir"], general.get("LocalMSPID", "OrdererMSP")
        )
    listen = (
        f"{general.get('ListenAddress', '127.0.0.1')}:"
        f"{general.get('ListenPort', 7050)}"
    )
    ops = (cfg.get("Operations") or {}).get("ListenAddress")
    cluster = cfg.get("Cluster") or {}
    tls_creds = None
    tls_cfg = general.get("TLS") or {}
    if tls_cfg.get("Enabled") and tls_cfg.get("Certificate") and tls_cfg.get("PrivateKey"):
        from fabric_tpu.comm.server import CertReloader

        tls_creds = CertReloader(
            tls_cfg["Certificate"],
            tls_cfg["PrivateKey"],
            tls_cfg.get("ClientRootCAs"),
        ).credentials()
    node = OrdererNode(
        general.get("WorkDir", "orderer-data"),
        signer=signer,
        listen_address=listen,
        system_channel_id=general.get("SystemChannel"),
        ops_address=ops,
        raft_node_id=int(cluster.get("NodeId", 1)),
        tls_credentials=tls_creds,
        rpc_limits=general.get("Limits"),
    )
    bootstrap = general.get("BootstrapFile")
    if bootstrap:
        block = common_pb2.Block()
        with open(bootstrap, "rb") as f:
            block.ParseFromString(f.read())
        node.join_channel(block)
    addr = node.start()
    logger.info("orderer listening on %s", addr)
    print(f"orderer listening on {addr}", flush=True)
    if block_until_signal:
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        stop.wait()
        node.stop()
    return node


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="orderer")
    sub = parser.add_subparsers(dest="cmd", required=True)
    st = sub.add_parser("start")
    st.add_argument("--config", required=True)
    args = parser.parse_args(argv)
    if args.cmd == "start":
        start(args.config)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
