"""orderer — ordering node binary (reference cmd/orderer +
orderer/common/server/main.go).

  python -m fabric_tpu.cli.orderer start --config orderer.yaml

orderer.yaml (localconfig subset):

  General:
    ListenAddress: 127.0.0.1
    ListenPort: 7050
    LocalMSPID: OrdererMSP
    LocalMSPDir: crypto-config/.../orderers/orderer.../msp
    BootstrapFile: genesis.block     # per-channel genesis to serve
    WorkDir: /var/fabric-tpu/orderer
  Operations:
    ListenAddress: 127.0.0.1:9443
  Cluster:                           # raft cluster membership
    NodeId: 2                        # this orderer's consenter index (1-based)
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

import yaml

from fabric_tpu.common import flogging
from fabric_tpu.msp.configbuilder import load_signing_identity
from fabric_tpu.nodes.orderer import OrdererNode
from fabric_tpu.protos import common_pb2

logger = flogging.must_get_logger("orderer.main")


def start(config_path: str, block_until_signal: bool = True) -> OrdererNode:
    from fabric_tpu.utils.config import apply_env_overrides

    with open(config_path) as f:
        cfg = yaml.safe_load(f) or {}
    # ORDERER_GENERAL_LISTENPORT=... style overrides (viper behavior,
    # orderer/common/localconfig)
    apply_env_overrides(cfg, "ORDERER")
    general = cfg.get("General") or {}
    signer = None
    if general.get("LocalMSPDir"):
        signer = load_signing_identity(
            general["LocalMSPDir"], general.get("LocalMSPID", "OrdererMSP")
        )
    listen = (
        f"{general.get('ListenAddress', '127.0.0.1')}:"
        f"{general.get('ListenPort', 7050)}"
    )
    ops = (cfg.get("Operations") or {}).get("ListenAddress")
    cluster = cfg.get("Cluster") or {}
    from fabric_tpu.comm.server import tls_credentials_from_config

    tls_creds = tls_credentials_from_config(general.get("TLS"))
    # Cluster.RootCAs (reference localconfig): CA PEMs the INTRA-cluster
    # dials (raft Step + follower block pulls) verify fellow orderers
    # against — without this, enabling server TLS would break consensus
    cluster_root_ca = b""
    ca_paths = cluster.get("RootCAs") or []
    if isinstance(ca_paths, str):
        ca_paths = [ca_paths]
    for p in ca_paths:
        with open(p, "rb") as f:
            cluster_root_ca += f.read()
    if tls_creds is not None and not cluster_root_ca:
        tls_cfg = general.get("TLS") or {}
        # sensible default: trust our own serving CA chain for dials
        cert_path = tls_cfg.get("Certificate") or tls_cfg.get("cert")
        root = tls_cfg.get("RootCAs")
        if isinstance(root, str):
            root = [root]
        for p in root or []:
            with open(p, "rb") as f:
                cluster_root_ca += f.read()
        if not cluster_root_ca and cert_path:
            logger.warning(
                "TLS enabled without Cluster.RootCAs/TLS.RootCAs: "
                "intra-cluster dials stay plaintext and a multi-orderer "
                "raft cluster will not form"
            )
    node = OrdererNode(
        general.get("WorkDir", "orderer-data"),
        signer=signer,
        listen_address=listen,
        system_channel_id=general.get("SystemChannel"),
        ops_address=ops,
        raft_node_id=int(cluster.get("NodeId", 1)),
        tls_credentials=tls_creds,
        rpc_limits=general.get("Limits"),
        cluster_root_ca=cluster_root_ca,
    )
    bootstrap = general.get("BootstrapFile")
    if bootstrap:
        block = common_pb2.Block()
        with open(bootstrap, "rb") as f:
            block.ParseFromString(f.read())
        node.join_channel(block)
    addr = node.start()
    logger.info("orderer listening on %s", addr)
    print(f"orderer listening on {addr}", flush=True)
    if block_until_signal:
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        stop.wait()
        node.stop()
    return node


def _version_cmd() -> int:
    from fabric_tpu.cli.peer import _version_cmd as _v

    return _v("orderer")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="orderer")
    sub = parser.add_subparsers(dest="cmd", required=True)
    st = sub.add_parser("start")
    st.add_argument("--config", required=True)
    sub.add_parser("version")
    args = parser.parse_args(argv)
    if args.cmd == "version":
        return _version_cmd()
    if args.cmd == "start":
        start(args.config)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
