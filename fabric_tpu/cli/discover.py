"""discover — service discovery CLI (reference discovery/cmd: peers,
config, endorsers against a peer's discovery service).

  python -m fabric_tpu.cli.discover peers --server 127.0.0.1:7051 \
      --channel mychannel --mspDir <user msp> --mspID Org1MSP
  python -m fabric_tpu.cli.discover config --server ... --channel ...
  python -m fabric_tpu.cli.discover endorsers --server ... --channel ... \
      --chaincode mycc
"""

from __future__ import annotations

import argparse
import json
import sys

from fabric_tpu.discovery.server import query
from fabric_tpu.discovery.service import DiscoveryError
from fabric_tpu.msp.configbuilder import load_signing_identity


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="discover")
    sub = parser.add_subparsers(dest="cmd", required=True)
    for cmd in ("peers", "config", "endorsers"):
        p = sub.add_parser(cmd)
        p.add_argument("--server", required=True)
        p.add_argument("--channel", required=True)
        p.add_argument("--mspDir", required=True)
        p.add_argument("--mspID", required=True)
        p.add_argument("--cafile", default="",
                       help="TLS root CA PEM for the peer dial")
        if cmd == "endorsers":
            p.add_argument("--chaincode", required=True)

    args = parser.parse_args(argv)
    signer = load_signing_identity(args.mspDir, args.mspID)
    try:
        root_ca = None
        if args.cafile:
            with open(args.cafile, "rb") as f:
                root_ca = f.read()
        result = query(
            args.server,
            signer,
            args.channel,
            args.cmd,
            chaincode=getattr(args, "chaincode", ""),
            root_ca=root_ca,
        )
    except DiscoveryError as exc:
        print(f"discovery failed: {exc}", file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
