"""qscc — ledger query system chaincode (reference core/scc/qscc/query.go).

Functions (args[0]=fn, args[1]=channelID, args[2]=param):
GetChainInfo, GetBlockByNumber, GetBlockByHash, GetTransactionByID,
GetBlockByTxID. Results are serialized protos, matching the reference's
payloads (BlockchainInfo / Block / ProcessedTransaction).

ACL checks run in the endorser via aclmgmt before dispatch; qscc itself
re-checks nothing (the reference checks ACLs inside Invoke — here the
shared aclmgmt hook covers both entry points).
"""

from __future__ import annotations

from typing import Callable, Optional

from fabric_tpu.chaincode.shim import ChaincodeStub, Response, error_response, success
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil

GET_CHAIN_INFO = "GetChainInfo"
GET_BLOCK_BY_NUMBER = "GetBlockByNumber"
GET_BLOCK_BY_HASH = "GetBlockByHash"
GET_TRANSACTION_BY_ID = "GetTransactionByID"
GET_BLOCK_BY_TX_ID = "GetBlockByTxID"


class QSCC:
    def __init__(self, get_ledger: Callable[[str], Optional[KVLedger]]):
        self._get_ledger = get_ledger

    def init(self, stub: ChaincodeStub) -> Response:
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        args = stub.get_args()
        if len(args) < 2:
            return error_response(
                f"Incorrect number of arguments, {len(args)}"
            )
        fname = args[0].decode()
        cid = args[1].decode()
        ledger = self._get_ledger(cid)
        if ledger is None:
            return error_response(f"Invalid chain ID, {cid}")
        if fname != GET_CHAIN_INFO and len(args) < 3:
            return error_response(
                f"missing 3rd argument for operation {fname}"
            )
        if fname == GET_CHAIN_INFO:
            return self._chain_info(ledger)
        if fname == GET_BLOCK_BY_NUMBER:
            return self._block_by_number(ledger, args[2])
        if fname == GET_BLOCK_BY_HASH:
            return self._block_by_hash(ledger, args[2])
        if fname == GET_TRANSACTION_BY_ID:
            return self._tx_by_id(ledger, args[2])
        if fname == GET_BLOCK_BY_TX_ID:
            return self._block_by_txid(ledger, args[2])
        return error_response(f"Requested function {fname} not found.")

    def _chain_info(self, ledger: KVLedger) -> Response:
        info = common_pb2.BlockchainInfo()
        info.height = ledger.height
        store = ledger.block_store
        if ledger.height > 0:
            info.currentBlockHash = store.last_block_hash
            # absent on a snapshot-bootstrapped store with no blocks yet
            last = store.get_block_by_number(ledger.height - 1)
            if last is not None:
                info.previousBlockHash = last.header.previous_hash
        return success(info.SerializeToString())

    def _block_by_number(self, ledger: KVLedger, arg: bytes) -> Response:
        try:
            number = int(arg.decode())
        except ValueError:
            return error_response(f"Failed to parse block number: {arg!r}")
        block = ledger.block_store.get_block_by_number(number)
        if block is None:
            return error_response(f"Fail to get block number {number}")
        return success(block.SerializeToString())

    def _block_by_hash(self, ledger: KVLedger, block_hash: bytes) -> Response:
        block = ledger.block_store.get_block_by_hash(block_hash)
        if block is None:
            return error_response("Fail to get block by hash")
        return success(block.SerializeToString())

    def _tx_by_id(self, ledger: KVLedger, arg: bytes) -> Response:
        txid = arg.decode()
        loc = ledger.block_store.get_tx_loc(txid)
        if loc is None:
            return error_response(
                f"Failed to get transaction with id {txid}"
            )
        block_num, tx_num = loc
        if block_num < 0:
            # pre-snapshot txid: indexed for dedup only, block not stored
            return error_response(
                f"transaction {txid} committed before the ledger snapshot"
            )
        block = ledger.block_store.get_block_by_number(block_num)
        if block is None:
            return error_response(f"Fail to get block {block_num}")
        env = protoutil.get_envelope_from_block_data(block.data.data[tx_num])
        flags = block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER]
        pt = peer_pb2.ProcessedTransaction()
        pt.transactionEnvelope.payload = env.payload
        pt.transactionEnvelope.signature = env.signature
        pt.validationCode = flags[tx_num] if tx_num < len(flags) else 0
        return success(pt.SerializeToString())

    def _block_by_txid(self, ledger: KVLedger, arg: bytes) -> Response:
        loc = ledger.block_store.get_tx_loc(arg.decode())
        if loc is None:
            return error_response(
                f"Failed to get transaction with id {arg.decode()}"
            )
        if loc[0] < 0:
            return error_response(
                f"transaction {arg.decode()} committed before the ledger snapshot"
            )
        block = ledger.block_store.get_block_by_number(loc[0])
        if block is None:
            return error_response(f"Fail to get block {loc[0]}")
        return success(block.SerializeToString())
