"""_lifecycle — chaincode lifecycle system chaincode endpoint (reference
core/chaincode/lifecycle/scc.go; the install/approve half that talks to
the peer's local package store — the org-scoped state the reference keeps
in implicit collections lives peer-locally here).

Functions (argument encodings simplified to JSON/bytes; the governance
semantics — sequence checks, approvals, commit readiness — live in
fabric_tpu.lifecycle.lifecycle):

  InstallChaincode            args[1] = package tar.gz -> package-id
  QueryInstalledChaincodes    -> JSON [{package_id, label}]
  ApproveChaincodeDefinitionForOrg
                              args[1] = JSON {channel, name, package_id}
  GetInstalledChaincodePackage args[1] = package-id -> package bytes
"""

from __future__ import annotations

import json
from typing import Callable

from fabric_tpu.chaincode.shim import ChaincodeStub, Response, error_response, success

INSTALL = "InstallChaincode"
QUERY_INSTALLED = "QueryInstalledChaincodes"
APPROVE = "ApproveChaincodeDefinitionForOrg"
GET_PACKAGE = "GetInstalledChaincodePackage"


class LifecycleSCC:
    def __init__(
        self,
        install: Callable[[bytes], str],
        list_installed: Callable[[], list],
        approve: Callable[[str, str, str], None],
        load_package: Callable[[str], bytes],
    ):
        self._install = install
        self._list = list_installed
        self._approve = approve
        self._load = load_package

    def init(self, stub: ChaincodeStub) -> Response:
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        args = stub.get_args()
        if not args:
            return error_response("lifecycle scc: no function")
        fname = args[0].decode()
        try:
            if fname == INSTALL:
                if len(args) < 2:
                    return error_response("missing chaincode package")
                return success(self._install(args[1]).encode())
            if fname == QUERY_INSTALLED:
                out = [
                    {"package_id": p.package_id, "label": p.label}
                    for p in self._list()
                ]
                return success(json.dumps(out, sort_keys=True).encode())
            if fname == APPROVE:
                if len(args) < 2:
                    return error_response("missing approval request")
                req = json.loads(args[1])
                self._approve(
                    req.get("channel", ""), req["name"], req["package_id"]
                )
                return success()
            if fname == GET_PACKAGE:
                if len(args) < 2:
                    return error_response("missing package id")
                return success(self._load(args[1].decode()))
        except Exception as exc:  # noqa: BLE001 - scc failures become 500s
            return error_response(f"{fname} failed: {exc}")
        return error_response(f"unknown lifecycle function {fname!r}")
