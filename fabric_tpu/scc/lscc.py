"""lscc — legacy lifecycle system chaincode (reference core/scc/lscc/
lscc.go), serving pre-2.0 chaincode queries over the new lifecycle's
definitions: getchaincodes, getid/getccdata, getdepspec stubs.

Deployment itself goes through _lifecycle (fabric_tpu.lifecycle); lscc
here is the query-compatibility surface the reference keeps for old SDKs.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from fabric_tpu.chaincode.shim import ChaincodeStub, Response, error_response, success
from fabric_tpu.protos import peer_pb2

GET_CHAINCODES = "getchaincodes"
GET_CC_INFO = "getid"
GET_CC_DATA = "getccdata"


class LSCC:
    def __init__(
        self,
        # () -> [(name, version)] of committed definitions on this channel
        list_definitions: Callable[[], List[Tuple[str, str]]],
    ):
        self._list_definitions = list_definitions

    def init(self, stub: ChaincodeStub) -> Response:
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        args = stub.get_args()
        if not args:
            return error_response("Incorrect number of arguments, 0")
        fname = args[0].decode().lower()
        if fname in (GET_CHAINCODES, "getchaincodesinfo"):
            resp = peer_pb2.ChaincodeQueryResponse()
            for name, version in sorted(self._list_definitions()):
                info = resp.chaincodes.add()
                info.name = name
                info.version = version
                info.escc = "escc"
                info.vscc = "vscc"
            return success(resp.SerializeToString())
        if fname in (GET_CC_INFO, GET_CC_DATA):
            if len(args) < 3:
                return error_response(
                    f"Incorrect number of arguments, {len(args)}"
                )
            name = args[2].decode()
            for n, version in self._list_definitions():
                if n == name:
                    info = peer_pb2.ChaincodeInfo()
                    info.name = n
                    info.version = version
                    return success(info.SerializeToString())
            return error_response(f"chaincode {name} not found")
        return error_response(f"invalid function to lscc: {fname}")
