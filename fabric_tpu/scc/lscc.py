"""lscc — legacy lifecycle system chaincode (reference core/scc/lscc/
lscc.go: Invoke :797, executeDeployOrUpgrade :580, putChaincodeData
lineage, plus the query surface old SDKs keep using).

Two roles:

* **Legacy deploy/upgrade** for pre-V2_0 channels: writes the
  ChaincodeData record at ("lscc", <name>) and the collection package at
  ("lscc", "<name>~collection") through the invoking tx's simulator, so
  the v12/v13 write-set guards validate the exact shapes this module
  produces and `validation.legacy.LSCCRegistry` resolves policies from
  the committed records.  Name/version syntax rules mirror lscc.go
  (isValidCCNameOrVersion: name `[A-Za-z0-9]+([-_][A-Za-z0-9]+)*`,
  version also allows ``.+-_``).
* **Query surface**: getchaincodes, getid, getccdata (ChaincodeData
  bytes, as the reference returns), getcollectionsconfig.

V2_0 channels deploy through _lifecycle (fabric_tpu.lifecycle); deploy /
upgrade here errors on them, like the reference does once the channel
has migrated.
"""

from __future__ import annotations

import hashlib
import re
from typing import Callable, List, Optional, Tuple

from fabric_tpu.chaincode.shim import ChaincodeStub, Response, error_response, success
from fabric_tpu.protos import peer_pb2

GET_CHAINCODES = "getchaincodes"
GET_CC_INFO = "getid"
GET_CC_DATA = "getccdata"
GET_COLLECTIONS_CONFIG = "getcollectionsconfig"
DEPLOY = "deploy"
UPGRADE = "upgrade"

_NAME_RE = re.compile(r"^[A-Za-z0-9]+([-_][A-Za-z0-9]+)*$")
_VERSION_RE = re.compile(r"^[A-Za-z0-9_.+-]+$")

COLLECTION_SUFFIX = "~collection"


def _collection_key(name: str) -> str:
    return name + COLLECTION_SUFFIX


class LSCC:
    def __init__(
        self,
        # () -> [(name, version)] of committed definitions on this channel
        list_definitions: Callable[[], List[Tuple[str, str]]],
        # (channel_id) -> bool: True when the channel has the V2_0
        # capability and legacy deploys must be refused
        # (lscc.go InvalidCCOnFabricVersionError)
        v20_active: Optional[Callable[[str], bool]] = None,
    ):
        self._list_definitions = list_definitions
        self._v20_active = v20_active or (lambda cid: False)

    def init(self, stub: ChaincodeStub) -> Response:
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        args = stub.get_args()
        if not args:
            return error_response("Incorrect number of arguments, 0")
        fname = args[0].decode().lower()
        if fname in (DEPLOY, UPGRADE):
            return self._deploy_or_upgrade(stub, fname, args)
        if fname in (GET_CHAINCODES, "getchaincodesinfo"):
            return self._get_chaincodes(stub)
        if fname in (GET_CC_INFO, GET_CC_DATA):
            return self._get_cc(stub, fname, args)
        if fname == GET_COLLECTIONS_CONFIG:
            if len(args) < 2:
                return error_response("Incorrect number of arguments, 1")
            raw = stub.get_state(_collection_key(args[1].decode()))
            if raw is None:
                return error_response(
                    f"collections config not defined for chaincode "
                    f"{args[1].decode()}"
                )
            return success(raw)
        return error_response(f"invalid function to lscc: {fname}")

    # -- legacy deploy/upgrade (executeDeployOrUpgrade :580) -------------
    def _deploy_or_upgrade(
        self, stub: ChaincodeStub, fname: str, args
    ) -> Response:
        if self._v20_active(stub.channel_id):
            return error_response(
                "Channel has been migrated to the new lifecycle, "
                "LSCC is no longer supported for deploy/upgrade"
            )
        # args: [fn, channel, depspec, policy?, escc?, vscc?, collections?]
        if len(args) < 3:
            return error_response(
                f"Incorrect number of arguments, {len(args)}"
            )
        spec = peer_pb2.ChaincodeDeploymentSpec()
        try:
            spec.ParseFromString(args[2])
        except Exception:  # noqa: BLE001 - malformed proto
            return error_response("error unmarshalling ChaincodeDeploymentSpec")
        ccid = spec.chaincode_spec.chaincode_id
        name, version = ccid.name, ccid.version
        if not _NAME_RE.match(name or ""):
            return error_response(f"invalid chaincode name '{name}'")
        if not _VERSION_RE.match(version or ""):
            return error_response(f"invalid chaincode version '{version}'")

        existing_raw = stub.get_state(name)
        if fname == DEPLOY and existing_raw is not None:
            return error_response(f"chaincode with name '{name}' already exists")
        if fname == UPGRADE:
            if existing_raw is None:
                return error_response(f"cannot get chaincode data for '{name}'")
            old = peer_pb2.ChaincodeData()
            old.ParseFromString(existing_raw)
            if old.version == version:
                return error_response(
                    f"chaincode '{name}' is already instantiated at "
                    f"version '{version}'"
                )

        # the endorsement policy is REQUIRED and must parse: committing a
        # ChaincodeData with empty/garbage policy bytes would make
        # LSCCRegistry.get() fail forever and brick the chaincode with
        # INVALID_CHAINCODE on every tx (the reference validates/defaults
        # the policy at deploy; lacking the channel-org context its
        # default needs, we require it explicitly)
        if len(args) < 4 or not args[3]:
            return error_response(
                "endorsement policy is required for deploy/upgrade"
            )
        try:
            from fabric_tpu.policy.proto_convert import unmarshal_envelope

            unmarshal_envelope(bytes(args[3]))
        except Exception as e:  # noqa: BLE001 - any parse failure
            return error_response(f"invalid endorsement policy: {e}")

        cd = peer_pb2.ChaincodeData()
        cd.name = name
        cd.version = version
        cd.escc = args[4].decode() if len(args) > 4 and args[4] else "escc"
        cd.vscc = args[5].decode() if len(args) > 5 and args[5] else "vscc"
        cd.policy = bytes(args[3])  # serialized SignaturePolicyEnvelope
        # id: fingerprint of the code package (ccprovider hash lineage)
        cd.id = hashlib.sha256(
            bytes(spec.code_package) + name.encode() + version.encode()
        ).digest()
        stub.put_state(name, cd.SerializeToString())

        if len(args) > 6 and args[6]:
            # collection package: written beside the chaincode record;
            # structural validation is the v13 validator's job on commit
            # (validation.legacy.check_v13_writeset), matching the
            # reference split between lscc and the validation plugin
            stub.put_state(_collection_key(name), bytes(args[6]))
        return success(cd.SerializeToString())

    # -- queries ----------------------------------------------------------
    def _get_chaincodes(self, stub: ChaincodeStub) -> Response:
        resp = peer_pb2.ChaincodeQueryResponse()
        listed = set()
        # committed legacy records first (state), then lifecycle
        # definitions (old SDKs expect one merged view)
        for key, raw in stub.get_state_by_range("", ""):
            if COLLECTION_SUFFIX in key:
                continue
            cd = peer_pb2.ChaincodeData()
            try:
                cd.ParseFromString(raw)
            except Exception:  # noqa: BLE001 - foreign record
                continue
            info = resp.chaincodes.add()
            info.name = cd.name or key
            info.version = cd.version
            info.escc = cd.escc or "escc"
            info.vscc = cd.vscc or "vscc"
            info.id = cd.id
            listed.add(info.name)
        for name, version in sorted(self._list_definitions()):
            if name in listed:
                continue
            info = resp.chaincodes.add()
            info.name = name
            info.version = version
            info.escc = "escc"
            info.vscc = "vscc"
        return success(resp.SerializeToString())

    def _get_cc(self, stub: ChaincodeStub, fname: str, args) -> Response:
        if len(args) < 3:
            return error_response(f"Incorrect number of arguments, {len(args)}")
        name = args[2].decode()
        raw = stub.get_state(name)
        if raw is not None:
            if fname == GET_CC_DATA:
                return success(raw)  # ChaincodeData bytes, as lscc.go returns
            cd = peer_pb2.ChaincodeData()
            cd.ParseFromString(raw)
            info = peer_pb2.ChaincodeInfo()
            info.name = cd.name or name
            info.version = cd.version
            info.id = cd.id
            return success(info.SerializeToString())
        for n, version in self._list_definitions():
            if n == name:
                if fname == GET_CC_DATA:
                    cd = peer_pb2.ChaincodeData()
                    cd.name = n
                    cd.version = version
                    cd.escc = "escc"
                    cd.vscc = "vscc"
                    return success(cd.SerializeToString())
                info = peer_pb2.ChaincodeInfo()
                info.name = n
                info.version = version
                return success(info.SerializeToString())
        return error_response(f"chaincode {name} not found")
