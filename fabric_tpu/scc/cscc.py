"""cscc — configuration system chaincode (reference core/scc/cscc/
configure.go).

Functions: JoinChain (bootstrap a channel from its genesis block),
GetChannels (ChannelQueryResponse), GetConfigBlock (latest config block
bytes), JoinBySnapshot status stubs. The peer node wires `join_chain` to
its channel-creation routine (core/peer createChannel).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from fabric_tpu.chaincode.shim import ChaincodeStub, Response, error_response, success
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil

JOIN_CHAIN = "JoinChain"
GET_CHANNELS = "GetChannels"
GET_CONFIG_BLOCK = "GetConfigBlock"


class CSCC:
    def __init__(
        self,
        join_chain: Callable[[common_pb2.Block], None],
        channel_list: Callable[[], List[str]],
        get_config_block: Callable[[str], Optional[common_pb2.Block]],
    ):
        self._join_chain = join_chain
        self._channel_list = channel_list
        self._get_config_block = get_config_block

    def init(self, stub: ChaincodeStub) -> Response:
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        args = stub.get_args()
        if not args:
            return error_response("Incorrect number of arguments, 0")
        fname = args[0].decode()
        if fname == JOIN_CHAIN:
            if len(args) < 2:
                return error_response("missing genesis block")
            try:
                block = protoutil.unmarshal(common_pb2.Block, args[1])
                self._join_chain(block)
            except Exception as e:  # noqa: BLE001 - report any join failure
                return error_response(f'"JoinChain" request failed: {e}')
            return success()
        if fname == GET_CHANNELS:
            resp = peer_pb2.ChannelQueryResponse()
            for cid in self._channel_list():
                resp.channels.add().channel_id = cid
            return success(resp.SerializeToString())
        if fname == GET_CONFIG_BLOCK:
            if len(args) < 2:
                return error_response("missing channel ID")
            block = self._get_config_block(args[1].decode())
            if block is None:
                return error_response(
                    f"Unknown chain ID, {args[1].decode()}"
                )
            return success(block.SerializeToString())
        return error_response(f"Requested function {fname} not found.")
