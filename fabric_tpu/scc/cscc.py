"""cscc — configuration system chaincode (reference core/scc/cscc/
configure.go).

Functions: JoinChain (bootstrap a channel from its genesis block),
JoinChainBySnapshot (build the channel from an exported ledger snapshot,
configure.go joinChainBySnapshot), GetChannels (ChannelQueryResponse),
GetConfigBlock (latest config block bytes), GetChannelConfig (the
current channel Config proto). The peer node wires `join_chain` /
`join_by_snapshot` to its channel-creation routines (core/peer
createChannel / CreateChannelFromSnapshot).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from fabric_tpu.chaincode.shim import ChaincodeStub, Response, error_response, success
from fabric_tpu.protos import common_pb2, peer_pb2, protoutil

JOIN_CHAIN = "JoinChain"
JOIN_CHAIN_BY_SNAPSHOT = "JoinChainBySnapshot"
GET_CHANNELS = "GetChannels"
GET_CONFIG_BLOCK = "GetConfigBlock"
GET_CHANNEL_CONFIG = "GetChannelConfig"


class CSCC:
    def __init__(
        self,
        join_chain: Callable[[common_pb2.Block], None],
        channel_list: Callable[[], List[str]],
        get_config_block: Callable[[str], Optional[common_pb2.Block]],
        join_by_snapshot: Optional[Callable[[str], str]] = None,
    ):
        self._join_chain = join_chain
        self._channel_list = channel_list
        self._get_config_block = get_config_block
        self._join_by_snapshot = join_by_snapshot

    def init(self, stub: ChaincodeStub) -> Response:
        return success()

    def invoke(self, stub: ChaincodeStub) -> Response:
        args = stub.get_args()
        if not args:
            return error_response("Incorrect number of arguments, 0")
        fname = args[0].decode()
        if fname == JOIN_CHAIN:
            if len(args) < 2:
                return error_response("missing genesis block")
            try:
                block = protoutil.unmarshal(common_pb2.Block, args[1])
                self._join_chain(block)
            except Exception as e:  # noqa: BLE001 - report any join failure
                return error_response(f'"JoinChain" request failed: {e}')
            return success()
        if fname == GET_CHANNELS:
            resp = peer_pb2.ChannelQueryResponse()
            for cid in self._channel_list():
                resp.channels.add().channel_id = cid
            return success(resp.SerializeToString())
        if fname == GET_CONFIG_BLOCK:
            if len(args) < 2:
                return error_response("missing channel ID")
            block = self._get_config_block(args[1].decode())
            if block is None:
                return error_response(
                    f"Unknown chain ID, {args[1].decode()}"
                )
            return success(block.SerializeToString())
        if fname == GET_CHANNEL_CONFIG:
            # the current channel Config proto (configure.go
            # getChannelConfig), extracted from the latest config block
            if len(args) < 2:
                return error_response("missing channel ID")
            block = self._get_config_block(args[1].decode())
            if block is None:
                return error_response(
                    f"Unknown chain ID, {args[1].decode()}"
                )
            try:
                from fabric_tpu.protos import configtx_pb2

                env = protoutil.get_envelope_from_block_data(
                    block.data.data[0]
                )
                payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
                cenv = protoutil.unmarshal(
                    configtx_pb2.ConfigEnvelope, payload.data
                )
                return success(cenv.config.SerializeToString())
            except Exception as e:  # noqa: BLE001 - malformed config block
                return error_response(f"failed to extract config: {e}")
        if fname == JOIN_CHAIN_BY_SNAPSHOT:
            if self._join_by_snapshot is None:
                return error_response(
                    "JoinChainBySnapshot is not enabled on this peer"
                )
            if len(args) < 2 or not args[1]:
                return error_response("missing snapshot directory")
            try:
                channel_id = self._join_by_snapshot(args[1].decode())
            except Exception as e:  # noqa: BLE001 - report join failure
                return error_response(
                    f'"JoinChainBySnapshot" request failed: {e}'
                )
            return success(channel_id.encode())
        return error_response(f"Requested function {fname} not found.")
