from fabric_tpu.scc.qscc import QSCC  # noqa: F401
from fabric_tpu.scc.cscc import CSCC  # noqa: F401
from fabric_tpu.scc.lscc import LSCC  # noqa: F401
