"""Gossip membership (reference gossip/discovery/discovery_impl.go):
alive/dead peer tracking from periodically-gossiped alive messages, with
sequence-number freshness and expiration sweeps, plus leader election
(reference gossip/election/election.go) built on the same view.

Deterministic, tick-driven (like the raft core): callers advance time via
tick() and inject messages via handle_alive(); the network layer carries
the message bytes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple


@dataclass
class PeerState:
    endpoint: str
    seq: int
    last_seen_tick: int
    metadata: bytes = b""
    probed: bool = False  # direct probe sent this suspicion episode


class Membership:
    """One node's view of the channel membership."""

    def __init__(
        self,
        self_id: str,
        endpoint: str = "",
        alive_expiration_ticks: int = 25,
        metadata: bytes = b"",
        suspect_ticks: Optional[int] = None,
    ):
        self.self_id = self_id
        self.endpoint = endpoint
        self.metadata = metadata
        self._seq = 0
        self._now = 0
        self._alive: Dict[str, PeerState] = {}
        self._dead: Dict[str, PeerState] = {}
        self.expiration = alive_expiration_ticks
        # SWIM suspicion (reference discovery: a silent peer is PROBED
        # before it is declared dead — push loss must not kill a live
        # member): silent for > suspect_ticks -> suspect (probe it);
        # silent past expiration -> dead. A fresh alive refutes.
        self.suspect_ticks = (
            suspect_ticks
            if suspect_ticks is not None
            else max(alive_expiration_ticks // 2, 1)
        )
        self._suspected: Set[str] = set()
        # the WHOLE view (_seq, _now, _alive, _dead, _suspected) sees TWO
        # writers: the ticker thread (tick/_expire) and gRPC handler
        # threads (handle_alive answering probes / refuting suspicion).
        # An unsynchronized `_seq += 1` can duplicate a sequence number
        # (a receiver dedups the refutation as stale), and an _expire
        # sweep racing handle_alive can move a peer to _dead while a
        # fresh alive re-inserts it — losing the refutation entirely.
        # fabdep unguarded-shared-write confirmed the _alive/_dead/_now
        # writes; every mutation now holds _lock.
        self._lock = threading.Lock()

    # -- outgoing -----------------------------------------------------------
    def tick(self) -> dict:
        """Advance time; returns this node's alive message to broadcast
        (reference periodicalSendAlive)."""
        with self._lock:
            self._now += 1
        self._expire()
        return self.bump_seq()

    def bump_seq(self) -> dict:
        """A fresh alive WITHOUT advancing local time — membership-probe
        replies need a new sequence number (the prober dedups by seq) but
        must not accelerate this node's expiry clock. The single shared
        alive-dict shape for broadcasts AND probe replies."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        return {
            "id": self.self_id,
            "endpoint": self.endpoint,
            "seq": seq,
            "metadata": self.metadata,
        }

    # -- incoming -----------------------------------------------------------
    def handle_alive(self, msg: dict) -> bool:
        """Returns True if the message advanced our view (and should be
        forwarded — push gossip)."""
        pid = msg["id"]
        if pid == self.self_id:
            return False
        seq = msg["seq"]
        with self._lock:
            known = self._alive.get(pid) or self._dead.get(pid)
            if known is not None and seq <= known.seq:
                return False
            state = PeerState(
                endpoint=msg.get("endpoint", ""),
                seq=seq,
                last_seen_tick=self._now,
                metadata=msg.get("metadata", b""),
            )
            self._dead.pop(pid, None)
            self._suspected.discard(pid)  # fresh alive refutes suspicion
            self._alive[pid] = state
        return True

    def _expire(self) -> None:
        with self._lock:
            for pid in list(self._alive):
                st = self._alive[pid]
                silent = self._now - st.last_seen_tick
                if silent > self.expiration:
                    self._suspected.discard(pid)
                    self._dead[pid] = self._alive.pop(pid)
                elif silent > self.suspect_ticks:
                    self._suspected.add(pid)

    def newly_suspect(self) -> List[str]:
        """Suspects not yet probed this suspicion episode — callers probe
        each ONCE per episode (a refuting alive clears the episode, so a
        peer that goes silent again gets probed again)."""
        out = []
        with self._lock:
            for pid in sorted(self._suspected):
                st = self._alive.get(pid)
                if st is not None and not st.probed:
                    st.probed = True
                    out.append(pid)
        return out

    # -- views --------------------------------------------------------------
    def alive_peers(self) -> List[str]:
        with self._lock:
            return sorted(self._alive)

    def suspect_peers(self) -> List[str]:
        with self._lock:
            return sorted(self._suspected)

    def dead_peers(self) -> List[str]:
        with self._lock:
            return sorted(self._dead)

    def endpoint_of(self, pid: str) -> Optional[str]:
        with self._lock:
            st = self._alive.get(pid)
            return st.endpoint if st else None

    def metadata_of(self, pid: str) -> Optional[bytes]:
        with self._lock:
            st = self._alive.get(pid)
            return st.metadata if st else None


class LeaderElection:
    """Per-channel leader election (reference gossip/election): the peer
    with the smallest id among alive candidates leads; peers declare
    themselves via the membership metadata. Deterministic and quiescent —
    no extra message type needed beyond the alive heartbeats."""

    def __init__(self, membership: Membership):
        self.membership = membership
        self.on_leadership_change: Optional[Callable[[bool], None]] = None
        self._is_leader = False
        # evaluate() runs from the ticker thread AND from gRPC handler
        # threads on membership change; an unguarded test-and-set can
        # fire the transition callback twice (fabdep finding).  The
        # reentrant delivery lock spans compute + callback so two racing
        # transitions cannot deliver their callbacks in inverted order
        # (last callback must match final _is_leader); reentrant because
        # a callback that re-enters gossip may evaluate again.
        self._lock = threading.Lock()
        self._cb_lock = threading.RLock()

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    @property
    def leader(self) -> str:
        candidates = [self.membership.self_id] + self.membership.alive_peers()
        return min(candidates)

    def evaluate(self) -> bool:
        """Recompute leadership after membership changes; fires the
        callback on transitions (reference leaderElection beLeader /
        stopBeingLeader)."""
        with self._cb_lock:
            now_leader = self.leader == self.membership.self_id
            with self._lock:
                changed = now_leader != self._is_leader
                if changed:
                    self._is_leader = now_leader
            if changed and self.on_leadership_change is not None:
                self.on_leadership_change(now_leader)
        return now_leader
