"""Private-data dissemination + reconciliation over gossip (reference
gossip/privdata/pull.go endorsement-time push/pull and
reconcile.go:104-126 the missed-data loop).

Two flows:

* dissemination: at ENDORSEMENT time the endorsing peer pushes each
  private writeset (PrivatePayload) to other peers' transient stores, so
  the data is already local when the block commits (dissemination in
  coordinator.go/pull.go DistributePrivateData).
* reconciliation: a committed block can still record missing collection
  data (this peer was offline or ineligible-then-eligible); the
  reconciler periodically sends RemotePvtDataRequest digests to peers,
  verifies returned payloads against the on-block hashes, and patches
  the pvt store + state via commit_pvt_data_of_old_blocks.
"""

from __future__ import annotations

import hashlib
from typing import Callable, List, Optional, Sequence

from fabric_tpu.protos import gossip_pb2


class PvtDataHandler:
    """Per-channel gossip hooks for private data."""

    def __init__(
        self,
        channel_id: str,
        transient_store,  # coordinator.TransientStore
        # (block_num, tx_num, ns, coll) -> cleartext rwset bytes or None
        pvt_reader: Callable[[int, int, str, str], Optional[bytes]],
        # (ns, coll) -> may this collection be served at all (e.g. this
        # peer's own eligibility / BTL); collection-level gate.
        serve_policy: Optional[Callable[[str, str], bool]] = None,
        # pki_id -> serialized identity (certstore lookup); with
        # requester_eligible set, requests from unknown pki_ids are denied
        resolve_identity: Optional[Callable[[bytes], Optional[bytes]]] = None,
        # (identity_bytes, data, signature) -> bool: verify the request
        # signature under the channel's MSPs
        verify_member_sig: Optional[Callable[[bytes, bytes, bytes], bool]] = None,
        # (ns, coll, identity_bytes) -> does the REQUESTER satisfy the
        # collection's member-orgs policy (pull.go:614,662
        # filterNotEligible / isEligibleByLatestConfig)?  When set,
        # private_req must carry an authenticated identity; unsigned or
        # unknown requesters are served NOTHING.
        requester_eligible: Optional[Callable[[str, str, bytes], bool]] = None,
        # signer hooks for OUR outgoing reconcile requests
        self_pki_id: bytes = b"",
        sign_request: Optional[Callable[[bytes], bytes]] = None,
    ):
        self.channel_id = channel_id
        self.transient = transient_store
        self._pvt_reader = pvt_reader
        self._serve_policy = serve_policy or (lambda ns, coll: True)
        self._resolve_identity = resolve_identity
        self._verify_member_sig = verify_member_sig
        self._requester_eligible = requester_eligible
        self._self_pki_id = self_pki_id
        self._sign_request = sign_request
        # replay window: ordered so eviction drops the OLDEST nonces
        # (a wholesale clear would re-admit every previously consumed
        # request); lock guards check+insert across gossip stream threads
        import collections
        import threading

        self._seen_nonces: "collections.OrderedDict[bytes, None]" = (
            collections.OrderedDict()
        )
        self._nonce_lock = threading.Lock()

    def _authenticated_requester(self, req) -> Optional[bytes]:
        """Resolve + signature-check the requester; None when the request
        cannot be tied to a channel identity, or when its nonce was
        already consumed (replay)."""
        if (
            self._resolve_identity is None
            or self._verify_member_sig is None
            or not req.pki_id
            or not req.signature
            or not req.nonce
        ):
            return None
        identity = self._resolve_identity(bytes(req.pki_id))
        if identity is None:
            return None
        if not self._verify_member_sig(
            identity,
            _request_signing_bytes(req, self.channel_id),
            bytes(req.signature),
        ):
            return None
        # replay gate AFTER signature verification so unauthenticated
        # garbage cannot consume nonces; atomic check+insert (concurrent
        # streams must not both pass the membership test)
        nonce = bytes(req.nonce)
        with self._nonce_lock:
            if nonce in self._seen_nonces:
                return None
            self._seen_nonces[nonce] = None
            while len(self._seen_nonces) > 65536:
                self._seen_nonces.popitem(last=False)  # evict oldest
        return identity

    # -- message handling (wired into GossipNode._handle) ------------------
    def handle(
        self, msg: gossip_pb2.GossipMessage
    ) -> Optional[gossip_pb2.GossipMessage]:
        if msg.channel != self.channel_id:
            return None  # cross-channel pvt traffic is never served
        kind = msg.WhichOneof("content")
        if kind == "private_data":
            p = msg.private_data.payload
            # endorsement-time push lands in the transient store, exactly
            # where the commit-time coordinator looks first
            self.transient.persist(
                p.tx_id, p.namespace, p.collection_name, bytes(p.private_rwset)
            )
            return None
        if kind == "private_req":
            requester: Optional[bytes] = None
            if self._requester_eligible is not None:
                # per-requester eligibility mode: the request must be
                # signed by a resolvable channel identity, and each digest
                # is filtered by the collection's member-orgs policy
                requester = self._authenticated_requester(msg.private_req)
                if requester is None:
                    return None
            resp = gossip_pb2.GossipMessage()
            resp.channel = self.channel_id
            # one eligibility decision per (ns, coll) per request — a
            # reconcile batch repeats the same collection across digests
            elig_memo: dict = {}

            def eligible(ns: str, coll: str) -> bool:
                key = (ns, coll)
                hit = elig_memo.get(key)
                if hit is None:
                    hit = self._requester_eligible(ns, coll, requester)
                    elig_memo[key] = hit
                return hit

            for digest in msg.private_req.digests:
                if not self._serve_policy(digest.namespace, digest.collection):
                    continue
                if self._requester_eligible is not None and not eligible(
                    digest.namespace, digest.collection
                ):
                    continue
                payload = self._pvt_reader(
                    digest.block_seq,
                    digest.seq_in_block,
                    digest.namespace,
                    digest.collection,
                )
                if payload is None:
                    continue
                el = resp.private_res.elements.add()
                el.digest.CopyFrom(digest)
                el.payload = payload
            if resp.private_res.elements:
                return resp
            return None
        return None

    # -- endorsement-time push ---------------------------------------------
    def dissemination_messages(
        self,
        tx_id: str,
        pvt_writes: Sequence,  # [(namespace, collection, rwset_bytes)]
    ) -> List[gossip_pb2.GossipMessage]:
        out = []
        for namespace, collection, raw in pvt_writes:
            msg = gossip_pb2.GossipMessage()
            msg.channel = self.channel_id
            p = msg.private_data.payload
            p.tx_id = tx_id
            p.namespace = namespace
            p.collection_name = collection
            p.private_rwset = raw
            out.append(msg)
        return out

    # -- reconciliation ----------------------------------------------------
    def reconcile_request(
        self, missing
    ) -> Optional[gossip_pb2.GossipMessage]:
        """{block_num: [MissingEntry]} (pvt store get_missing_pvt_data) ->
        one RemotePvtDataRequest (reconcile.go batching)."""
        msg = gossip_pb2.GossipMessage()
        msg.channel = self.channel_id
        for block_num in sorted(missing):
            for m in missing[block_num]:
                if not m.eligible:
                    continue
                d = msg.private_req.digests.add()
                d.namespace = m.namespace
                d.collection = m.collection
                d.block_seq = block_num
                d.seq_in_block = m.tx_num
        if not msg.private_req.digests:
            return None
        if self._sign_request is not None and self._self_pki_id:
            import secrets

            msg.private_req.pki_id = self._self_pki_id
            msg.private_req.nonce = secrets.token_bytes(24)
            msg.private_req.signature = self._sign_request(
                _request_signing_bytes(msg.private_req, self.channel_id)
            )
        return msg


def _request_signing_bytes(req, channel_id: str) -> bytes:
    """Deterministic serialization both sides sign/verify.  Binds the
    CHANNEL, the requester's pki_id, and a fresh nonce alongside the
    digest list (signature field excluded) — without those bindings a
    captured request could be replayed verbatim to any serving peer
    forever and the eligibility gate would be worthless."""
    bare = gossip_pb2.RemotePvtDataRequest()
    for d in req.digests:
        bare.digests.add().CopyFrom(d)
    bare.pki_id = req.pki_id
    bare.nonce = req.nonce
    return channel_id.encode() + b"\x00" + bare.SerializeToString()


def reconcile_response_entries(msg: gossip_pb2.GossipMessage):
    """RemotePvtDataResponse -> [(block_num, tx_num, ns, coll, payload)]."""
    out = []
    for el in msg.private_res.elements:
        out.append(
            (
                el.digest.block_seq,
                el.digest.seq_in_block,
                el.digest.namespace,
                el.digest.collection,
                bytes(el.payload),
            )
        )
    return out
