"""Private-data dissemination + reconciliation over gossip (reference
gossip/privdata/pull.go endorsement-time push/pull and
reconcile.go:104-126 the missed-data loop).

Two flows:

* dissemination: at ENDORSEMENT time the endorsing peer pushes each
  private writeset (PrivatePayload) to other peers' transient stores, so
  the data is already local when the block commits (dissemination in
  coordinator.go/pull.go DistributePrivateData).
* reconciliation: a committed block can still record missing collection
  data (this peer was offline or ineligible-then-eligible); the
  reconciler periodically sends RemotePvtDataRequest digests to peers,
  verifies returned payloads against the on-block hashes, and patches
  the pvt store + state via commit_pvt_data_of_old_blocks.
"""

from __future__ import annotations

import hashlib
from typing import Callable, List, Optional, Sequence

from fabric_tpu.protos import gossip_pb2


class PvtDataHandler:
    """Per-channel gossip hooks for private data."""

    def __init__(
        self,
        channel_id: str,
        transient_store,  # coordinator.TransientStore
        # (block_num, tx_num, ns, coll) -> cleartext rwset bytes or None
        pvt_reader: Callable[[int, int, str, str], Optional[bytes]],
        # (ns, coll) -> may this collection be served to channel members?
        # The reference additionally checks the REQUESTER's org against
        # the collection policy via the TLS-bound peer identity
        # (pull.go); this transport has no per-stream identity yet, so
        # the gate is collection-level (Channel.is_eligible).
        serve_policy: Optional[Callable[[str, str], bool]] = None,
    ):
        self.channel_id = channel_id
        self.transient = transient_store
        self._pvt_reader = pvt_reader
        self._serve_policy = serve_policy or (lambda ns, coll: True)

    # -- message handling (wired into GossipNode._handle) ------------------
    def handle(
        self, msg: gossip_pb2.GossipMessage
    ) -> Optional[gossip_pb2.GossipMessage]:
        if msg.channel != self.channel_id:
            return None  # cross-channel pvt traffic is never served
        kind = msg.WhichOneof("content")
        if kind == "private_data":
            p = msg.private_data.payload
            # endorsement-time push lands in the transient store, exactly
            # where the commit-time coordinator looks first
            self.transient.persist(
                p.tx_id, p.namespace, p.collection_name, bytes(p.private_rwset)
            )
            return None
        if kind == "private_req":
            resp = gossip_pb2.GossipMessage()
            resp.channel = self.channel_id
            for digest in msg.private_req.digests:
                if not self._serve_policy(digest.namespace, digest.collection):
                    continue
                payload = self._pvt_reader(
                    digest.block_seq,
                    digest.seq_in_block,
                    digest.namespace,
                    digest.collection,
                )
                if payload is None:
                    continue
                el = resp.private_res.elements.add()
                el.digest.CopyFrom(digest)
                el.payload = payload
            if resp.private_res.elements:
                return resp
            return None
        return None

    # -- endorsement-time push ---------------------------------------------
    def dissemination_messages(
        self,
        tx_id: str,
        pvt_writes: Sequence,  # [(namespace, collection, rwset_bytes)]
    ) -> List[gossip_pb2.GossipMessage]:
        out = []
        for namespace, collection, raw in pvt_writes:
            msg = gossip_pb2.GossipMessage()
            msg.channel = self.channel_id
            p = msg.private_data.payload
            p.tx_id = tx_id
            p.namespace = namespace
            p.collection_name = collection
            p.private_rwset = raw
            out.append(msg)
        return out

    # -- reconciliation ----------------------------------------------------
    def reconcile_request(
        self, missing
    ) -> Optional[gossip_pb2.GossipMessage]:
        """{block_num: [MissingEntry]} (pvt store get_missing_pvt_data) ->
        one RemotePvtDataRequest (reconcile.go batching)."""
        msg = gossip_pb2.GossipMessage()
        msg.channel = self.channel_id
        for block_num in sorted(missing):
            for m in missing[block_num]:
                if not m.eligible:
                    continue
                d = msg.private_req.digests.add()
                d.namespace = m.namespace
                d.collection = m.collection
                d.block_seq = block_num
                d.seq_in_block = m.tx_num
        if not msg.private_req.digests:
            return None
        return msg


def reconcile_response_entries(msg: gossip_pb2.GossipMessage):
    """RemotePvtDataResponse -> [(block_num, tx_num, ns, coll, payload)]."""
    out = []
    for el in msg.private_res.elements:
        out.append(
            (
                el.digest.block_seq,
                el.digest.seq_in_block,
                el.digest.namespace,
                el.digest.collection,
                bytes(el.payload),
            )
        )
    return out
