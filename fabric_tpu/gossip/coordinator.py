"""Commit entry point (reference gossip/privdata/coordinator.go
StoreBlock): Validate(block) -> assemble private data -> commit, with
transient-store lookups, peer pulls with a retry budget, and a
reconciler for private data that arrived after commit.

The TPU pipeline note: Validate() here is the batched device validator
(fabric_tpu.validation), so StoreBlock is exactly the reference's
coordinator boundary with the goroutine fan-out replaced by one device
batch per block.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from fabric_tpu.protos import common_pb2


@dataclass(frozen=True)
class PvtKey:
    tx_index: int
    namespace: str
    collection: str


class TransientStore:
    """Pre-commit private-data staging, keyed by txid (reference
    core/transientstore): endorsement-time writesets wait here until the
    block arrives."""

    def __init__(self):
        self._by_txid: Dict[str, Dict[Tuple[str, str], bytes]] = {}
        # persist() runs on endorsement (gRPC handler) threads while the
        # commit thread purges and the pvt-assembly path reads (fabdep
        # unguarded-shared-write): the nested per-txid dict makes the
        # setdefault-then-setitem sequence non-atomic even under the GIL
        self._lock = threading.Lock()

    def persist(
        self, txid: str, namespace: str, collection: str, pvt_writeset: bytes
    ) -> None:
        with self._lock:
            self._by_txid.setdefault(txid, {})[
                (namespace, collection)
            ] = pvt_writeset

    def get(
        self, txid: str, namespace: str, collection: str
    ) -> Optional[bytes]:
        with self._lock:
            return self._by_txid.get(txid, {}).get((namespace, collection))

    def purge_by_txids(self, txids: Sequence[str]) -> None:
        with self._lock:
            for t in txids:
                self._by_txid.pop(t, None)

    def purge_below_height(self, height: int) -> None:
        # height-based purge hook (reference PurgeBelowHeight); txid map
        # keeps no heights, so this is driven by the caller's bookkeeping
        pass


@dataclass
class PvtDataRequirement:
    """Private collections a valid tx's rwset hashes reference."""

    txid: str
    keys: List[PvtKey]


class Coordinator:
    """Per-channel commit coordinator."""

    def __init__(
        self,
        channel_id: str,
        validate: Callable[[common_pb2.Block], object],
        commit: Callable[[common_pb2.Block, Dict[PvtKey, bytes]], object],
        transient: Optional[TransientStore] = None,
        fetch_from_peers: Optional[
            Callable[[List[PvtKey]], Dict[PvtKey, bytes]]
        ] = None,
        pvt_requirements: Optional[
            Callable[[common_pb2.Block, object], List[PvtDataRequirement]]
        ] = None,
        pull_retries: int = 3,
    ):
        self.channel_id = channel_id
        self._validate = validate
        self._commit = commit
        self.transient = transient or TransientStore()
        self._fetch = fetch_from_peers or (lambda keys: {})
        self._requirements = pvt_requirements or (lambda block, flags: [])
        self.pull_retries = pull_retries
        # pvt data we could not assemble at commit time -> reconciler
        self.missing: Set[PvtKey] = set()

    def store_block(self, block: common_pb2.Block):
        """Validate -> fetch pvtdata (transient store, then peers with a
        retry budget) -> commit (coordinator.go:149-209). Returns the
        commit result (validation flags)."""
        flags = self._validate(block)

        needed = self._requirements(block, flags)
        assembled: Dict[PvtKey, bytes] = {}
        outstanding: List[Tuple[str, PvtKey]] = []
        for req in needed:
            for key in req.keys:
                data = self.transient.get(
                    req.txid, key.namespace, key.collection
                )
                if data is not None:
                    assembled[key] = data
                else:
                    outstanding.append((req.txid, key))

        retries = self.pull_retries
        while outstanding and retries > 0:
            fetched = self._fetch([k for _, k in outstanding])
            still = []
            for txid, key in outstanding:
                if key in fetched:
                    assembled[key] = fetched[key]
                else:
                    still.append((txid, key))
            outstanding = still
            retries -= 1

        # commit proceeds with what we have; missing keys go to the
        # reconciler (coordinator commits with missing-data tracking)
        for _txid, key in outstanding:
            self.missing.add(key)

        result = self._commit(block, assembled)
        self.transient.purge_by_txids([req.txid for req in needed])
        return result if result is not None else flags

    # -- reconciliation (gossip/privdata/reconcile.go) ---------------------
    def reconcile(
        self,
        store_pvt: Callable[[PvtKey, bytes], None],
    ) -> int:
        """Try to fetch previously-missing private data; returns how many
        keys were recovered."""
        if not self.missing:
            return 0
        fetched = self._fetch(sorted(self.missing, key=lambda k: (k.tx_index, k.namespace, k.collection)))
        recovered = 0
        for key, data in fetched.items():
            if key in self.missing:
                store_pvt(key, data)
                self.missing.discard(key)
                recovered += 1
        return recovered
