"""Gossip state transfer (reference gossip/state/state.go): the ordered
payload buffer between block dissemination and the commit pipeline, plus
anti-entropy catch-up.

deliverPayloads semantics reproduced (state.go:542-585): blocks commit
strictly in sequence from a buffer keyed by block number; duplicates and
stale blocks are dropped; a commit failure aborts the channel (the
reference panics on VSCCExecutionFailure). Anti-entropy (state.go:586-612)
asks taller peers for [height, max) ranges and feeds responses back into
the same buffer.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

from fabric_tpu.protos import common_pb2


class PayloadBuffer:
    """Ordered block buffer (reference gossip/state/payloads_buffer.go)."""

    def __init__(self, next_seq: int):
        self._items: Dict[int, common_pb2.Block] = {}
        self._next = next_seq
        self.dropped = 0

    @property
    def next_seq(self) -> int:
        return self._next

    def push(self, block: common_pb2.Block) -> bool:
        """Accept a block unless stale/duplicate. Returns True if stored."""
        seq = block.header.number
        if seq < self._next or seq in self._items:
            self.dropped += 1
            return False
        self._items[seq] = block
        return True

    def advance_to(self, seq: int) -> None:
        """Sync with commits that bypassed the buffer (the deliver client
        commits directly on the gossip leader): drop buffered blocks below
        seq and move the cursor forward."""
        if seq <= self._next:
            return
        for n in list(self._items):
            if n < seq:
                del self._items[n]
        self._next = seq

    def pop(self) -> Optional[common_pb2.Block]:
        blk = self._items.pop(self._next, None)
        if blk is not None:
            self._next += 1
        return blk

    def ready(self) -> bool:
        return self._next in self._items

    def __len__(self) -> int:
        return len(self._items)


class CommitFailure(Exception):
    """Commit errors abort the channel's processing (the reference panics
    on StoreBlock failure, state.go:570-577)."""


class StateProvider:
    """Per-channel state sync: buffer -> commit loop + anti-entropy."""

    def __init__(
        self,
        channel_id: str,
        commit_block: Callable[[common_pb2.Block], None],
        height: Callable[[], int],
        max_block_dist: int = 100,
    ):
        self.channel_id = channel_id
        self._commit = commit_block
        self._height = height
        self.buffer = PayloadBuffer(height())
        self.max_block_dist = max_block_dist
        self.failed = False
        # The gossip node drives this from the tick thread, gRPC stream
        # handlers and forward threads concurrently; an unguarded pop
        # race would double-commit (and poison the channel via `failed`).
        self._lock = threading.RLock()

    # -- ingest (gossip DataMsg / deliver client / state response) ---------
    def add_payload(self, block: common_pb2.Block, from_gossip: bool = True) -> bool:
        """Reference addPayload: gossiped blocks too far ahead of the
        ledger height are dropped (non-blocking ingest); direct/deliver
        payloads are always buffered."""
        with self._lock:
            self.buffer.advance_to(self._height())
            if from_gossip and block.header.number >= self._height() + self.max_block_dist:
                self.buffer.dropped += 1
                return False
            return self.buffer.push(block)

    # -- commit loop --------------------------------------------------------
    def deliver_payloads(self) -> int:
        """Drain in-order payloads into the committer. Returns number
        committed. Raises CommitFailure on commit error."""
        with self._lock:
            if self.failed:
                raise CommitFailure(
                    f"channel {self.channel_id} previously failed"
                )
            self.buffer.advance_to(self._height())
            committed = 0
            while self.buffer.ready():
                block = self.buffer.pop()
                try:
                    self._commit(block)
                except Exception as e:
                    self.failed = True
                    raise CommitFailure(
                        f"block {block.header.number} commit failed: {e}"
                    ) from e
                committed += 1
            return committed

    # -- anti-entropy -------------------------------------------------------
    def missing_range(self, peer_heights: Sequence[int]) -> Optional[range]:
        """antiEntropy: if some peer is taller, the [our_height, max)
        range to request (state.go:586-616)."""
        if not peer_heights:
            return None
        with self._lock:
            self.buffer.advance_to(self._height())
            max_h = max(peer_heights)
            ours = self.buffer.next_seq
        if max_h <= ours:
            return None
        return range(ours, max_h)

    def handle_state_request(
        self,
        start: int,
        end: int,
        get_block: Callable[[int], Optional[common_pb2.Block]],
        max_blocks: int = 100,
    ) -> List[common_pb2.Block]:
        """Serve a peer's StateRequest [start, end) from our ledger
        (state.go handleStateRequest, range capped)."""
        out = []
        for n in range(start, min(end, start + max_blocks)):
            blk = get_block(n)
            if blk is None:
                break
            out.append(blk)
        return out

    def handle_state_response(self, blocks: Sequence[common_pb2.Block]) -> int:
        """Buffer anti-entropy blocks and drain."""
        for b in blocks:
            self.add_payload(b, from_gossip=False)
        return self.deliver_payloads()
