"""Dedup-expiry message store (reference gossip/msgstore/msgs.go).

The reference keeps every gossiped message in a store whose `Add`
returns false for duplicates and for messages an already-stored one
invalidates (e.g. a newer alive from the same peer invalidates older
ones), and expires entries after a TTL so the memory stays bounded and
a long-dead message can circulate again without being mistaken for a
duplicate. Without it, a push mesh re-forwards every message endlessly.

TPU-native simplification: messages here are identified by an explicit
(key, rank) pair chosen by the caller — (pki_id, seq) for alives,
(seq, 0) for data messages — instead of a generic invalidation
predicate over opaque messages; the semantics (newer rank invalidates
older, equal rank is a duplicate) match the reference's
NewGossipMessageComparator ordering for these types.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Hashable, Tuple


class MessageStore:
    def __init__(self, ttl_s: float = 30.0, max_entries: int = 4096):
        self._ttl = ttl_s
        self._max = max_entries
        self._lock = threading.Lock()
        # key -> (rank, stored_at)
        self._entries: Dict[Hashable, Tuple[int, float]] = {}

    def add(self, key: Hashable, rank: int = 0) -> bool:
        """True if the message is FRESH (process + forward it); False if
        a stored entry with the same key has an equal or newer rank."""
        now = time.monotonic()
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                stored_rank, stored_at = hit
                if now - stored_at < self._ttl and stored_rank >= rank:
                    return False
            if len(self._entries) >= self._max:
                self._expire_locked(now)
                if len(self._entries) >= self._max:
                    # still full: drop the oldest entries (bounded memory
                    # beats perfect dedup, same trade as the reference's
                    # externalLock-less eviction)
                    for k, _ in sorted(
                        self._entries.items(), key=lambda kv: kv[1][1]
                    )[: self._max // 4]:
                        del self._entries[k]
            self._entries[key] = (rank, now)
            return True

    def seen(self, key: Hashable, rank: int = 0) -> bool:
        now = time.monotonic()
        with self._lock:
            hit = self._entries.get(key)
            return (
                hit is not None
                and now - hit[1] < self._ttl
                and hit[0] >= rank
            )

    def _expire_locked(self, now: float) -> None:
        dead = [
            k for k, (_r, at) in self._entries.items() if now - at >= self._ttl
        ]
        for k in dead:
            del self._entries[k]

    def expire_old(self) -> None:
        with self._lock:
            self._expire_locked(time.monotonic())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
