"""Gossip comm over gRPC (reference gossip/comm/comm_impl.go
GossipStream + gossip/gossip_impl.go message routing).

One ``GossipNode`` per peer process:

* serves ``gossip.Gossip/GossipStream`` (client pushes a stream of
  GossipMessages, server replies with its own pending messages — the
  reference's bidi stream collapsed to push + piggyback);
* a tick loop broadcasts SWIM alive messages (fabric_tpu.gossip.
  membership) carrying ledger heights, pushes freshly committed blocks
  (DataMessage) to other members, and runs anti-entropy: when a taller
  peer shows up in the membership view, request the missing block range
  directly (state.go antiEntropy -> StateRequest/StateResponse).

Blocks flow into the per-channel StateProvider buffer and commit in
order through the peer's commit pipeline.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

from fabric_tpu.comm.server import GRPCServer, STREAM_STREAM, channel_to
from fabric_tpu.common.faults import fault_point, faults_enabled
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.gossip.membership import LeaderElection, Membership
from fabric_tpu.gossip.pull import PULL_MEMBERSHIP
from fabric_tpu.gossip.state import StateProvider
from fabric_tpu.protos import common_pb2, gossip_pb2

logger = must_get_logger("gossip.comm")


class GossipNode:
    def __init__(
        self,
        self_id: str,
        channel_id: str,
        state: StateProvider,
        get_block: Callable[[int], Optional[common_pb2.Block]],
        height: Callable[[], int],
        listen_address: str = "127.0.0.1:0",
        tick_interval: float = 0.2,
        identity_bytes: bytes = b"",
        verify_identity=None,
        transient_store=None,
        pvt_reader=None,  # (block, tx, ns, coll) -> bytes|None
        pvt_serve_policy=None,  # (ns, coll) -> bool
        # per-requester pvtdata eligibility (pull.go:614,662): requests
        # are authenticated against the certstore and each digest checked
        # against the collection's member-orgs policy for THAT identity
        pvt_verify_member_sig=None,  # (identity, data, sig) -> bool
        pvt_requester_eligible=None,  # (ns, coll, identity) -> bool
        pvt_sign_request=None,  # (data) -> sig, for our reconcile pulls
        # signed membership (reference SignedGossipMessage): we sign our
        # alive messages with sign_message; with require_signed_alive the
        # server drops alives whose signature does not verify against the
        # certstore identity for the claimed pki_id (forged liveness /
        # endpoint / ledger-height claims)
        sign_message=None,  # (data) -> sig
        require_signed_alive: bool = False,
        # mutual-TLS transport + TLS-bound stream handshake (reference
        # comm_impl.go:563 authenticateRemotePeer): tls_server_creds is a
        # grpc.ServerCredentials built with a client CA (mTLS);
        # tls_client = (root_ca_pem, (key_pem, cert_pem)) for outbound;
        # self_tls_cert_der feeds our ConnEstablish tls_cert_hash. With
        # require_handshake the server refuses streams whose first
        # message is not a ConnEstablish whose signature verifies AND
        # whose tls_cert_hash matches the cert actually presented on the
        # mTLS transport — a stolen MSP identity over an attacker's TLS
        # session, or a spliced stream, is rejected.
        tls_server_creds=None,
        tls_client=None,
        self_tls_cert_der: bytes = b"",
        require_handshake: bool = False,
    ):
        from fabric_tpu.gossip.msgstore import MessageStore
        from fabric_tpu.gossip.pull import CertStore, PullMediator
        from fabric_tpu.gossip.pvtdata import PvtDataHandler

        self.self_id = self_id
        self.channel_id = channel_id
        self.state = state
        self._get_block = get_block
        self._height = height
        self.membership = Membership(self_id)
        self.election = LeaderElection(self.membership)
        # certstore + pull mediator (identity + block anti-entropy)
        self.certstore = CertStore(self_id, identity_bytes, verify_identity)
        self.pull = PullMediator(
            channel_id,
            self.certstore,
            get_block=get_block,
            height=height,
            add_block=self._pull_block_in,
        )
        # dedup-expiry store (gossip/msgstore/msgs.go): stops data-msg
        # forward loops and re-processing in a mesh
        self._msgstore = MessageStore(ttl_s=30.0)
        self._tls_client = tls_client
        self._self_tls_cert_der = self_tls_cert_der
        self._require_handshake = require_handshake
        # private-data push/pull (None transient store -> disabled)
        self.pvt = (
            PvtDataHandler(
                channel_id,
                transient_store,
                pvt_reader or (lambda *a: None),
                serve_policy=pvt_serve_policy,
                resolve_identity=self.certstore.get,
                verify_member_sig=pvt_verify_member_sig,
                requester_eligible=pvt_requester_eligible,
                self_pki_id=self_id.encode(),
                sign_request=pvt_sign_request,
            )
            if transient_store is not None
            else None
        )
        self._sign_message = sign_message
        self._require_signed_alive = require_signed_alive
        self._verify_member_sig = pvt_verify_member_sig
        self._endpoints: Dict[str, str] = {}  # peer id -> endpoint
        # bootstrap anchors (connect() targets): re-introduced on a
        # paced per-anchor backoff until a member answers from that
        # endpoint, so ONE lost hello on a lossy link cannot partition
        # the pair forever (the reference keeps dialing its bootstrap
        # peers; fabchaos gossip_storm drops stream opens and relies on
        # this re-try) — the backoff caps the redial rate so a
        # permanently-decommissioned anchor is not dialed every tick
        # for the node's remaining lifetime
        self._anchors: set = set()
        self._anchor_backoff: Dict[str, list] = {}  # ep -> [next_tick, interval]
        self._conns: Dict[str, object] = {}  # endpoint -> grpc channel
        self._lock = threading.Lock()
        # per-endpoint send sequence, so fault decisions key per stream
        # open (a static endpoint key would degenerate a probabilistic
        # plan into a permanent per-peer partition)
        self._send_seq: Dict[str, int] = {}
        self._stop = threading.Event()
        self._tick_interval = tick_interval

        self.server = GRPCServer(listen_address, credentials=tls_server_creds)
        self.server.register(
            "gossip.Gossip",
            {
                "GossipStream": (
                    STREAM_STREAM,
                    self._gossip_stream,
                    gossip_pb2.GossipMessage.FromString,
                    gossip_pb2.GossipMessage.SerializeToString,
                ),
            },
        )
        self._thread: Optional[threading.Thread] = None
        # in-flight fire-and-forget send threads (forward/push/probe):
        # registered so stop() can reap them instead of leaving sends
        # racing the conn teardown (pruned on every spawn, so the list
        # is bounded by concurrent sends, not node lifetime)
        self._senders: List[threading.Thread] = []

    def _spawn_send(self, endpoint: str, messages) -> None:
        """One async send on its own reaped thread (every push/forward
        path funnels through here — the fablife thread-unjoined
        discipline: no unowned Thread.start())."""
        t = threading.Thread(
            target=self._send, args=(endpoint, messages), daemon=True
        )
        with self._lock:
            self._senders = [s for s in self._senders if s.is_alive()]
            self._senders.append(t)
        t.start()

    def _pull_block_in(self, block: common_pb2.Block) -> None:
        """Pulled blocks enter through the same ordered payload buffer
        as pushed DataMessages — and mark the msgstore so a later pushed
        copy of the same block is neither re-buffered nor re-forwarded."""
        self._msgstore.add(("data", block.header.number))
        if self.state.add_payload(block):
            self._drain()

    # -- server side ------------------------------------------------------
    def _gossip_stream(self, request_iterator, context):
        first = True
        for msg in request_iterator:
            if first:
                first = False
                if msg.WhichOneof("content") == "conn":
                    if not self._handshake_ok(msg.conn, context):
                        if self._require_handshake:
                            return  # refuse the stream (comm_impl.go:563)
                        # permissive mode: an unverifiable handshake is
                        # ignored, the piggybacked messages still flow
                        # (silently killing the stream would blackhole a
                        # mixed-config mesh with no error on either side)
                    continue
                if self._require_handshake:
                    return  # strict mode: no handshake, no service
            reply = self._handle(msg)
            if reply is not None:
                yield reply

    def _handshake_ok(self, conn, context) -> bool:
        """Verify a ConnEstablish: signature over (channel, pki_id,
        tls_cert_hash) against the carried identity, and the hash against
        the TLS cert the client ACTUALLY presented on this connection."""
        import hashlib

        identity = bytes(conn.identity) or self.certstore.get(bytes(conn.pki_id))
        if not identity:
            return False
        if self._verify_member_sig is not None:
            signed = _conn_signing_bytes(
                self.channel_id, bytes(conn.pki_id), bytes(conn.tls_cert_hash)
            )
            if not self._verify_member_sig(
                identity, signed, bytes(conn.signature)
            ):
                return False
        # TLS binding: only checkable when the transport is mTLS (the
        # auth context then carries the verified client cert)
        actual = self._peer_tls_cert_der(context)
        if actual is not None:
            if hashlib.sha256(actual).digest() != bytes(conn.tls_cert_hash):
                return False
        elif self._require_handshake and self._self_tls_cert_der:
            # we are TLS-configured but the client came in without a
            # client cert: refuse rather than accept an unbindable claim
            return False
        # pki_id <-> identity binding: the signature above only proves
        # possession of the key for the identity the CLIENT supplied —
        # nothing yet ties that identity to the claimed pki_id. The
        # certstore's verify hook is the binding authority (the
        # reference derives pki_id from the identity bytes themselves);
        # a rejected or conflicting bind refuses the stream, so a valid
        # member cannot authenticate under another peer's pki_id or
        # pre-poison the first-bind-wins store.
        if not self.certstore.put(bytes(conn.pki_id), identity):
            existing = self.certstore.get(bytes(conn.pki_id))
            if existing != identity:
                return False
        return True

    @staticmethod
    def _peer_tls_cert_der(context):
        try:
            auth = context.auth_context()
        except Exception:  # noqa: BLE001 - non-grpc test contexts
            return None
        pems = auth.get("x509_pem_cert") if auth else None
        if not pems:
            return None
        try:
            from cryptography import x509
            from cryptography.hazmat.primitives.serialization import Encoding

            return x509.load_pem_x509_certificate(pems[0]).public_bytes(
                Encoding.DER
            )
        except Exception:  # noqa: BLE001
            return None

    def _handle(
        self, msg: gossip_pb2.GossipMessage
    ) -> Optional[gossip_pb2.GossipMessage]:
        # per-channel routing: this node serves ONE channel; foreign
        # channel traffic is dropped (gossip channel.go eligibility)
        if msg.channel and msg.channel != self.channel_id:
            return None
        kind = msg.WhichOneof("content")
        if kind == "alive_msg":
            alive = msg.alive_msg
            pid = alive.membership.pki_id.decode()
            if pid == self.self_id:
                return None
            if not self._alive_signature_ok(alive):
                return None
            advanced = self.membership.handle_alive(
                {
                    "id": pid,
                    "endpoint": alive.membership.endpoint,
                    "seq": alive.seq_num,
                    "metadata": alive.membership.ledger_height.to_bytes(8, "big"),
                }
            )
            if advanced:
                # endpoint map follows only FRESH alives — a replayed old
                # (validly signed) alive must not roll the endpoint back
                with self._lock:
                    self._endpoints[pid] = alive.membership.endpoint
                # push-forward fresh alive messages so the view spreads
                # transitively (gossip_impl.go forwards messages that
                # advanced the local view); seq dedup stops loops.  The
                # originator's identity rides along so strict-mode third
                # parties can verify the forwarded signature.
                fwd = [msg]
                origin_identity = self.certstore.get(bytes(alive.membership.pki_id))
                if origin_identity:
                    intro = gossip_pb2.GossipMessage()
                    intro.channel = self.channel_id
                    intro.peer_identity.pki_id = alive.membership.pki_id
                    intro.peer_identity.cert = origin_identity
                    fwd = [intro, msg]
                for endpoint in self._peer_endpoints():
                    if endpoint != alive.membership.endpoint:
                        self._spawn_send(endpoint, fwd)
        elif kind == "data_msg":
            # msgstore dedup: a block seen within the TTL is neither
            # re-buffered nor re-forwarded (msgstore stops forward loops
            # in a mesh; gossip_impl.go handleMessage -> Forward gate)
            if not self._msgstore.add(("data", msg.data_msg.seq_num)):
                return None
            block = common_pb2.Block()
            block.ParseFromString(msg.data_msg.block)
            if self.state.add_payload(block):
                self._drain()
            # push-forward to a bounded random subset (PropagatePeerNum)
            import random as _random

            peers = self._peer_endpoints()
            _random.shuffle(peers)
            for endpoint in peers[:3]:
                self._spawn_send(endpoint, [msg])
        elif kind == "state_request":
            blocks = self.state.handle_state_request(
                msg.state_request.start_seq_num,
                msg.state_request.end_seq_num,
                self._get_block,
            )
            resp = gossip_pb2.GossipMessage()
            resp.channel = self.channel_id
            resp.state_response.blocks.extend(
                b.SerializeToString() for b in blocks
            )
            return resp
        elif kind == "state_response":
            parsed = []
            for raw in msg.state_response.blocks:
                b = common_pb2.Block()
                b.ParseFromString(raw)
                parsed.append(b)
            try:
                self.state.handle_state_response(parsed)
            except Exception as exc:
                logger.debug("state response rejected: %s", exc)
        elif kind in (
            "hello",
            "data_dig",
            "data_req",
            "data_update",
            "peer_identity",
        ):
            if (
                kind == "hello"
                and msg.hello.msg_type == PULL_MEMBERSHIP
            ):
                # direct membership probe of a suspect (discovery
                # MembershipRequest): answer with OUR fresh alive so the
                # prober refutes the suspicion
                return self._alive_message(probe_reply=True)
            return self.pull.handle(msg)
        elif kind in ("private_data", "private_req"):
            if self.pvt is not None:
                return self.pvt.handle(msg)
        elif kind == "private_res":
            if self.pvt is not None and self._reconcile_commit is not None:
                from fabric_tpu.gossip.pvtdata import (
                    reconcile_response_entries,
                )

                try:
                    self._reconcile_commit(reconcile_response_entries(msg))
                except Exception as exc:
                    logger.debug("pvtdata reconcile commit failed: %s", exc)
        return None

    def _drain(self) -> None:
        try:
            self.state.deliver_payloads()
        except Exception as exc:
            logger.debug("payload delivery failed: %s", exc)

    def _alive_signature_ok(self, alive) -> bool:
        """Membership authentication (reference aliveMsgStore validation):
        verify the signature over the alive content against the certstore
        identity for the claimed pki_id.  Unsigned alives pass only in
        permissive mode (unit-test topologies without signers); a PRESENT
        signature is always checked when a verifier is configured."""
        if not alive.signature:
            return not self._require_signed_alive
        if self._verify_member_sig is None:
            return True  # no verifier configured: nothing to check against
        identity = self.certstore.get(bytes(alive.membership.pki_id))
        if identity is None:
            # identity not yet learned (certstore anti-entropy catches up);
            # strict mode refuses rather than trusting the claim
            return not self._require_signed_alive
        return self._verify_member_sig(
            identity,
            _alive_signing_bytes(alive, self.channel_id),
            bytes(alive.signature),
        )

    # -- push side --------------------------------------------------------
    def _alive_message(self, probe_reply: bool = False) -> gossip_pb2.GossipMessage:
        if probe_reply:
            # a probe answer needs a FRESH seq (the prober dedups by
            # seq) but must not advance our own membership clock
            tick = self.membership.bump_seq()
        else:
            tick = self.membership.tick()
            self.election.evaluate()
        msg = gossip_pb2.GossipMessage()
        msg.channel = self.channel_id
        msg.alive_msg.membership.endpoint = self.server.addr
        msg.alive_msg.membership.pki_id = self.self_id.encode()
        msg.alive_msg.membership.ledger_height = self._height()
        msg.alive_msg.seq_num = tick["seq"]
        if self._sign_message is not None:
            msg.alive_msg.signature = self._sign_message(
                _alive_signing_bytes(msg.alive_msg, self.channel_id)
            )
        return msg

    def _conn(self, endpoint: str):
        """One cached channel per peer (reference comm_impl connStore)."""
        with self._lock:
            conn = self._conns.get(endpoint)
            if conn is None:
                if self._tls_client is not None:
                    root_ca, client_pair = self._tls_client
                    conn = channel_to(
                        endpoint, root_ca_pem=root_ca, client_cert=client_pair
                    )
                else:
                    conn = channel_to(endpoint)
                self._conns[endpoint] = conn
            return conn

    _conn_msg_cache = None

    def _conn_establish(self) -> Optional[gossip_pb2.GossipMessage]:
        """Our ConnEstablish for stream openings (None when handshaking
        is not configured). Built once — its inputs (channel, pki_id,
        static TLS cert) never change, and re-signing on every send
        would add one ECDSA op per peer per tick on the hot path."""
        if not (self._require_handshake or self._self_tls_cert_der):
            return None
        if self._conn_msg_cache is not None:
            return self._conn_msg_cache
        import hashlib

        msg = gossip_pb2.GossipMessage()
        msg.channel = self.channel_id
        msg.conn.pki_id = self.self_id.encode()
        msg.conn.identity = self.certstore.get(self.self_id.encode()) or b""
        if self._self_tls_cert_der:
            msg.conn.tls_cert_hash = hashlib.sha256(
                self._self_tls_cert_der
            ).digest()
        if self._sign_message is not None:
            msg.conn.signature = self._sign_message(
                _conn_signing_bytes(
                    self.channel_id,
                    bytes(msg.conn.pki_id),
                    bytes(msg.conn.tls_cert_hash),
                )
            )
        # _send worker threads race to build the first handshake (fabdep
        # unguarded-shared-write): sign outside the lock (ECDSA is the
        # expensive part and the inputs are static), publish under it so
        # exactly one message wins and every stream sends the same bytes
        with self._lock:
            if self._conn_msg_cache is None:
                self._conn_msg_cache = msg
            return self._conn_msg_cache

    def _send(
        self,
        endpoint: str,
        messages: Sequence[gossip_pb2.GossipMessage],
        _depth: int = 0,
    ):
        try:
            # chaos seam: "drop" silently loses the send (membership
            # expiry + pull reconciliation must recover), "raise" takes
            # the dead-peer path below; keyed per (endpoint, stream
            # open) so probabilistic plans model a flaky link, not a
            # permanent partition
            if faults_enabled():
                with self._lock:
                    seq = self._send_seq.get(endpoint, 0)
                    self._send_seq[endpoint] = seq + 1
                spec = fault_point(
                    "gossip.comm.send", key=(endpoint, seq),
                    interprets=("drop",),
                )
                if spec is not None and spec.action == "drop":
                    return
            conn = self._conn(endpoint)
            stub = conn.stream_stream(
                "/gossip.Gossip/GossipStream",
                request_serializer=gossip_pb2.GossipMessage.SerializeToString,
                response_deserializer=gossip_pb2.GossipMessage.FromString,
            )
            outbound = list(messages)
            hello = self._conn_establish()
            if hello is not None:
                # every stream opening re-authenticates (the reference
                # handshakes per connection; our sends are one stream
                # each, so prepend on every send)
                outbound.insert(0, hello)
            followups = []
            for reply in stub(iter(outbound)):
                out = self._handle(reply)
                if out is not None:
                    followups.append(out)
            if followups and _depth < 3:
                # pull four-step: hello -> digest -> request -> update
                # needs the requester to answer replies with new sends
                self._send(endpoint, followups, _depth + 1)
        except Exception:
            # dead peer: drop the cached connection; membership expiry
            # will remove it from the view
            with self._lock:
                conn = self._conns.pop(endpoint, None)
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass

    def broadcast_block(self, block: common_pb2.Block) -> None:
        """Leader push after pulling from the orderer (gossip DataMsg).
        Fan-out runs on worker threads: the caller is the leader's
        commit path and must not block on a dead follower's connect
        timeout (comm_impl.go sends are async for the same reason)."""
        msg = gossip_pb2.GossipMessage()
        msg.channel = self.channel_id
        msg.data_msg.seq_num = block.header.number
        msg.data_msg.block = block.SerializeToString()
        # mark our own broadcast seen so a forwarded copy is not
        # re-buffered or re-forwarded by us
        self._msgstore.add(("data", block.header.number))
        for endpoint in self._peer_endpoints():
            self._spawn_send(endpoint, [msg])

    def _peer_endpoints(self) -> List[str]:
        with self._lock:
            return [
                self._endpoints[pid]
                for pid in self.membership.alive_peers()
                if pid in self._endpoints and pid != self.self_id
            ]

    def _peer_heights(self) -> List[int]:
        out = []
        for pid in self.membership.alive_peers():
            meta = self.membership.metadata_of(pid)
            if meta and len(meta) == 8:
                out.append(int.from_bytes(meta, "big"))
        return out

    _reconcile_commit = None
    _missing_provider = None
    _tick_count = 0
    # pull/reconcile cadence in ticks (the reference pulls on a ~4s
    # interval vs 5 alive ticks/s — running the 4-step exchange every
    # tick would open streams constantly for nothing)
    PULL_EVERY = 5
    RECONCILE_EVERY = 5
    #: cap on the per-anchor redial backoff (in ticks): a silent
    #: bootstrap anchor is re-dialed at most once per cap window
    ANCHOR_REDIAL_CAP_TICKS = 50

    def _tick_once(self) -> None:
        import random as _random

        self._tick_count += 1
        batch = self._intro_messages()
        member_endpoints = self._peer_endpoints()
        for endpoint in member_endpoints:
            self._send(endpoint, batch)
        # bootstrap resilience: an anchor whose hello was lost (flaky
        # link, chaos gossip.comm.send drop) gets re-introduced until a
        # member answers from that endpoint — without this, one dropped
        # connect() partitions the pair permanently because ticks only
        # address peers ALREADY in the member view.  Redials are paced
        # by a per-anchor exponential backoff in ticks (first retry on
        # the next tick, doubling to ANCHOR_REDIAL_CAP_TICKS), so a
        # dead anchor costs one dial per cap window, not one per tick.
        known = set(member_endpoints)
        with self._lock:
            silent_anchors = []
            for a in self._anchors:
                if a in known:
                    # answered: reset the ramp so a future re-silence
                    # (restart, partition) retries fast again
                    self._anchor_backoff.pop(a, None)
                    continue
                nxt = self._anchor_backoff.setdefault(a, [self._tick_count, 1])
                if self._tick_count < nxt[0]:
                    continue
                nxt[1] = min(nxt[1] * 2, self.ANCHOR_REDIAL_CAP_TICKS)
                nxt[0] = self._tick_count + nxt[1]
                silent_anchors.append(a)
        for endpoint in silent_anchors:
            self._send(endpoint, batch)
        # SWIM suspicion: direct-probe peers whose heartbeats stopped
        # reaching us BEFORE expiring them (push loss != death); their
        # reply is a fresh alive that refutes the suspicion
        for pid in self.membership.newly_suspect():
            with self._lock:
                ep = self._endpoints.get(pid)
            if ep:
                probe = self.pull.hello(PULL_MEMBERSHIP)
                self._spawn_send(ep, [probe])
        # anti-entropy: ask ONE taller peer for the missing range
        rng = self.state.missing_range(self._peer_heights())
        if rng is not None:
            endpoints = self._taller_peer_endpoints(rng.stop)
            if endpoints:
                req = gossip_pb2.GossipMessage()
                req.channel = self.channel_id
                req.state_request.start_seq_num = rng.start
                req.state_request.end_seq_num = rng.stop
                self._send(endpoints[0], [req])
        endpoints = self._peer_endpoints()
        # identity pull round with one random peer (certstore sync)
        if endpoints and self._tick_count % self.PULL_EVERY == 0:
            self._send(_random.choice(endpoints), [self.pull.hello()])
        # block pull round (phase-shifted from the identity round): the
        # digest/request/response path converges peers the push missed
        # even when height metadata never spread (pullstore.go)
        if endpoints and self._tick_count % self.PULL_EVERY == 2:
            self._send(_random.choice(endpoints), [self.pull.hello_blocks()])
            self._msgstore.expire_old()
        # pvt-data reconciliation (reconcile.go:104-126): request data the
        # pvt store recorded as missing from one random peer
        if (
            self.pvt is not None
            and self._missing_provider is not None
            and endpoints
            and self._tick_count % self.RECONCILE_EVERY == 0
        ):
            req = self.pvt.reconcile_request(self._missing_provider())
            if req is not None:
                self._send(_random.choice(endpoints), [req])
        self._drain()

    # -- pvt data push (DistributePrivateData) ----------------------------
    def disseminate_pvt(self, tx_id: str, pvt_writes) -> None:
        """Endorsement-time push of [(ns, coll, rwset_bytes)] to every
        member's transient store."""
        if self.pvt is None:
            return
        messages = self.pvt.dissemination_messages(tx_id, pvt_writes)
        if not messages:
            return
        for endpoint in self._peer_endpoints():
            self._spawn_send(endpoint, messages)

    def enable_reconciliation(self, missing_provider, reconcile_commit) -> None:
        """missing_provider() -> {block: [MissingEntry]};
        reconcile_commit([(block, tx, ns, coll, payload)])."""
        self._missing_provider = missing_provider
        self._reconcile_commit = reconcile_commit

    def _taller_peer_endpoints(self, needed_height: int) -> List[str]:
        out = []
        with self._lock:
            for pid in self.membership.alive_peers():
                meta = self.membership.metadata_of(pid)
                if (
                    meta
                    and len(meta) == 8
                    and int.from_bytes(meta, "big") >= needed_height
                    and pid in self._endpoints
                    and pid != self.self_id
                ):
                    out.append(self._endpoints[pid])
        return out

    def _intro_messages(self) -> List[gossip_pb2.GossipMessage]:
        """Identity + alive, in that order: with signed membership the
        receiver must know our certstore identity BEFORE the alive or the
        strict gate drops it (the reference disseminates identities with
        connection establishment; this is the push-stream equivalent,
        avoiding the learn-endpoint-needs-alive bootstrap deadlock)."""
        batch: List[gossip_pb2.GossipMessage] = []
        identity = self.certstore.get(self.self_id.encode())
        if identity and self._tick_count % self.PULL_EVERY in (0, 1):
            # identity rides along on bootstrap and then periodically —
            # resending a ~1KB cert to every peer 5x/s would make every
            # receiver re-run cert-chain validation for nothing
            intro = gossip_pb2.GossipMessage()
            intro.channel = self.channel_id
            intro.peer_identity.pki_id = self.self_id.encode()
            intro.peer_identity.cert = identity
            batch.append(intro)
        batch.append(self._alive_message())
        return batch

    # -- lifecycle --------------------------------------------------------
    def connect(self, endpoint: str) -> None:
        """Bootstrap: introduce ourselves to an anchor peer.  The
        endpoint is remembered: the tick loop re-introduces us until the
        anchor shows up in the member view (lossy-link resilience)."""
        with self._lock:
            self._anchors.add(endpoint)
        self._send(endpoint, self._intro_messages())

    def start(self) -> str:
        addr = self.server.start()

        def loop():
            while not self._stop.wait(self._tick_interval):
                try:
                    self._tick_once()
                except Exception as exc:
                    logger.debug("gossip tick failed: %s", exc)

        self._thread = threading.Thread(target=loop, name="gossip", daemon=True)
        self._thread.start()
        return addr

    def stop(self) -> None:
        self._stop.set()
        # reap the tick loop BEFORE tearing down the conns it uses: a
        # mid-_tick_once thread surviving stop() is exactly the
        # leaked-per-node lifetime class fablife pins (the loop observes
        # _stop within one tick interval)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        with self._lock:
            senders = list(self._senders)
            self._senders.clear()
        for s in senders:
            if s is not threading.current_thread():
                try:
                    s.join(timeout=1.0)
                except RuntimeError:
                    pass  # registered but not yet started (append-before-start window)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except Exception:
                pass
        self.server.stop()

    @property
    def addr(self) -> str:
        return self.server.addr

    @property
    def is_leader(self) -> bool:
        return self.election.is_leader


def _conn_signing_bytes(channel_id: str, pki_id: bytes, tls_hash: bytes) -> bytes:
    """ConnEstablish signed content: channel + pki_id + tls cert hash
    (comm_impl.go createConnectionMsg signs pkiID + certHash)."""
    return b"conn\x00" + channel_id.encode() + b"\x00" + pki_id + b"\x00" + tls_hash


def _alive_signing_bytes(alive, channel_id: str) -> bytes:
    """Deterministic alive content for sign/verify: CHANNEL + (membership,
    seq_num, inc_num) with the signature field excluded.  Binding the
    channel stops cross-channel replay of a validly signed alive (each
    channel has its own GossipNode with independent seq counters and
    ledger heights)."""
    bare = gossip_pb2.AliveMessage()
    bare.membership.CopyFrom(alive.membership)
    bare.seq_num = alive.seq_num
    bare.inc_num = alive.inc_num
    return channel_id.encode() + b"\x00" + bare.SerializeToString()
