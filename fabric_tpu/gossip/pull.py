"""Pull-mediator + certstore identity exchange (reference
gossip/gossip/pull/pullstore.go and gossip/identity + certstore: the
Hello -> DataDigest -> DataRequest -> DataUpdate four-step that spreads
items a push can miss).

Two item types ride the same four-step:

* PEER IDENTITIES: each node holds {pki_id: identity bytes} (its own
  MSP serialized identity plus everything pulled), so policies and
  discovery can resolve remote members' certs without a direct
  connection to them.
* BLOCKS (reference pull.BlockPullPolicy / gossip_impl.go:443): digests
  are recent block sequence numbers; a peer that missed a push — or a
  late joiner whose height metadata never spread — converges through
  pull alone, independent of the height-driven anti-entropy
  (state.go:586) which needs working membership metadata first."""

from __future__ import annotations

import random
import threading
from typing import Callable, Dict, List, Optional

from fabric_tpu.protos import common_pb2, gossip_pb2

PULL_IDENTITY = 1
PULL_BLOCK = 2
# direct membership probe (reference discovery MembershipRequest: sent
# to a SUSPECT peer; the response is the target's own fresh alive)
PULL_MEMBERSHIP = 3

# how many trailing blocks a responder advertises in a block digest
# (the reference bounds its block pull store the same way; older blocks
# flow through the state-transfer range protocol instead)
BLOCK_DIGEST_WINDOW = 10


class CertStore:
    """pki_id -> serialized identity (gossip/state certstore analog);
    thread-safe, verification hook applied before adoption."""

    def __init__(
        self,
        self_id: str,
        self_identity: bytes,
        verify: Optional[Callable[[bytes, bytes], bool]] = None,
    ):
        self._lock = threading.Lock()
        self._store: Dict[bytes, bytes] = {}
        self._verify = verify
        if self_identity:
            self._store[self_id.encode()] = self_identity

    def put(self, pki_id: bytes, identity: bytes) -> bool:
        if self._verify is not None and not self._verify(pki_id, identity):
            return False
        with self._lock:
            existing = self._store.get(pki_id)
            if existing is not None and existing != identity:
                # FIRST BIND WINS: a pki_id, once bound, cannot be
                # re-bound to a different identity — otherwise any valid
                # same-MSP member could swap a victim's binding and then
                # sign "the victim's" membership messages with its own
                # key (the reference avoids this by deriving pki_id from
                # the cert itself). Rotation requires a restart/expiry,
                # the trade the reference's certstore also makes.
                return False
            self._store[pki_id] = identity
        return True

    def get(self, pki_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._store.get(pki_id)

    def digests(self) -> List[bytes]:
        with self._lock:
            return sorted(self._store)

    def missing(self, digests) -> List[bytes]:
        with self._lock:
            return [d for d in digests if d not in self._store]


class PullMediator:
    """The requester/responder halves of one pull round. The transport is
    a callable (endpoint, [GossipMessage]) -> [reply GossipMessages]
    (the gossip node's stream send)."""

    def __init__(
        self,
        channel_id: str,
        store: CertStore,
        get_block: Optional[Callable[[int], Optional[common_pb2.Block]]] = None,
        height: Optional[Callable[[], int]] = None,
        add_block: Optional[Callable[[common_pb2.Block], None]] = None,
    ):
        self.channel_id = channel_id
        self.store = store
        self._get_block = get_block
        self._height = height
        self._add_block = add_block
        self._rng = random.Random()

    # -- responder side (handled from the gossip stream) -------------------
    def handle(
        self, msg: gossip_pb2.GossipMessage
    ) -> Optional[gossip_pb2.GossipMessage]:
        kind = msg.WhichOneof("content")
        if kind == "hello" and msg.hello.msg_type == PULL_BLOCK:
            if self._height is None:
                return None
            h = self._height()
            out = gossip_pb2.GossipMessage()
            out.channel = self.channel_id
            out.data_dig.nonce = msg.hello.nonce
            out.data_dig.msg_type = PULL_BLOCK
            out.data_dig.digests.extend(
                str(seq).encode()
                for seq in range(max(0, h - BLOCK_DIGEST_WINDOW), h)
            )
            return out
        if kind == "data_dig" and msg.data_dig.msg_type == PULL_BLOCK:
            if self._height is None:
                return None
            mine = self._height()
            want = sorted(
                int(d)
                for d in msg.data_dig.digests
                if d.isdigit() and int(d) >= mine
            )
            if not want:
                return None
            out = gossip_pb2.GossipMessage()
            out.channel = self.channel_id
            out.data_req.nonce = msg.data_dig.nonce
            out.data_req.msg_type = PULL_BLOCK
            out.data_req.digests.extend(str(s).encode() for s in want)
            return out
        if kind == "data_req" and msg.data_req.msg_type == PULL_BLOCK:
            if self._get_block is None:
                return None
            out = gossip_pb2.GossipMessage()
            out.channel = self.channel_id
            out.data_update.nonce = msg.data_req.nonce
            out.data_update.msg_type = PULL_BLOCK
            for d in msg.data_req.digests:
                if not d.isdigit():
                    continue
                block = self._get_block(int(d))
                if block is None:
                    continue
                item = gossip_pb2.GossipMessage()
                item.channel = self.channel_id
                item.data_msg.seq_num = block.header.number
                item.data_msg.block = block.SerializeToString()
                out.data_update.data.append(item.SerializeToString())
            return out if out.data_update.data else None
        if kind == "data_update" and msg.data_update.msg_type == PULL_BLOCK:
            if self._add_block is not None:
                for raw in msg.data_update.data:
                    item = gossip_pb2.GossipMessage()
                    item.ParseFromString(raw)
                    if item.WhichOneof("content") != "data_msg":
                        continue
                    block = common_pb2.Block()
                    block.ParseFromString(item.data_msg.block)
                    self._add_block(block)
            return None
        if kind == "hello" and msg.hello.msg_type == PULL_IDENTITY:
            out = gossip_pb2.GossipMessage()
            out.channel = self.channel_id
            out.data_dig.nonce = msg.hello.nonce
            out.data_dig.msg_type = PULL_IDENTITY
            out.data_dig.digests.extend(self.store.digests())
            return out
        if kind == "data_req" and msg.data_req.msg_type == PULL_IDENTITY:
            out = gossip_pb2.GossipMessage()
            out.channel = self.channel_id
            out.data_update.nonce = msg.data_req.nonce
            out.data_update.msg_type = PULL_IDENTITY
            for digest in msg.data_req.digests:
                identity = self.store.get(bytes(digest))
                if identity is None:
                    continue
                item = gossip_pb2.GossipMessage()
                item.channel = self.channel_id
                item.peer_identity.pki_id = digest
                item.peer_identity.cert = identity
                out.data_update.data.append(item.SerializeToString())
            return out
        if kind == "data_dig" and msg.data_dig.msg_type == PULL_IDENTITY:
            want = self.store.missing(
                [bytes(d) for d in msg.data_dig.digests]
            )
            if not want:
                return None
            out = gossip_pb2.GossipMessage()
            out.channel = self.channel_id
            out.data_req.nonce = msg.data_dig.nonce
            out.data_req.msg_type = PULL_IDENTITY
            out.data_req.digests.extend(want)
            return out
        if kind == "data_update" and msg.data_update.msg_type == PULL_IDENTITY:
            for raw in msg.data_update.data:
                item = gossip_pb2.GossipMessage()
                item.ParseFromString(raw)
                if item.WhichOneof("content") == "peer_identity":
                    self.store.put(
                        bytes(item.peer_identity.pki_id),
                        bytes(item.peer_identity.cert),
                    )
            return None
        if kind == "peer_identity":
            self.store.put(
                bytes(msg.peer_identity.pki_id), bytes(msg.peer_identity.cert)
            )
            return None
        return None

    # -- requester side (called from the gossip tick) ----------------------
    def hello(self, msg_type: int = PULL_IDENTITY) -> gossip_pb2.GossipMessage:
        out = gossip_pb2.GossipMessage()
        out.channel = self.channel_id
        out.hello.nonce = self._rng.getrandbits(63)
        out.hello.msg_type = msg_type
        return out

    def hello_blocks(self) -> gossip_pb2.GossipMessage:
        return self.hello(PULL_BLOCK)
