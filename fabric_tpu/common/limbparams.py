"""The canonical limb-radix parameters — single source of truth.

Radix-2^13, 20-limb representation (260 bits for 256-bit fields): the
one headroom bet the whole device ops layer rests on, proven
overflow-free by the fabflow gate (see ops/bignum.py for the CIOS
accumulator bound it mechanizes: worst case < 0.625 * 2^32 < 2^32).

This module is dependency-free so HOST-tier code (crypto/hostec,
crypto/hostec_np — which condenses adjacent limbs into 2^(2*LIMB_BITS)
pair rows for its numpy kernels — common/fp256bn, tools) can reference
the constants without importing jax; fabric_tpu.ops.bignum re-exports
them under the historical names.
Hardcoding 13 / 20 / 0x1fff / 8192 / 260 anywhere in the limb tier is a
fabflow `const-drift` finding.
"""

from __future__ import annotations

LIMB_BITS = 13
NLIMBS = 20
LIMB_MASK = (1 << LIMB_BITS) - 1
RADIX_BITS = LIMB_BITS * NLIMBS  # 260
