"""Deterministic fault injection for the fabchaos harness.

The runtime carries named *fault points* at its failure seams — the
places where production traffic actually breaks (BENCH_r04/r05: backend
init, pool breakage, transport flaps):

=========================  ==================================================
site                       seam
=========================  ==================================================
``batcher.submit``         VerifyBatcher.submit, before lane admission
``batcher.dispatch``       VerifyBatcher dispatcher, per launch attempt
``pipeline.commit``        CommitPipeline._commit_loop, before store_block
``bccsp.dispatch``         SoftwareProvider batch dispatch (EC ladder)
``bccsp.verdict``          SoftwareProvider verdict mask (corrupt action)
``hostec.pool.submit``     hostec shard submission to the process pool
``hostec.pool.resolve``    hostec shard result join
``hostec_np.pool.submit``  hostec_np shm shard submission
``hostec_np.pool.resolve`` hostec_np shm shard result join
``hostbn.pool.submit``     hostbn idemix shard submission
``hostbn.pool.resolve``    hostbn idemix shard result join
``deliver.pull``           BlockDeliverer.run, per connection attempt
``gossip.comm.send``       GossipNode._send, per stream open
``serve.dispatch``         SidecarServer verify handling, per request
``serve.route``            SidecarRouter, per endpoint dispatch attempt
``raft.step``              RaftChain.step, per consensus message (drop)
``idemix.verdict``         idemix/batch verdict mask (corrupt action)
``blockstore.append.pre_fsync``   BlockStore.add_block, frame written
                                  but not yet fsynced (kill window)
``blockstore.append.post_fsync``  BlockStore.add_block, frame fsynced,
                                  directory entry not yet (kill window)
``blockstore.append.pre_index``   BlockStore.add_block, durable on disk,
                                  in-memory index not updated (kill window)
``kvledger.commit.pre_pvt``       KVLedger.commit, before the pvt store
                                  write (kill window)
``kvledger.commit.post_block``    KVLedger.commit, block appended, state
                                  not yet committed (kill window)
``persistent.commit.mid``         SqliteVersionedDB.commit_block, mid
                                  transaction before the savepoint row
                                  (kill window)
=========================  ==================================================

A ``fault_point(site, key=...)`` call costs ONE module-global load and a
``None`` check when no plan is installed — the registry is free in
production.  With a plan installed it either does nothing, raises
:class:`InjectedFault`, sleeps (``delay``), or returns the matched
:class:`FaultSpec` for actions the site must interpret itself
(``corrupt`` / ``drop``).

Determinism: every decision is a pure function of ``(plan seed, site,
key)`` — ``sha256(seed|site|key)`` compared against the probability — so
a replayed seed injects the *same* faults regardless of thread
interleaving, as long as call sites pass stable keys.  Sites that pass
no key fall back to a per-site seeded counter (order-dependent across
threads; documented per site).  ``max_fires`` caps are counter-based and
therefore order-dependent by nature.

Plan grammar (``FABRIC_TPU_FAULTS`` env var or :meth:`FaultPlan.parse`)::

    plan   := entry (";" entry)*
    entry  := site "=" action [":" prob] (":" param "=" int)*
    action := "raise" | "delay" | "corrupt" | "drop" | "kill"
    params := max (max fires) | ms (delay millis) | lanes (corrupt width)
              | at (fire only when the call key equals this int)

    FABRIC_TPU_FAULTS="batcher.dispatch=raise:0.2:max=3;deliver.pull=raise:0.5"
    FABRIC_TPU_FAULTS_SEED=7

The ``kill`` action is the fabcrash crash-consistency harness: the
process dies on the spot via ``os._exit(137)`` — no atexit hooks, no
interpreter cleanup, no flushing of Python-buffered file data — the
deterministic stand-in for SIGKILLing a peer mid-commit.  The ``at``
param pins a kill (or any action) to one exact call key (a block
number), which is how the crash matrix walks kill WINDOWS instead of
kill probabilities.  ``FABRIC_TPU_CRASH_SITES`` is operator sugar for
kill plans: ``site[@block]`` entries joined by ``;``/``,`` that merge
into the installed plan alongside ``FABRIC_TPU_FAULTS``.

A malformed env plan warns and installs nothing — chaos knobs must never
poison a production import (the PR 1 env-var discipline).
"""

from __future__ import annotations

import hashlib
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Union

from fabric_tpu.common import fabobs

ACTIONS = ("raise", "delay", "corrupt", "drop", "kill")

#: the kill action's exit code: what a SIGKILLed process reports (128+9),
#: so harnesses watching returncodes treat os._exit kills and real
#: SIGKILLs identically
KILL_EXIT_CODE = 137


class InjectedFault(Exception):
    """Raised by a fault point running a ``raise`` action.  Transient by
    contract: retry layers (common.retry) may retry it, mask layers must
    fail closed on it like any other exception."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site}")
        self.site = site


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault: ``site=action:prob:param=...``."""

    site: str
    action: str  # raise | delay | corrupt | drop | kill
    prob: float = 1.0
    max_fires: int = 0  # 0 = unlimited
    delay_ms: int = 10  # delay action: sleep duration
    lanes: int = 1  # corrupt action: verdict lanes to flip
    at_key: Optional[int] = None  # fire only when the call key == at_key

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r} "
                f"(expected one of {ACTIONS})"
            )
        if not (0.0 <= self.prob <= 1.0):
            raise ValueError(f"fault probability {self.prob!r} not in [0, 1]")


def _keyed_hit(seed: int, site: str, key, prob: float) -> bool:
    """Pure decision function: identical (seed, site, key) -> identical
    verdict, independent of call order and thread scheduling."""
    if prob >= 1.0:
        return True
    h = hashlib.sha256(
        f"{seed}|{site}|{key!r}".encode("utf-8", "backslashreplace")
    ).digest()
    return int.from_bytes(h[:8], "big") < prob * 2.0**64


class FaultPlan:
    """A set of armed fault specs plus per-site fire accounting."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.seed = int(seed)
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for spec in specs:
            self._by_site.setdefault(spec.site, []).append(spec)
        self._lock = threading.Lock()
        # per-SPEC fire counters (a site may carry several specs, each
        # with its own max_fires budget); fired() aggregates per site
        self._fired: Dict[int, int] = {}
        # unkeyed decisions draw from a per-site seeded stream
        self._rng: Dict[str, random.Random] = {}
        self._warned: set = set()  # (site, action) mismatch warnings

    # -- construction ----------------------------------------------------
    @classmethod
    def parse(
        cls, text: str, seed: int = 0
    ) -> "FaultPlan":
        """Parse the ``site=action:prob:param=v`` grammar; raises
        ValueError on malformed entries (env installation catches)."""
        specs: List[FaultSpec] = []
        for raw in text.replace(",", ";").split(";"):
            entry = raw.strip()
            if not entry:
                continue
            site, sep, rhs = entry.partition("=")
            if not sep or not site.strip():
                raise ValueError(f"fault entry {entry!r} is not site=action")
            parts = rhs.split(":")
            action = parts[0].strip()
            kwargs = {"site": site.strip(), "action": action}
            pos = 1
            if len(parts) > 1 and "=" not in parts[1]:
                kwargs["prob"] = float(parts[1])
                pos = 2
            for param in parts[pos:]:
                name, psep, value = param.partition("=")
                if not psep:
                    raise ValueError(
                        f"fault param {param!r} is not name=int"
                    )
                name = name.strip()
                if name == "max":
                    kwargs["max_fires"] = int(value)
                elif name == "ms":
                    kwargs["delay_ms"] = int(value)
                elif name == "lanes":
                    kwargs["lanes"] = int(value)
                elif name == "at":
                    kwargs["at_key"] = int(value)
                else:
                    raise ValueError(f"unknown fault param {name!r}")
            specs.append(FaultSpec(**kwargs))
        return cls(specs, seed=seed)

    @classmethod
    def from_dict(
        cls, mapping: Dict[str, Union[str, FaultSpec]], seed: int = 0
    ) -> "FaultPlan":
        """{"site": "action:prob:param=v" | FaultSpec} convenience."""
        specs: List[FaultSpec] = []
        for site, rhs in mapping.items():
            if isinstance(rhs, FaultSpec):
                specs.append(rhs)
            else:
                plan = cls.parse(f"{site}={rhs}")
                specs.extend(plan.specs())
        return cls(specs, seed=seed)

    def specs(self) -> List[FaultSpec]:
        return [s for lst in self._by_site.values() for s in lst]

    # -- decision --------------------------------------------------------
    def check(
        self, site: str, key=None, interprets: Sequence[str] = ()
    ) -> Optional[FaultSpec]:
        """The armed spec that fires for this call, or None.  Counts
        fires and honors per-spec ``max_fires`` caps.  ``interprets``
        names the corrupt/drop actions this site actually implements:
        a spec whose action the site would silently discard is skipped
        WITHOUT counting as fired (and warns once) — an operator must
        never read 'pipeline.commit=drop fired N times' off a scorecard
        when nothing was injected."""
        specs = self._by_site.get(site)
        if not specs:
            return None
        for spec in specs:
            if spec.action in ("corrupt", "drop") and (
                spec.action not in interprets
            ):
                self._warn_uninterpreted(site, spec.action)
                continue
            if spec.at_key is not None and key != spec.at_key:
                # window-pinned spec (crash matrix kill points): only the
                # exact call key arms it; other calls pass untouched
                continue
            if spec.prob < 1.0 and key is None:
                with self._lock:
                    rng = self._rng.get(site)
                    if rng is None:
                        rng = self._rng[site] = random.Random(
                            (self.seed << 32)
                            ^ int.from_bytes(
                                hashlib.sha256(site.encode()).digest()[:4],
                                "big",
                            )
                        )
                    hit = rng.random() < spec.prob
            else:
                hit = _keyed_hit(self.seed, site, key, spec.prob)
            if not hit:
                continue
            with self._lock:
                fired = self._fired.get(id(spec), 0)
                if spec.max_fires and fired >= spec.max_fires:
                    continue
                self._fired[id(spec)] = fired + 1
            return spec
        return None

    def _warn_uninterpreted(self, site: str, action: str) -> None:
        with self._lock:
            if (site, action) in self._warned:
                return
            self._warned.add((site, action))
        import warnings

        warnings.warn(
            f"fault plan arms {site}={action}, but that site does not "
            f"interpret {action!r} — the spec is ignored (not counted)",
            RuntimeWarning,
            stacklevel=4,
        )

    def fired(self) -> Dict[str, int]:
        """Snapshot of per-site fire counts (scorecard material)."""
        with self._lock:
            out: Dict[str, int] = {}
            for site, specs in self._by_site.items():
                n = sum(self._fired.get(id(s), 0) for s in specs)
                if n:
                    out[site] = n
            return out

    def reset_counters(self) -> None:
        with self._lock:
            self._fired.clear()
            self._rng.clear()


# ---------------------------------------------------------------------------
# Process-wide installation.  _PLAN is written only under _PLAN_LOCK
# (install/uninstall are control-plane rare); the hot-path read in
# fault_point is a single GIL-atomic global load.
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_PLAN_LOCK = threading.Lock()


def install_plan(plan: Optional[FaultPlan]) -> None:
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = plan


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


class plan_installed:
    """``with plan_installed(plan):`` — scoped installation (tests and
    the fabchaos scenario runner).  The PREVIOUS plan is restored on
    exit, so a scenario run inside a process chaos'd via
    FABRIC_TPU_FAULTS does not silently disarm the operator's plan.
    Not reentrant across threads: one plan is process-wide by design
    (the seams read one global)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> FaultPlan:
        self._prev = active_plan()
        install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        install_plan(self._prev)


def faults_enabled() -> bool:
    return _PLAN is not None


def fault_point(
    site: str, key=None, interprets: Sequence[str] = ()
) -> Optional[FaultSpec]:
    """The injection seam.  No plan installed: returns None at the cost
    of one global load.  Otherwise: ``raise`` raises InjectedFault,
    ``delay`` sleeps then returns None (transparent), ``corrupt`` and
    ``drop`` return the spec for the call site to interpret —
    ``interprets`` declares which of those the site implements (an
    unsupported action is skipped, uncounted, with a one-shot warning).

    Key discipline: pass a key only when it genuinely varies per
    decision (block number, connection attempt, stream sequence) —
    replayed seeds then inject identical faults independent of thread
    order.  Sites whose natural key is static per steady-state call
    (a fixed batch size) must pass key=None: the per-site seeded
    stream keeps probabilistic plans probabilistic instead of
    degenerating into all-or-nothing per key value."""
    plan = _PLAN
    if plan is None:
        return None
    spec = plan.check(site, key, interprets)
    if spec is None:
        return None
    # chaos runs become observable: every fired injection is a counter
    # series (and an obs event) when the registry is enabled — metrics
    # are memory-only, so the deterministic scorecard stays byte-exact
    fabobs.obs_count("fabric_fault_fired_total", site=site)
    fabobs.obs_event("fault.fired", site=site, action=spec.action)
    if spec.action == "raise":
        raise InjectedFault(site)
    if spec.action == "delay":
        time.sleep(spec.delay_ms / 1000.0)
        return None
    if spec.action == "kill":
        # SIGKILL stand-in: die NOW, from any thread, with no interpreter
        # cleanup — atexit hooks don't run and Python-buffered file data
        # is lost, exactly the torn-write surface a real kill exposes.
        # Whatever the seam already pushed to the OS survives (the OS
        # flushes its own page cache); whatever sits in Python buffers
        # does not.
        os._exit(KILL_EXIT_CODE)
    return spec


def corrupt_verdicts(verdicts: Sequence[bool], spec: FaultSpec) -> List[bool]:
    """Flip the first ``spec.lanes`` verdicts (all lanes when 0) — the
    ``corrupt`` action's standard interpretation at mask-producing
    sites.  Exists so the empirical oracle gate (fabchaos corrupt_detect
    and the bit-exact mask assertions) can prove it would CATCH a
    verdict-corrupting bug; never reachable without an installed plan."""
    out = list(verdicts)
    n = len(out) if spec.lanes <= 0 else min(spec.lanes, len(out))
    for i in range(n):
        out[i] = not out[i]
    return out


def crash_specs_from_text(text: str) -> List[FaultSpec]:
    """Parse the FABRIC_TPU_CRASH_SITES kill-point selector: ``site`` or
    ``site@block`` entries joined by ``;``/``,`` — sugar for
    ``site=kill:max=1`` / ``site=kill:at=block:max=1``.  The crash
    matrix (tools/fabchaos crash scenarios) arms its subprocess peers
    this way; raises ValueError on malformed entries."""
    specs: List[FaultSpec] = []
    for raw in text.replace(",", ";").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        site, _sep, at = entry.partition("@")
        site = site.strip()
        if not site:
            raise ValueError(f"crash site entry {entry!r} has no site")
        specs.append(
            FaultSpec(
                site=site,
                action="kill",
                max_fires=1,
                at_key=int(at) if at.strip() else None,
            )
        )
    return specs


def _install_from_env() -> None:
    """Honor FABRIC_TPU_FAULTS (+ the FABRIC_TPU_CRASH_SITES kill-point
    sugar) at import so external runs (bench, a node under soak, the
    crash matrix's subprocess peers) can be chaos'd without code
    changes.  Malformed values warn and install nothing — never raise
    out of an import."""
    text = os.environ.get("FABRIC_TPU_FAULTS", "")
    crash_text = os.environ.get("FABRIC_TPU_CRASH_SITES", "")
    if not text and not crash_text:
        return
    seed_raw = os.environ.get("FABRIC_TPU_FAULTS_SEED", "0")
    try:
        seed = int(seed_raw)
    except ValueError:
        seed = 0
    import warnings

    specs: List[FaultSpec] = []
    try:
        if text:
            specs.extend(FaultPlan.parse(text, seed=seed).specs())
    except (ValueError, TypeError) as exc:
        warnings.warn(
            f"FABRIC_TPU_FAULTS ignored (malformed: {exc})",
            RuntimeWarning,
            stacklevel=2,
        )
    try:
        if crash_text:
            specs.extend(crash_specs_from_text(crash_text))
    except (ValueError, TypeError) as exc:
        warnings.warn(
            f"FABRIC_TPU_CRASH_SITES ignored (malformed: {exc})",
            RuntimeWarning,
            stacklevel=2,
        )
    if specs:
        install_plan(FaultPlan(specs, seed=seed))


_install_from_env()
