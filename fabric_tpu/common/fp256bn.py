"""FP256BN pairing curve: host oracle for the Idemix crypto suite.

The reference's Idemix stack (idemix/*.go) does all its math on the
256-bit Barreto-Naehrig curve FP256BN via the Milagro (amcl) library.
This module is an independent implementation of the same curve from its
public parameters (ISO/IEC 15946-5 "BN" P256; parameters mirrored from
the amcl ROM, reference vendor .../FP256BN/ROM.go):

  p  = 36u^4 + 36u^3 + 24u^2 + 6u + 1       (field modulus)
  r  = 36u^4 + 36u^3 + 18u^2 + 6u + 1       (group order)
  u  = -0x6882F5C030B0A801                  (BN parameter, negative)
  E  : y^2 = x^3 + 3 over Fp, G1 = (1, 2)
  E' : y^2 = x^3 + 3/xi over Fp2 (M-type sextic twist, xi = 1 + i)

Tower: Fp2 = Fp[i]/(i^2+1); Fp12 built directly as Fp2[w]/(w^6 - xi).
G2 points are untwisted into E(Fp12) and the optimal-ate Miller loop runs
with generic Fp12 line arithmetic — slower than a dedicated tower but
obviously correct; the batched TPU kernel is the fast path.

Serialization parity (idemix/util.go): BIG = 32-byte big-endian; G1 =
0x04 || x || y (65 bytes); G2 = xa || xb || ya || yb (128 bytes).

Only verification-grade correctness is required (public data, no
constant-time concerns).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

# --------------------------------------------------------------------------
# Curve constants (amcl FP256BN ROM, assembled from base-2^56 chunks)
# --------------------------------------------------------------------------

P = 0xFFFFFFFFFFFCF0CD46E5F25EEE71A49F0CDC65FB12980A82D3292DDBAED33013
R = 0xFFFFFFFFFFFCF0CD46E5F25EEE71A49E0CDC65FB1299921AF62D536CD10B500D
B_COEFF = 3
U = -0x6882F5C030B0A801  # BN parameter (SIGN_OF_X = NEGATIVEX)

G1_X = 1
G1_Y = 2

# G2 generator on the twist (Fp2 coords, ROM CURVE_Pxa/Pxb/Pya/Pyb)
G2_XA = 0xFE0C3350B4C96C2028560F577C28913ACE1C539A12BF843CD22616B689C09EFB
G2_XB = 0x4EA66057738AC054DB5AE1C637D813B924DD78E287D03589D269ED34A37E6A2B
G2_YA = 0x702046E7C542A3B376770D75124E3E51EFCB24758D615848E909B481BEDC27FF
G2_YB = 0x0554E3BCD388C29042EEA649297EB29F8B4CBE80821A98B3E01281114AAD049B

FIELD_BYTES = 32


# --------------------------------------------------------------------------
# Fp2 = Fp[i] / (i^2 + 1): represented as (a, b) = a + b*i
# --------------------------------------------------------------------------

Fp2 = Tuple[int, int]

FP2_ZERO: Fp2 = (0, 0)
FP2_ONE: Fp2 = (1, 0)
XI: Fp2 = (1, 1)  # the sextic non-residue 1 + i


def fp2_add(x: Fp2, y: Fp2) -> Fp2:
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def fp2_sub(x: Fp2, y: Fp2) -> Fp2:
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def fp2_neg(x: Fp2) -> Fp2:
    return ((-x[0]) % P, (-x[1]) % P)


def fp2_mul(x: Fp2, y: Fp2) -> Fp2:
    a, b = x
    c, d = y
    ac = a * c
    bd = b * d
    return ((ac - bd) % P, ((a + b) * (c + d) - ac - bd) % P)


def fp2_sqr(x: Fp2) -> Fp2:
    a, b = x
    return ((a + b) * (a - b) % P, 2 * a * b % P)


def fp2_scalar(x: Fp2, k: int) -> Fp2:
    return (x[0] * k % P, x[1] * k % P)


def fp2_inv(x: Fp2) -> Fp2:
    a, b = x
    norm = (a * a + b * b) % P
    inv = pow(norm, P - 2, P)
    return (a * inv % P, (-b) * inv % P)


def fp2_conj(x: Fp2) -> Fp2:
    return (x[0], (-x[1]) % P)


# --------------------------------------------------------------------------
# Fp12 = Fp2[w] / (w^6 - xi): vector of 6 Fp2 coefficients (c0..c5),
# value = sum(c_k * w^k). G2 untwists into E(Fp12) with x,y in Fp12.
# --------------------------------------------------------------------------

Fp12 = Tuple[Fp2, Fp2, Fp2, Fp2, Fp2, Fp2]

FP12_ZERO: Fp12 = (FP2_ZERO,) * 6
FP12_ONE: Fp12 = (FP2_ONE,) + (FP2_ZERO,) * 5


def fp12_from_fp2(c: Fp2, k: int = 0) -> Fp12:
    out = [FP2_ZERO] * 6
    out[k] = c
    return tuple(out)


def fp12_add(x: Fp12, y: Fp12) -> Fp12:
    return tuple(fp2_add(a, b) for a, b in zip(x, y))


def fp12_sub(x: Fp12, y: Fp12) -> Fp12:
    return tuple(fp2_sub(a, b) for a, b in zip(x, y))


def fp12_neg(x: Fp12) -> Fp12:
    return tuple(fp2_neg(a) for a in x)


def fp12_mul(x: Fp12, y: Fp12) -> Fp12:
    # schoolbook in w with reduction w^6 = xi
    acc: List[Fp2] = [FP2_ZERO] * 11
    for i2, xi_ in enumerate(x):
        if xi_ == FP2_ZERO:
            continue
        for j, yj in enumerate(y):
            if yj == FP2_ZERO:
                continue
            acc[i2 + j] = fp2_add(acc[i2 + j], fp2_mul(xi_, yj))
    out = list(acc[:6])
    for k in range(6, 11):
        if acc[k] != FP2_ZERO:
            out[k - 6] = fp2_add(out[k - 6], fp2_mul(acc[k], XI))
    return tuple(out)


def fp12_sqr(x: Fp12) -> Fp12:
    return fp12_mul(x, x)


def fp12_conj(x: Fp12) -> Fp12:
    """Conjugate over Fp6 (negate odd w-powers): equals x^(p^6), and for
    unitary GT elements the inverse."""
    return (
        x[0],
        fp2_neg(x[1]),
        x[2],
        fp2_neg(x[3]),
        x[4],
        fp2_neg(x[5]),
    )


def fp12_inv(x: Fp12) -> Fp12:
    # generic inverse via solving x * y = 1 with Gaussian elimination is
    # overkill; use the norm-map chain: for a in Fp12 with conj over Fp6,
    # a^{-1} = conj(a) * (a * conj(a))^{-1} where a*conj(a) lies in the
    # even subalgebra (an Fp6 image). We reduce twice down to Fp2.
    # a * conj(a) has only even coefficients -> element of Fp6 over w^2.
    ac = fp12_mul(x, fp12_conj(x))
    if ac[1] != FP2_ZERO or ac[3] != FP2_ZERO or ac[5] != FP2_ZERO:
        raise ArithmeticError("a*conj(a) left the even Fp6 subalgebra")
    # Fp6 = Fp2[v]/(v^3 - xi) with v = w^2: coefficients (ac[0], ac[2], ac[4])
    inv6 = _fp6_inv((ac[0], ac[2], ac[4]))
    inv12 = (inv6[0], FP2_ZERO, inv6[1], FP2_ZERO, inv6[2], FP2_ZERO)
    return fp12_mul(fp12_conj(x), inv12)


def _fp6_mul(x, y):
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0 = fp2_mul(a0, b0)
    t1 = fp2_add(fp2_mul(a0, b1), fp2_mul(a1, b0))
    t2 = fp2_add(fp2_add(fp2_mul(a0, b2), fp2_mul(a1, b1)), fp2_mul(a2, b0))
    t3 = fp2_add(fp2_mul(a1, b2), fp2_mul(a2, b1))
    t4 = fp2_mul(a2, b2)
    return (
        fp2_add(t0, fp2_mul(t3, XI)),
        fp2_add(t1, fp2_mul(t4, XI)),
        t2,
    )


def _fp6_inv(x):
    a0, a1, a2 = x
    c0 = fp2_sub(fp2_sqr(a0), fp2_mul(XI, fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul(XI, fp2_sqr(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    t = fp2_add(
        fp2_mul(XI, fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))),
        fp2_mul(a0, c0),
    )
    ti = fp2_inv(t)
    return (fp2_mul(c0, ti), fp2_mul(c1, ti), fp2_mul(c2, ti))


def fp12_pow(x: Fp12, e: int) -> Fp12:
    if e < 0:
        return fp12_pow(fp12_conj(x), -e)  # valid for unitary elements only
    out = FP12_ONE
    for bit in bin(e)[2:]:
        out = fp12_sqr(out)
        if bit == "1":
            out = fp12_mul(out, x)
    return out


def fp12_frobenius(x: Fp12, n: int = 1) -> Fp12:
    """x -> x^(p^n). coeff c_k w^k -> c_k^(p^n) * gamma_{n,k} w^k with
    gamma_{n,k} = xi^{k*(p^n-1)/6}."""
    out = []
    for k, c in enumerate(x):
        cc = c
        for _ in range(n % 2):
            cc = fp2_conj(cc)
        gamma = _FROB_GAMMA[n % 12][k]
        out.append(fp2_mul(cc, gamma))
    return tuple(out)


def _fp2_pow(x: Fp2, e: int) -> Fp2:
    out = FP2_ONE
    for bit in bin(e)[2:]:
        out = fp2_sqr(out)
        if bit == "1":
            out = fp2_mul(out, x)
    return out


def _build_frob_constants():
    """gamma_{n,k} = xi^{k*(p^n - 1)/6} for n in 0..11, k in 0..5."""
    gammas = []
    for n in range(12):
        row = []
        for k in range(6):
            e = k * (pow(P, n) - 1) // 6
            row.append(_fp2_pow(XI, e % ((P * P) - 1)))
        gammas.append(row)
    return gammas


_FROB_GAMMA = _build_frob_constants()


# --------------------------------------------------------------------------
# G1: E(Fp) : y^2 = x^3 + 3. Affine (x, y) with None = infinity.
# --------------------------------------------------------------------------

G1Point = Optional[Tuple[int, int]]
G1_GEN: G1Point = (G1_X, G1_Y)


def g1_is_on_curve(pt: G1Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + B_COEFF)) % P == 0


def g1_add(p1: G1Point, p2: G1Point) -> G1Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_neg(pt: G1Point) -> G1Point:
    return None if pt is None else (pt[0], (-pt[1]) % P)


def g1_mul(pt: G1Point, k: int) -> G1Point:
    k %= R
    out: G1Point = None
    add = pt
    while k:
        if k & 1:
            out = g1_add(out, add)
        add = g1_add(add, add)
        k >>= 1
    return out


def g1_mul2(p: G1Point, a: int, q: G1Point, b: int) -> G1Point:
    """a*P + b*Q (amcl Mul2)."""
    return g1_add(g1_mul(p, a), g1_mul(q, b))


# --------------------------------------------------------------------------
# G2: E'(Fp2) : y^2 = x^3 + 3/xi (M-type twist). Affine Fp2 coords.
# --------------------------------------------------------------------------

G2Point = Optional[Tuple[Fp2, Fp2]]

# M-type sextic twist (amcl CONFIG_CURVE SEXTIC_TWIST = M_TYPE):
# E' : y^2 = x^3 + b * xi
TWIST_B: Fp2 = fp2_scalar(XI, B_COEFF)
G2_GEN: G2Point = ((G2_XA, G2_XB), (G2_YA, G2_YB))


def g2_is_on_curve(pt: G2Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    lhs = fp2_sqr(y)
    rhs = fp2_add(fp2_mul(fp2_sqr(x), x), TWIST_B)
    return lhs == rhs


def g2_add(p1: G2Point, p2: G2Point) -> G2Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp2_add(y1, y2) == FP2_ZERO:
            return None
        lam = fp2_mul(
            fp2_scalar(fp2_sqr(x1), 3), fp2_inv(fp2_scalar(y1, 2))
        )
    else:
        lam = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sqr(lam), x1), x2)
    y3 = fp2_sub(fp2_mul(lam, fp2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_neg(pt: G2Point) -> G2Point:
    return None if pt is None else (pt[0], fp2_neg(pt[1]))


def g2_mul(pt: G2Point, k: int) -> G2Point:
    k %= R
    out: G2Point = None
    add = pt
    while k:
        if k & 1:
            out = g2_add(out, add)
        add = g2_add(add, add)
        k >>= 1
    return out


# --------------------------------------------------------------------------
# Pairing: optimal ate over E(Fp12) with generic line functions.
# --------------------------------------------------------------------------

# Untwist map for the M-type twist E' -> E over Fp12:
#   psi(x', y') = (x' / w^2, y' / w^3) = (x' w^4 / xi, y' w^3 / xi)
# since w^6 = xi. Check: y'^2/w^6 = x'^3/w^6 + 3  <=>  y'^2 = x'^3 + 3 xi,
# exactly E'. Verified numerically in tests.


def _untwist(pt: G2Point) -> Optional[Tuple[Fp12, Fp12]]:
    if pt is None:
        return None
    x, y = pt
    xi_inv = fp2_inv(XI)
    fx = fp12_from_fp2(fp2_mul(x, xi_inv), 4)  # x' * w^4 / xi
    fy = fp12_from_fp2(fp2_mul(y, xi_inv), 3)  # y' * w^3 / xi
    return (fx, fy)


E12Point = Optional[Tuple[Fp12, Fp12]]


def _e12_add(p1: E12Point, p2: E12Point) -> E12Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp12_add(y1, y2) == FP12_ZERO:
            return None
        lam = fp12_mul(
            fp12_add(fp12_add(fp12_sqr(x1), fp12_sqr(x1)), fp12_sqr(x1)),
            fp12_inv(fp12_add(y1, y1)),
        )
    else:
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    x3 = fp12_sub(fp12_sub(fp12_sqr(lam), x1), x2)
    y3 = fp12_sub(fp12_mul(lam, fp12_sub(x1, x3)), y1)
    return (x3, y3)


def _line(t: E12Point, q: E12Point, p_g1: Tuple[int, int]) -> Fp12:
    """Evaluate the line through T and Q (tangent when T==Q) at the G1
    point P embedded in Fp12."""
    px = fp12_from_fp2((p_g1[0], 0), 0)
    py = fp12_from_fp2((p_g1[1], 0), 0)
    if t is None or q is None:
        raise ArithmeticError("line evaluation through the point at infinity")
    x1, y1 = t
    x2, y2 = q
    if x1 == x2 and y1 == y2:
        three_x2 = fp12_add(fp12_add(fp12_sqr(x1), fp12_sqr(x1)), fp12_sqr(x1))
        lam = fp12_mul(three_x2, fp12_inv(fp12_add(y1, y1)))
    elif x1 == x2:
        # vertical line: x - x1
        return fp12_sub(px, x1)
    else:
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    # l = (py - y1) - lam * (px - x1)
    return fp12_sub(fp12_sub(py, y1), fp12_mul(lam, fp12_sub(px, x1)))


def miller_loop(q: G2Point, p: G1Point) -> Fp12:
    """f_{|6u+2|, Q}(P) with the two frobenius correction lines (optimal
    ate for BN curves); conjugated at the end for u < 0."""
    if q is None or p is None:
        return FP12_ONE
    six_u_two = 6 * U + 2
    n = abs(six_u_two)
    qe = _untwist(q)
    t = qe
    f = FP12_ONE
    for bit in bin(n)[3:]:
        f = fp12_mul(fp12_sqr(f), _line(t, t, p))
        t = _e12_add(t, t)
        if bit == "1":
            f = fp12_mul(f, _line(t, qe, p))
            t = _e12_add(t, qe)
    if six_u_two < 0:
        f = fp12_conj(f)
        t = (t[0], fp12_neg(t[1])) if t is not None else None
    # frobenius corrections: Q1 = pi_p(Q), Q2 = -pi_{p^2}(Q)
    q1 = (fp12_frobenius(qe[0], 1), fp12_frobenius(qe[1], 1))
    q2 = (fp12_frobenius(qe[0], 2), fp12_neg(fp12_frobenius(qe[1], 2)))
    f = fp12_mul(f, _line(t, q1, p))
    t = _e12_add(t, q1)
    f = fp12_mul(f, _line(t, q2, p))
    return f


def line_coeffs(
    t: Tuple[Fp12, Fp12], q: Tuple[Fp12, Fp12]
) -> Tuple[Fp12, Fp12]:
    """(A, B) with l(P) = A + B·px + py — the chord/tangent line of
    `_line` factored into P-independent Fp12 constants, so fixed-G2
    Miller schedules (ops/pairing_kernel, crypto/hostbn) can precompute
    them per issuer.  Expanding `_line`: (py − y1) − λ(px − x1) =
    (λ·x1 − y1) + (−λ)·px + py.  Vertical lines cannot occur in the ate
    chain of order-r points — raised, never silently mis-evaluated."""
    x1, y1 = t
    x2, y2 = q
    if x1 == x2 and y1 == y2:
        three_x2 = fp12_add(fp12_add(fp12_sqr(x1), fp12_sqr(x1)), fp12_sqr(x1))
        lam = fp12_mul(three_x2, fp12_inv(fp12_add(y1, y1)))
    else:
        if x1 == x2:
            raise ArithmeticError("vertical line in ate loop (unexpected)")
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    return fp12_sub(fp12_mul(lam, x1), y1), fp12_neg(lam)


_HARD_EXP = (pow(P, 4) - pow(P, 2) + 1) // R


def final_exp(f: Fp12) -> Fp12:
    """f^((p^12 - 1) / r): easy part (p^6-1)(p^2+1), then a direct
    exponentiation by the ~1020-bit hard part (p^4 - p^2 + 1)/r. The
    oracle favors obvious correctness; the device kernel uses the
    x-power addition chain."""
    f = fp12_mul(fp12_conj(f), fp12_inv(f))  # f^(p^6 - 1): now unitary
    f = fp12_mul(fp12_frobenius(f, 2), f)  # ^(p^2 + 1)
    return fp12_pow(f, _HARD_EXP)


def ate(q: G2Point, p: G1Point) -> Fp12:
    """FP256BN.Ate analog (NOT final-exponentiated)."""
    return miller_loop(q, p)


def fexp(f: Fp12) -> Fp12:
    return final_exp(f)


def pairing(q: G2Point, p: G1Point) -> Fp12:
    return final_exp(miller_loop(q, p))


def gt_is_unity(f: Fp12) -> bool:
    return f == FP12_ONE


# --------------------------------------------------------------------------
# Serialization (idemix/util.go parity)
# --------------------------------------------------------------------------


def big_to_bytes(n: int) -> bytes:
    return (n % (1 << 256)).to_bytes(FIELD_BYTES, "big")


def big_from_bytes(b: bytes) -> int:
    return int.from_bytes(b[:FIELD_BYTES], "big")


def g1_to_bytes(pt: G1Point) -> bytes:
    """amcl ECP.ToBytes(compress=False): 0x04 || x || y."""
    if pt is None:
        return b"\x04" + b"\x00" * 64
    return b"\x04" + big_to_bytes(pt[0]) + big_to_bytes(pt[1])


def g1_from_bytes(b: bytes) -> G1Point:
    if len(b) != 65 or b[0] != 0x04:
        raise ValueError("bad G1 encoding")
    x = big_from_bytes(b[1:33])
    y = big_from_bytes(b[33:65])
    if x == 0 and y == 0:
        return None
    pt = (x, y)
    if not g1_is_on_curve(pt):
        raise ValueError("G1 point not on curve")
    return pt


def g2_to_bytes(pt: G2Point) -> bytes:
    """amcl ECP2.ToBytes: xa || xb || ya || yb."""
    if pt is None:
        return b"\x00" * 128
    (xa, xb), (ya, yb) = pt
    return (
        big_to_bytes(xa) + big_to_bytes(xb) + big_to_bytes(ya) + big_to_bytes(yb)
    )


def g2_from_bytes(b: bytes) -> G2Point:
    if len(b) != 128:
        raise ValueError("bad G2 encoding")
    xa, xb, ya, yb = (big_from_bytes(b[i * 32 : (i + 1) * 32]) for i in range(4))
    pt = ((xa, xb), (ya, yb))
    if not g2_is_on_curve(pt):
        raise ValueError("G2 point not on twist curve")
    return pt


def hash_mod_order(data: bytes) -> int:
    """idemix HashModOrder: SHA-256(data) interpreted big-endian mod r."""
    return big_from_bytes(hashlib.sha256(data).digest()) % R


def rand_mod_order(rng) -> int:
    """Uniform scalar in [0, r). `rng` is a random.Random or secrets-like
    object exposing randrange."""
    return rng.randrange(R)
