"""Transaction validation codes and the per-block flags bitmask.

Code values are wire-compatible with the reference
(fabric-protos peer/transaction.proto TxValidationCode; array semantics per
usable-inter-nal/pkg/txflags/validation_flags.go): one uint8 per
transaction, stored in block metadata TRANSACTIONS_FILTER. This is THE
parity surface — the TPU pipeline must produce the identical byte string.
"""

from __future__ import annotations

import enum

import numpy as np


class TxValidationCode(enum.IntEnum):
    VALID = 0
    NIL_ENVELOPE = 1
    BAD_PAYLOAD = 2
    BAD_COMMON_HEADER = 3
    BAD_CREATOR_SIGNATURE = 4
    INVALID_ENDORSER_TRANSACTION = 5
    INVALID_CONFIG_TRANSACTION = 6
    UNSUPPORTED_TX_PAYLOAD = 7
    BAD_PROPOSAL_TXID = 8
    DUPLICATE_TXID = 9
    ENDORSEMENT_POLICY_FAILURE = 10
    MVCC_READ_CONFLICT = 11
    PHANTOM_READ_CONFLICT = 12
    UNKNOWN_TX_TYPE = 13
    TARGET_CHAIN_NOT_FOUND = 14
    MARSHAL_TX_ERROR = 15
    NIL_TXACTION = 16
    EXPIRED_CHAINCODE = 17
    CHAINCODE_VERSION_CONFLICT = 18
    BAD_HEADER_EXTENSION = 19
    BAD_CHANNEL_HEADER = 20
    BAD_RESPONSE_PAYLOAD = 21
    BAD_RWSET = 22
    ILLEGAL_WRITESET = 23
    INVALID_WRITESET = 24
    INVALID_CHAINCODE = 25
    NOT_VALIDATED = 254
    INVALID_OTHER_REASON = 255


class ValidationFlags:
    """uint8-per-tx flags array (TRANSACTIONS_FILTER payload)."""

    def __init__(self, size: int, value: TxValidationCode = TxValidationCode.NOT_VALIDATED):
        self._flags = np.full(size, int(value), dtype=np.uint8)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "ValidationFlags":
        out = cls(0)
        out._flags = np.frombuffer(raw, dtype=np.uint8).copy()
        return out

    def __len__(self) -> int:
        return len(self._flags)

    def set_flag(self, tx_index: int, flag: TxValidationCode) -> None:
        self._flags[tx_index] = int(flag)

    def flag(self, tx_index: int) -> TxValidationCode:
        return TxValidationCode(int(self._flags[tx_index]))

    def is_valid(self, tx_index: int) -> bool:
        return self._flags[tx_index] == int(TxValidationCode.VALID)

    def is_set_to(self, tx_index: int, flag: TxValidationCode) -> bool:
        return self._flags[tx_index] == int(flag)

    def all_validated(self) -> bool:
        return not (self._flags == int(TxValidationCode.NOT_VALIDATED)).any()

    def tobytes(self) -> bytes:
        return self._flags.tobytes()

    def asarray(self) -> np.ndarray:
        return self._flags

    def __eq__(self, other) -> bool:
        return isinstance(other, ValidationFlags) and np.array_equal(
            self._flags, other._flags
        )

    def __repr__(self) -> str:
        return f"ValidationFlags({[TxValidationCode(int(f)).name for f in self._flags]})"
