"""Pure-Python NIST P-256 (secp256r1) arithmetic and ECDSA.

This module is the *oracle*: a small, dependency-free, big-int implementation
of exactly the verification semantics the reference software crypto provider
has (reference: bccsp/sw/ecdsa.go:41-57 -> Go crypto/ecdsa + the low-S rule in
bccsp/utils/ecdsa.go). The batched TPU kernel in fabric_tpu.ops.p256_kernel is
differentially tested against this module, and this module in turn is tested
against the `cryptography` package.

It is intentionally written for clarity, not speed, and is also used as the
host-side fallback provider on machines without an accelerator.
"""

from __future__ import annotations

import hashlib
import secrets
from typing import NamedTuple, Optional, Tuple

# Curve parameters (FIPS 186-4 / SEC2 secp256r1). Public constants.
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
# The reference accepts only low-S signatures: s <= N >> 1
# (bccsp/utils/ecdsa.go curveHalfOrders / IsLowS).
HALF_N = N >> 1

# Affine points are (x, y) tuples; None is the point at infinity.
AffinePoint = Optional[Tuple[int, int]]


def is_on_curve(pt: AffinePoint) -> bool:
    if pt is None:
        return True
    x, y = pt
    if not (0 <= x < P and 0 <= y < P):
        return False
    return (y * y - (x * x * x + A * x + B)) % P == 0


def point_neg(pt: AffinePoint) -> AffinePoint:
    if pt is None:
        return None
    x, y = pt
    return (x, (-y) % P)


def point_add(p1: AffinePoint, p2: AffinePoint) -> AffinePoint:
    """Affine group law (slow; oracle only)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None  # p1 == -p2
        # doubling
        lam = (3 * x1 * x1 + A) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def scalar_mult(k: int, pt: AffinePoint) -> AffinePoint:
    """k * pt by double-and-add (oracle only)."""
    k %= N
    result: AffinePoint = None
    addend = pt
    while k:
        if k & 1:
            result = point_add(result, addend)
        addend = point_add(addend, addend)
        k >>= 1
    return result


GENERATOR: Tuple[int, int] = (GX, GY)


def hash_to_int(digest: bytes) -> int:
    """Leftmost-bits digest truncation, matching Go crypto/ecdsa hashToInt.

    For P-256 orderBits = 256: take the leftmost 32 bytes, then shift right
    by any excess bits (none when len(digest) is a whole number of bytes
    covering >= 256 bits).
    """
    order_bits = 256
    order_bytes = (order_bits + 7) // 8
    if len(digest) > order_bytes:
        digest = digest[:order_bytes]
    e = int.from_bytes(digest, "big")
    excess = len(digest) * 8 - order_bits
    if excess > 0:
        e >>= excess
    return e


def is_low_s(s: int) -> bool:
    """Reference low-S rule: s <= N>>1 (bccsp/utils/ecdsa.go IsLowS)."""
    return s <= HALF_N


def verify_digest(pub: Tuple[int, int], digest: bytes, r: int, s: int) -> bool:
    """Raw ECDSA verification (Go crypto/ecdsa.Verify semantics).

    Does NOT apply the low-S rule; callers replicating the reference
    verifyECDSA (bccsp/sw/ecdsa.go:41) must check is_low_s first.
    """
    if not (1 <= r < N and 1 <= s < N):
        return False
    if not is_on_curve(pub) or pub is None:
        return False
    e = hash_to_int(digest)
    w = pow(s, N - 2, N)
    u1 = (e * w) % N
    u2 = (r * w) % N
    pt = point_add(scalar_mult(u1, GENERATOR), scalar_mult(u2, pub))
    if pt is None:
        return False
    return pt[0] % N == r


def sign_digest(
    priv: int, digest: bytes, k: Optional[int] = None, low_s: bool = True
) -> Tuple[int, int]:
    """ECDSA signing (for vector generation / the SW provider).

    Matches the reference signer, which normalizes to low-S
    (bccsp/sw/ecdsa.go signECDSA -> utils.ToLowS).
    """
    e = hash_to_int(digest)
    while True:
        kk = k if k is not None else (secrets.randbelow(N - 1) + 1)
        pt = scalar_mult(kk, GENERATOR)
        if pt is None:
            raise ArithmeticError("k*G is infinity for k in [1, N-1]")
        r = pt[0] % N
        if r == 0:
            if k is not None:
                raise ValueError("bad fixed nonce: r == 0")
            continue
        s = (pow(kk, N - 2, N) * (e + r * priv)) % N
        if s == 0:
            if k is not None:
                raise ValueError("bad fixed nonce: s == 0")
            continue
        if low_s and not is_low_s(s):
            s = N - s
        return r, s


class KeyPair(NamedTuple):
    priv: int
    pub: Tuple[int, int]


def generate_keypair() -> KeyPair:
    d = secrets.randbelow(N - 1) + 1
    q = scalar_mult(d, GENERATOR)
    if q is None:
        raise ArithmeticError("d*G is infinity for d in [1, N-1]")
    return KeyPair(d, q)


def pubkey_from_bytes(data: bytes) -> Tuple[int, int]:
    """Parse an uncompressed SEC1 point (0x04 || X || Y) and validate it."""
    if len(data) != 65 or data[0] != 0x04:
        raise ValueError("expected 65-byte uncompressed SEC1 point")
    x = int.from_bytes(data[1:33], "big")
    y = int.from_bytes(data[33:65], "big")
    pt = (x, y)
    if not is_on_curve(pt):
        raise ValueError("point not on curve")
    return pt


def pubkey_to_bytes(pub: Tuple[int, int]) -> bytes:
    return b"\x04" + pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()
