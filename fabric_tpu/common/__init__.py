"""Shared leaf types (lowest layer above protos).

`limbparams` is the canonical, jax-free home of the limb-radix
constants (LIMB_BITS / NLIMBS / LIMB_MASK / RADIX_BITS);
`fabric_tpu.ops.bignum` re-exports them for the device tier.
"""
