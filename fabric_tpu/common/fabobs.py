"""fabobs — process-wide observability registry for the validation data
plane.

The runtime carries named *obs points* at its hot seams — the same
discipline as :mod:`fabric_tpu.common.faults`: one module-global load
and a ``None`` check when observability is disabled, so production code
pays nothing until an operator turns the registry on.  Enabled, every
hook drives two layers at once:

1. **Metrics** — the Fabric-faithful :mod:`fabric_tpu.common.metrics`
   ``Provider`` SPI.  Families come from one canonical table
   (:data:`CANONICAL_METRICS`): family name, kind, labels, and the seam
   that emits it.  Enabling the registry eagerly registers every family,
   so a ``/metrics`` scrape always shows the full canonical surface
   (``# TYPE`` lines) even before traffic arrives.
2. **Spans + flight recorder** — ``span(name)`` context managers with
   monotonic clocks, thread-propagated parent links (a
   ``threading.local`` stack; cross-thread hand-offs pass an explicit
   ``parent=``), all landing in a bounded ring buffer.  ``dump()``
   renders the ring as Chrome trace-event JSON (``chrome://tracing`` /
   Perfetto); :func:`obs_trigger` snapshots it to disk automatically on
   degrade/fail-closed events so the moments worth debugging are the
   moments that self-record.

Mask safety contract (this file rides the fabflow MASK tier): no
function here produces or transforms a verdict mask, and every enabled
path is wrapped so an observability failure is swallowed with a debug
log — instrumentation can slow a verify path down, never alter it or
fail it.  The hooks are therefore safe to call from inside mask-critical
code without try/except at the call site.

Enable programmatically (tests use the scoped form)::

    from fabric_tpu.common import fabobs
    reg = fabobs.enable()                     # fresh PrometheusProvider
    with fabobs.obs_installed() as reg: ...   # scoped; restores previous

or from the environment (same warn-never-raise discipline as
``FABRIC_TPU_FAULTS``)::

    FABRIC_TPU_OBS=1                 # enable (prometheus provider)
    FABRIC_TPU_OBS_RING=8192         # flight-recorder ring size
    FABRIC_TPU_OBS_DUMP_DIR=/tmp/ft  # auto-dump traces on obs_trigger
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from fabric_tpu.common import metrics as metrics_mod
from fabric_tpu.common.flogging import must_get_logger

logger = must_get_logger("fabobs")

# latency histograms: the shared prometheus-style seconds ladder
LATENCY_BUCKETS = metrics_mod.DEFAULT_BUCKETS
# lane-count histograms (batch sizes): powers of four up to the
# max_pending_lanes default, so bucket edges track the bucket ladder
LANE_BUCKETS = (1.0, 8.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0)
# pipeline-stage latency: the default ladder extended downward — warm
# host-ladder prepare sits in the sub-millisecond range the 5ms lowest
# default bucket would flatten.  ONE definition shared by the /metrics
# series AND peer/pipeline's embedded stage_stats state, so the two
# surfaces can never quantize the same stage differently.
STAGE_BUCKETS = (0.0005, 0.001, 0.0025) + LATENCY_BUCKETS


@dataclass(frozen=True)
class MetricSpec:
    """One canonical family: the README metric-name table is generated
    from these entries, and the obs_gate asserts every one appears on a
    live ``/metrics`` scrape."""

    name: str
    kind: str  # counter | gauge | histogram
    labels: Tuple[str, ...]
    help: str
    seam: str
    buckets: Tuple[float, ...] = ()


#: The canonical metric-name table.  Adding an obs hook to a new seam
#: means adding its family here FIRST — an unknown family is swallowed
#: (debug log + dropped counter), never implicitly registered.
CANONICAL_METRICS: Tuple[MetricSpec, ...] = (
    # -- VerifyBatcher (parallel/batcher.py) ---------------------------
    MetricSpec(
        "fabric_batcher_pending_lanes", "gauge", (),
        "lanes admitted but not yet dispatched (admission-control fill)",
        "parallel/batcher.py _admit/_run",
    ),
    MetricSpec(
        "fabric_batcher_batch_lanes", "histogram", (),
        "coalesced lanes per device/provider launch",
        "parallel/batcher.py _run", LANE_BUCKETS,
    ),
    MetricSpec(
        "fabric_batcher_submit_wait_seconds", "histogram", (),
        "submit -> settle latency per request",
        "parallel/batcher.py _settle", LATENCY_BUCKETS,
    ),
    MetricSpec(
        "fabric_batcher_launches_total", "counter", ("mode",),
        "provider launches by transport mode (coalesce|passthrough)",
        "parallel/batcher.py _run",
    ),
    MetricSpec(
        "fabric_batcher_busy_rejects_total", "counter", (),
        "try_submit admissions rejected (ST_BUSY backpressure)",
        "parallel/batcher.py _admit",
    ),
    MetricSpec(
        "fabric_batcher_dispatch_retries_total", "counter", (),
        "transient launch failures retried by the dispatch policy",
        "parallel/batcher.py _launch",
    ),
    MetricSpec(
        "fabric_batcher_fail_closed_total", "counter", (),
        "requests settled all-False by a stopping/hung batcher",
        "parallel/batcher.py stop",
    ),
    # -- backend ladder rungs (crypto/, serve/client.py) ---------------
    MetricSpec(
        "fabric_verify_lanes_total", "counter", ("rung",),
        "signature lanes verified per ladder rung "
        "(fastec|hostec_np|hostec|p256|device|serve|hostbn|scheme)",
        "crypto/bccsp.py, crypto/tpu_provider.py, serve/client.py, "
        "idemix/batch.py",
    ),
    MetricSpec(
        "fabric_verify_seconds", "histogram", ("rung",),
        "batch verify wall time per ladder rung",
        "crypto/bccsp.py, crypto/tpu_provider.py, serve/client.py",
        LATENCY_BUCKETS,
    ),
    MetricSpec(
        "fabric_degrade_total", "counter", ("seam",),
        "degrade transitions (sidecar->in-process, pool->inline, "
        "device->software)",
        "serve/client.py, crypto/hostec*.py, crypto/tpu_provider.py",
    ),
    MetricSpec(
        "fabric_pool_rebuilds_total", "counter", ("pool",),
        "process-pool constructions (hostec|hostec_np)",
        "crypto/hostec.py, crypto/hostec_np.py",
    ),
    MetricSpec(
        "fabric_pool_cooldowns_total", "counter", ("pool",),
        "broken-pool teardowns arming the rebuild cooldown",
        "crypto/hostec.py, crypto/hostec_np.py",
    ),
    # -- serve sidecar (serve/server.py) -------------------------------
    MetricSpec(
        "fabric_serve_requests_total", "counter", ("status",),
        "verify requests by reply status (ok|busy|error|stopping)",
        "serve/server.py ServeStats",
    ),
    MetricSpec(
        "fabric_serve_lanes_total", "counter", (),
        "lanes served OK by the sidecar",
        "serve/server.py ServeStats",
    ),
    MetricSpec(
        "fabric_serve_request_seconds", "histogram", (),
        "decode -> reply latency of served verify requests",
        "serve/server.py ServeStats", LATENCY_BUCKETS,
    ),
    MetricSpec(
        "fabric_serve_bucket_requests_total", "counter", ("bucket",),
        "served requests per registry lane bucket",
        "serve/server.py ServeStats",
    ),
    MetricSpec(
        "fabric_serve_connections_total", "counter", ("event",),
        "client connection churn (open|close)",
        "serve/server.py _accept_loop/_serve_conn",
    ),
    MetricSpec(
        "fabric_serve_class_lanes_total", "counter", ("cls",),
        "lanes served OK per admission class (high|normal|bulk)",
        "serve/server.py ServeStats",
    ),
    MetricSpec(
        "fabric_serve_class_busy_total", "counter", ("cls",),
        "ST_BUSY sheds per admission class — every rejection is a "
        "protocol-level reply, never a silent drop",
        "serve/server.py ServeStats",
    ),
    MetricSpec(
        "fabric_serve_endpoint_healthy", "gauge", ("endpoint",),
        "router endpoint health (1 = in rotation, 0 = evicted/cooling)",
        "serve/router.py _Endpoint",
    ),
    # -- tail tolerance (fabtail: serve/router.py, serve/server.py,
    #    serve/client.py) ----------------------------------------------
    MetricSpec(
        "fabric_serve_hedges_total", "counter", (),
        "hedged requests fired at a second endpoint after the primary "
        "stayed silent past its learned hedge delay",
        "serve/router.py _await_hedged",
    ),
    MetricSpec(
        "fabric_serve_hedge_wins_total", "counter", (),
        "hedges whose verdict arrived before the primary's (the loser "
        "is cancelled best-effort via OP_CANCEL)",
        "serve/router.py _await_hedged",
    ),
    MetricSpec(
        "fabric_serve_deadline_expired_total", "counter", ("seam",),
        "wire-deadline budgets that ran out (serve.server = provably-"
        "unfinishable work shed ST_BUSY; serve.client / serve.router = "
        "batches handed back to the in-process ladder)",
        "serve/server.py ServeStats, serve/client.py, serve/router.py",
    ),
    MetricSpec(
        "fabric_serve_slow_evictions_total", "counter", ("endpoint",),
        "gray-failure evictions: endpoints alive but latency outliers "
        "(EWMA far above the fleet best, or consecutive lost hedges) "
        "pulled from rotation through the cooldown ladder",
        "serve/router.py _evict_slow",
    ),
    MetricSpec(
        "fabric_serve_bucket_warm_ms", "gauge", ("bucket",),
        "per-bucket warm wall ms (registry warm report)",
        "serve/server.py warm",
    ),
    MetricSpec(
        "fabric_serve_bucket_xla_compiles", "gauge", ("bucket",),
        "XLA compiles the bucket warm paid (0 = AOT/cache)",
        "serve/server.py warm",
    ),
    MetricSpec(
        "fabric_serve_bucket_cache_hits", "gauge", ("bucket",),
        "persistent compile-cache hits during the bucket warm",
        "serve/server.py warm",
    ),
    MetricSpec(
        "fabric_serve_bucket_aot_hit", "gauge", ("bucket",),
        "1 when the bucket loaded its serialized AOT artifact",
        "serve/server.py warm",
    ),
    # -- commit pipeline (peer/pipeline.py) ----------------------------
    MetricSpec(
        "fabric_pipeline_stage_seconds", "histogram", ("stage",),
        "per-stage latency (prepare|commit) of the two-stage pipeline",
        "peer/pipeline.py", STAGE_BUCKETS,
    ),
    MetricSpec(
        "fabric_pipeline_commit_failures_total", "counter", (),
        "commit-stage exceptions surfaced to the owner",
        "peer/pipeline.py _commit_loop",
    ),
    # -- shared retry/backoff (common/retry.py) ------------------------
    MetricSpec(
        "fabric_retry_attempts_total", "counter", (),
        "backoff sleeps taken across every retry loop",
        "common/retry.py Backoff.sleep",
    ),
    MetricSpec(
        "fabric_retry_backoff_seconds", "histogram", (),
        "nominal delay per backoff sleep",
        "common/retry.py Backoff.sleep", LATENCY_BUCKETS,
    ),
    # -- fault injection (common/faults.py) ----------------------------
    MetricSpec(
        "fabric_fault_fired_total", "counter", ("site",),
        "injected faults that actually fired, per site",
        "common/faults.py fault_point",
    ),
    # -- crash-consistent commit plane (fabcrash, ledger/) -------------
    MetricSpec(
        "fabric_ledger_recovered_blocks_total", "counter", (),
        "blocks replayed into state/pvt by restart recovery (the gap "
        "between the block store and the state savepoint)",
        "ledger/kvledger.py _recover",
    ),
    MetricSpec(
        "fabric_ledger_torn_tail_total", "counter", ("store",),
        "torn tail records truncated on recovery (chain|pvtdata)",
        "ledger/blockstore.py _rebuild_index, ledger/pvtdatastore.py "
        "_recover",
    ),
    MetricSpec(
        "fabric_ledger_recovery_refusals_total", "counter", ("reason",),
        "recoveries refused fail-closed (corrupt-chain|corrupt-pvtdata|"
        "statedb-ahead): inconsistency recovery cannot repair forward",
        "ledger/blockstore.py _refuse, ledger/pvtdatastore.py _refuse, "
        "ledger/kvledger.py _recover",
    ),
    MetricSpec(
        "fabric_mvcc_table_invalidations_total", "counter", (),
        "resident MVCC version tables dropped because the state db "
        "generation moved behind their back (stale reads fail closed)",
        "ledger/mvcc_device.py ResidentDeviceValidator",
    ),
)

CANONICAL_BY_NAME: Dict[str, MetricSpec] = {
    s.name: s for s in CANONICAL_METRICS
}


# ---------------------------------------------------------------------------
# Span / flight-recorder layer
# ---------------------------------------------------------------------------

_tls = threading.local()


def _span_stack() -> List["Span"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_span() -> Optional["Span"]:
    """The innermost open span on THIS thread (cross-thread hand-offs
    pass it as ``span(..., parent=...)`` explicitly)."""
    stack = _span_stack()
    return stack[-1] if stack else None


class Span:
    """One timed section.  Entering pushes it on the thread's span
    stack; exiting records a Chrome ``ph:"X"`` complete event into the
    registry's flight ring.  Failures inside the obs machinery are
    swallowed (``_swallow``); exceptions from the *wrapped* code
    propagate untouched — a span can never eat a verify error."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "_reg", "_t0")

    def __init__(self, reg: "ObsRegistry", name: str, attrs: Dict,
                 parent: Optional["Span"] = None):
        self._reg = reg
        self.name = name
        self.attrs = attrs
        self.span_id = reg._next_span_id()
        self.parent_id = parent.span_id if parent is not None else 0
        self._t0 = 0.0

    def __enter__(self) -> "Span":
        try:
            if self.parent_id == 0:
                cur = current_span()
                if cur is not None:
                    self.parent_id = cur.span_id
            _span_stack().append(self)
            self._t0 = time.perf_counter()
        except Exception as exc:  # noqa: BLE001 - obs must never raise
            self._reg._swallow("span.enter", exc)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            t1 = time.perf_counter()
            stack = _span_stack()
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:  # tolerate mis-nested exits
                stack.remove(self)
            args = dict(self.attrs)
            args["span_id"] = self.span_id
            if self.parent_id:
                args["parent_id"] = self.parent_id
            if exc_type is not None:
                args["error"] = exc_type.__name__
            self._reg._record_event(
                {
                    "name": self.name,
                    "ph": "X",
                    "ts": self._reg._us(self._t0),
                    "dur": round((t1 - self._t0) * 1e6, 1),
                    "args": args,
                }
            )
        except Exception as swallow_exc:  # noqa: BLE001 - obs must never raise
            self._reg._swallow("span.exit", swallow_exc)
        # never suppress the wrapped code's exception (implicit None)


class _NoopSpan:
    """Shared do-nothing span: what ``span()`` returns when the registry
    is disabled, and what enabled hooks fall back to on internal
    failure.  Reentrant and stateless."""

    __slots__ = ()
    name = "noop"
    span_id = 0
    parent_id = 0

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NOOP_SPAN = _NoopSpan()


# ---------------------------------------------------------------------------
# The registry
# ---------------------------------------------------------------------------


class ObsRegistry:
    """One process-wide observability hub: metric instruments for every
    canonical family plus the span flight ring.  All mutable state is
    guarded by ``_lock`` (fabdep unguarded-shared-write discipline);
    metric series carry their own per-family locks inside the SPI."""

    def __init__(
        self,
        provider: Optional[metrics_mod.Provider] = None,
        ring: int = 4096,
        dump_dir: Optional[str] = None,
        max_dumps: int = 8,
    ):
        self.provider = provider or metrics_mod.PrometheusProvider()
        self.dump_dir = dump_dir
        self.max_dumps = max_dumps
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(ring)))
        self._epoch = time.perf_counter()
        self._span_seq = 0
        self._dumps = 0
        self._dumped_paths: List[str] = []
        self.dropped = 0  # obs failures swallowed (self-accounting)
        self._warned_families: set = set()
        self._instruments: Dict[str, object] = {}
        for spec in CANONICAL_METRICS:
            try:
                self._instruments[spec.name] = self._build(spec)
            except Exception as exc:  # noqa: BLE001 - obs must never raise
                self._swallow(f"register:{spec.name}", exc)

    # -- instrument construction ----------------------------------------
    def _build(self, spec: MetricSpec):
        if spec.kind == "counter":
            return self.provider.new_counter(
                metrics_mod.CounterOpts(
                    name=spec.name, help=spec.help, label_names=spec.labels
                )
            )
        if spec.kind == "gauge":
            return self.provider.new_gauge(
                metrics_mod.GaugeOpts(
                    name=spec.name, help=spec.help, label_names=spec.labels
                )
            )
        if spec.kind == "histogram":
            return self.provider.new_histogram(
                metrics_mod.HistogramOpts(
                    name=spec.name,
                    help=spec.help,
                    label_names=spec.labels,
                    buckets=spec.buckets or LATENCY_BUCKETS,
                )
            )
        raise ValueError(f"unknown metric kind {spec.kind!r}")

    def _lookup(self, name: str, labels: Dict[str, str]):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                first = name not in self._warned_families
                self._warned_families.add(name)
            if first:
                logger.debug(
                    "obs point %r is not in the canonical metric table; "
                    "dropped", name,
                )
            return None
        if labels:
            flat: List[str] = []
            for k, v in labels.items():
                flat.append(k)
                flat.append(str(v))
            inst = inst.with_labels(*flat)
        return inst

    # -- hot-path sinks (never raise) ------------------------------------
    def count(self, name: str, n: float = 1.0, **labels) -> None:
        try:
            inst = self._lookup(name, labels)
            if inst is not None:
                inst.add(n)
        except Exception as exc:  # noqa: BLE001 - obs must never raise
            self._swallow(name, exc)

    def gauge(self, name: str, value: float, **labels) -> None:
        try:
            inst = self._lookup(name, labels)
            if inst is not None:
                inst.set(value)
        except Exception as exc:  # noqa: BLE001 - obs must never raise
            self._swallow(name, exc)

    def observe(self, name: str, value: float, **labels) -> None:
        try:
            inst = self._lookup(name, labels)
            if inst is not None:
                inst.observe(value)
        except Exception as exc:  # noqa: BLE001 - obs must never raise
            self._swallow(name, exc)

    def span(self, name: str, parent: Optional[Span] = None, **attrs) -> Span:
        try:
            return Span(self, name, attrs, parent=parent)
        except Exception as exc:  # noqa: BLE001 - obs must never raise
            self._swallow(name, exc)
            return _NOOP_SPAN  # type: ignore[return-value]

    def event(self, name: str, **attrs) -> None:
        """Instant flight-recorder mark (Chrome ``ph:"i"``)."""
        try:
            self._record_event(
                {
                    "name": name,
                    "ph": "i",
                    "ts": self._us(time.perf_counter()),
                    "s": "p",
                    "args": attrs,
                }
            )
        except Exception as exc:  # noqa: BLE001 - obs must never raise
            self._swallow(name, exc)

    def trigger(self, reason: str, **attrs) -> Optional[str]:
        """A degrade/fail-closed moment: record the event AND, when a
        dump dir is configured, snapshot the flight ring to disk (capped
        at ``max_dumps`` per process so a flapping seam cannot fill a
        disk).  Returns the dump path when one was written."""
        try:
            self.event(f"trigger:{reason}", **attrs)
            if not self.dump_dir:
                return None
            with self._lock:
                if self._dumps >= self.max_dumps:
                    return None
                self._dumps += 1
                seq = self._dumps
            safe = "".join(
                c if (c.isalnum() or c in "-_.") else "_" for c in reason
            )
            path = os.path.join(
                self.dump_dir, f"fabobs-{os.getpid()}-{seq:02d}-{safe}.json"
            )
            self.dump(path)
            with self._lock:
                self._dumped_paths.append(path)
            logger.warning("flight recorder dumped to %s (%s)", path, reason)
            return path
        except Exception as exc:  # noqa: BLE001 - obs must never raise
            self._swallow(f"trigger:{reason}", exc)
            return None

    # -- flight recorder --------------------------------------------------
    def _us(self, t: float) -> float:
        return round((t - self._epoch) * 1e6, 1)

    def _next_span_id(self) -> int:
        with self._lock:
            self._span_seq += 1
            return self._span_seq

    def _record_event(self, record: Dict) -> None:
        record.setdefault("pid", os.getpid())
        record.setdefault("tid", threading.get_ident())
        with self._lock:
            self._ring.append(record)

    def trace_events(self) -> List[Dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def dump(self, path: Optional[str] = None) -> str:
        """Chrome trace-event JSON of the flight ring (load it in
        ``chrome://tracing`` or Perfetto).  Writes ``path`` when given,
        returns the JSON text either way."""
        payload = json.dumps(
            {
                "traceEvents": self.trace_events(),
                "displayTimeUnit": "ms",
                "otherData": {"source": "fabric_tpu.fabobs"},
            },
            sort_keys=True,
        )
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(payload)
            os.replace(tmp, path)
        return payload

    def dumped_paths(self) -> List[str]:
        with self._lock:
            return list(self._dumped_paths)

    # -- scrape-side views -------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition of the provider (empty string for
        non-prometheus providers — the ops server answers 404 then)."""
        gather = getattr(self.provider, "gather", None)
        return gather() if callable(gather) else ""

    def snapshot(self) -> Dict:
        """JSON-able {family: {kind, series}} snapshot — what bench.py
        attaches as ``configs.metrics_snapshot``.  Histogram series
        collapse to the bucket-quantized summary
        (:func:`metrics.summary_from_histogram_state`)."""
        out: Dict[str, Dict] = {}
        prov = self.provider
        if not isinstance(prov, metrics_mod.PrometheusProvider):
            return out
        with prov._lock:
            families = dict(prov._metrics)
        for name, metric in sorted(families.items()):
            with metric.lock:
                series = dict(metric.series)
            rendered: Dict[str, object] = {}
            for labels, value in sorted(series.items()):
                key = ",".join(
                    f"{n}={v}"
                    for n, v in zip(metric.opts.label_names, labels)
                ) or "_"
                if isinstance(value, metrics_mod._HistState):
                    rendered[key] = metrics_mod.summary_from_histogram_state(
                        value, metric.opts.buckets  # type: ignore[attr-defined]
                    )
                else:
                    rendered[key] = value
            if rendered:
                out[name] = {"kind": metric.kind, "series": rendered}
        return out

    def _swallow(self, where: str, exc: BaseException) -> None:
        """The one rule of this module: an observability failure is
        accounted and debug-logged, NEVER raised into the observed
        code."""
        try:
            with self._lock:
                self.dropped += 1
            logger.debug("obs failure at %s swallowed: %s", where, exc)
        except Exception:  # noqa: BLE001 - last-ditch: even the swallow must not raise into a verify path
            pass


# ---------------------------------------------------------------------------
# Process-wide installation (the faults.py discipline: _OBS is written
# only under _OBS_LOCK; the hot-path read is one GIL-atomic global load)
# ---------------------------------------------------------------------------

_OBS: Optional[ObsRegistry] = None
_OBS_LOCK = threading.Lock()


def enable(
    provider: Optional[metrics_mod.Provider] = None,
    ring: int = 4096,
    dump_dir: Optional[str] = None,
    max_dumps: int = 8,
) -> ObsRegistry:
    """Install a fresh registry process-wide and return it."""
    global _OBS
    reg = ObsRegistry(
        provider=provider, ring=ring, dump_dir=dump_dir, max_dumps=max_dumps
    )
    with _OBS_LOCK:
        _OBS = reg
    return reg


def ensure_enabled(
    provider: Optional[metrics_mod.Provider] = None, **kwargs
) -> ObsRegistry:
    """Enable unless a registry is already installed (first enabler
    wins — one process, one obs hub).  Used by the node shells so a
    peer and its ops server share the provider without trampling an
    operator's earlier installation.  The registry is built outside the
    lock (construction registers every canonical family) and installed
    only if no racer got there first — the loser's registry is
    discarded, so two concurrent enablers can never silently replace
    each other's installation."""
    global _OBS
    existing = _OBS
    if existing is None:
        candidate = ObsRegistry(provider=provider, **kwargs)
        with _OBS_LOCK:
            if _OBS is None:
                _OBS = candidate
                return candidate
            existing = _OBS
    if provider is not None and existing.provider is not provider:
        logger.warning(
            "fabobs already enabled; keeping the existing provider "
            "(a second ops surface will not see data-plane series)"
        )
    return existing


def disable() -> None:
    global _OBS
    with _OBS_LOCK:
        _OBS = None


def enabled() -> bool:
    return _OBS is not None


def active() -> Optional[ObsRegistry]:
    return _OBS


class obs_installed:
    """``with obs_installed() as reg:`` — scoped enablement for tests
    and gates; the previous registry (usually None) is restored on exit,
    mirroring ``faults.plan_installed``."""

    def __init__(self, registry: Optional[ObsRegistry] = None, **kwargs):
        self.registry = registry if registry is not None else ObsRegistry(**kwargs)
        self._prev: Optional[ObsRegistry] = None

    def __enter__(self) -> ObsRegistry:
        global _OBS
        with _OBS_LOCK:
            self._prev = _OBS
            _OBS = self.registry
        return self.registry

    def __exit__(self, *exc) -> None:
        global _OBS
        with _OBS_LOCK:
            _OBS = self._prev


# -- the hot-path hooks ------------------------------------------------------


def obs_count(name: str, n: float = 1.0, **labels) -> None:
    """Add ``n`` to a canonical counter.  Disabled cost: one global
    load and a ``None`` check."""
    reg = _OBS
    if reg is None:
        return
    reg.count(name, n, **labels)


def obs_gauge(name: str, value: float, **labels) -> None:
    reg = _OBS
    if reg is None:
        return
    reg.gauge(name, value, **labels)


def obs_observe(name: str, value: float, **labels) -> None:
    reg = _OBS
    if reg is None:
        return
    reg.observe(name, value, **labels)


def span(name: str, parent: Optional[Span] = None, **attrs):
    """Context manager timing one section into the flight ring.
    Disabled: returns the shared no-op span (no allocation)."""
    reg = _OBS
    if reg is None:
        return _NOOP_SPAN
    return reg.span(name, parent=parent, **attrs)


def obs_event(name: str, **attrs) -> None:
    reg = _OBS
    if reg is None:
        return
    reg.event(name, **attrs)


def obs_trigger(reason: str, **attrs) -> Optional[str]:
    """Degrade/fail-closed mark + automatic flight-recorder dump (when a
    dump dir is configured).  Call it where the system gives ground:
    sidecar degrade, pool -> inline, batcher fail-closed settlement."""
    reg = _OBS
    if reg is None:
        return None
    return reg.trigger(reason, **attrs)


def snapshot() -> Dict:
    """{} when disabled; else the active registry's metric snapshot."""
    reg = _OBS
    return {} if reg is None else reg.snapshot()


def metric_table() -> List[Dict[str, str]]:
    """The canonical table as rows (README/docs generation + gates)."""
    return [
        {
            "name": s.name,
            "kind": s.kind,
            "labels": ",".join(s.labels),
            "seam": s.seam,
            "help": s.help,
        }
        for s in CANONICAL_METRICS
    ]


def _truthy(raw: str) -> bool:
    return raw.strip().lower() not in ("", "0", "false", "off", "no")


def _install_from_env() -> None:
    """Honor FABRIC_TPU_OBS at import so external runs (bench, a node
    under soak, the obs_gate chaos re-run) can be observed without code
    changes.  Malformed values warn and install nothing — observability
    knobs must never poison a production import."""
    raw = os.environ.get("FABRIC_TPU_OBS", "")
    if not _truthy(raw):
        return
    try:
        ring = int(os.environ.get("FABRIC_TPU_OBS_RING", "4096"))
    except ValueError:
        ring = 4096
    dump_dir = os.environ.get("FABRIC_TPU_OBS_DUMP_DIR", "") or None
    try:
        ensure_enabled(ring=ring, dump_dir=dump_dir)
    except Exception as exc:  # noqa: BLE001 - env install is best-effort
        import warnings

        warnings.warn(
            f"FABRIC_TPU_OBS ignored (install failed: {exc})",
            RuntimeWarning,
            stacklevel=2,
        )


_install_from_env()
