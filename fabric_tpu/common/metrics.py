"""Metrics provider SPI (reference common/metrics/provider.go:11-121).

The reference defines Counter/Gauge/Histogram interfaces with a
``With(labelValues...)`` currying pattern and three providers (prometheus,
statsd, disabled). This module keeps the same shape:

* ``CounterOpts/GaugeOpts/HistogramOpts`` — namespace/subsystem/name,
  help, label names, statsd format string.
* ``PrometheusProvider`` — in-process registry rendering the Prometheus
  text exposition format (served by the operations server at /metrics).
* ``StatsdProvider`` — formats ``%{#fqname}.%{label}`` style bucket names
  and hands values to a sink callable (UDP emitter or test buffer).
* ``DisabledProvider`` — no-ops.

Thread-safe; histograms keep fixed buckets + sum/count like Prometheus.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def latency_summary(samples_s: Sequence[float]) -> Dict[str, float]:
    """``{n, p50_ms, p99_ms, max_ms}`` over seconds-valued latency
    samples (``{"n": 0}`` when empty) — the one quantile-index
    definition shared by the serve sidecar's ServeStats, the commit
    pipeline's stage reservoirs, and bench.py's client-side columns,
    so the three surfaces can never silently diverge."""
    if not samples_s:
        return {"n": 0}
    s = sorted(samples_s)

    def pct(q: float) -> float:
        return s[min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))]

    return {
        "n": len(s),
        "p50_ms": round(pct(0.50) * 1e3, 3),
        "p99_ms": round(pct(0.99) * 1e3, 3),
        "max_ms": round(s[-1] * 1e3, 3),
    }


def summary_from_histogram_state(
    state: "_HistState", buckets: Sequence[float]
) -> Dict[str, float]:
    """``latency_summary``'s shape computed from accumulated histogram
    state instead of raw samples: quantiles are the upper bound of the
    bucket where the cumulative count crosses the rank (bucket-quantized,
    so an exact-sample consumer should keep ``latency_summary``).  The
    top open bucket has no upper bound; ranks landing there report a
    LOWER BOUND on that bucket's mean — ``(sum - bounded_count *
    top_bucket) / inf_count``, clamped to at least the top finite bound
    — so a tail outlier can never be reported below the ladder it
    overflowed.  Keys: ``{n, p50_ms, p99_ms, mean_ms}`` (``{"n": 0}``
    when empty)."""
    if state.total == 0:
        return {"n": 0}

    def pct(q: float) -> float:
        rank = q * (state.total - 1) + 1
        cum = 0
        for ub, c in zip(buckets, state.counts):
            cum += c
            if cum >= rank:
                return ub
        inf_count = state.total - sum(state.counts)
        if not inf_count:
            return buckets[-1]
        # bounded samples contribute at most bounded_count * top bucket
        # to the sum, so this is a conservative mean of the +Inf bucket
        bounded_cap = (state.total - inf_count) * buckets[-1]
        return max(buckets[-1], (state.sum - bounded_cap) / inf_count)

    return {
        "n": state.total,
        "p50_ms": round(pct(0.50) * 1e3, 3),
        "p99_ms": round(pct(0.99) * 1e3, 3),
        "mean_ms": round(state.sum / state.total * 1e3, 3),
    }


@dataclass(frozen=True)
class MetricOpts:
    namespace: str = ""
    subsystem: str = ""
    name: str = ""
    help: str = ""
    label_names: Tuple[str, ...] = ()
    statsd_format: str = ""

    def fq_name(self) -> str:
        parts = [p for p in (self.namespace, self.subsystem, self.name) if p]
        return "_".join(parts)


class CounterOpts(MetricOpts):
    pass


class GaugeOpts(MetricOpts):
    pass


@dataclass(frozen=True)
class HistogramOpts(MetricOpts):
    buckets: Tuple[float, ...] = DEFAULT_BUCKETS


def validate_label_values(
    opts: MetricOpts, label_values: Sequence[str]
) -> Tuple[str, ...]:
    """Name/value pairs -> the series key ordered by ``opts.label_names``.
    Shared by every provider's ``with_labels`` (the statsd path used to
    construct a throwaway ``_Metric`` per call just to run this)."""
    if len(label_values) % 2 != 0:
        raise ValueError("label values must come in name/value pairs")
    pairs = dict(zip(label_values[::2], label_values[1::2]))
    missing = [n for n in opts.label_names if n not in pairs]
    if missing:
        raise ValueError(f"missing label values: {missing}")
    return tuple(pairs[n] for n in opts.label_names)


class _Metric:
    """One named metric family; label-tuple -> series state."""

    def __init__(self, opts: MetricOpts, kind: str):
        self.opts = opts
        self.kind = kind
        self.lock = threading.Lock()
        self.series: Dict[Tuple[str, ...], object] = {}

    def _labels_key(self, label_values: Sequence[str]) -> Tuple[str, ...]:
        return validate_label_values(self.opts, label_values)


class Counter:
    def __init__(self, metric: _Metric, labels: Tuple[str, ...] = ()):
        self._m = metric
        self._labels = labels

    def with_labels(self, *label_values: str) -> "Counter":
        return Counter(self._m, self._m._labels_key(label_values))

    def add(self, delta: float = 1.0) -> None:
        with self._m.lock:
            self._m.series[self._labels] = (
                self._m.series.get(self._labels, 0.0) + delta
            )


class Gauge:
    def __init__(self, metric: _Metric, labels: Tuple[str, ...] = ()):
        self._m = metric
        self._labels = labels

    def with_labels(self, *label_values: str) -> "Gauge":
        return Gauge(self._m, self._m._labels_key(label_values))

    def set(self, value: float) -> None:
        with self._m.lock:
            self._m.series[self._labels] = value

    def add(self, delta: float) -> None:
        with self._m.lock:
            self._m.series[self._labels] = (
                self._m.series.get(self._labels, 0.0) + delta
            )


@dataclass
class _HistState:
    counts: List[int]
    total: int = 0
    sum: float = 0.0


#: Public name for embedders (peer/pipeline keeps per-stage histogram
#: state directly, summarized by ``summary_from_histogram_state``).
HistogramState = _HistState


def new_histogram_state(buckets: Sequence[float]) -> _HistState:
    return _HistState(counts=[0] * len(buckets))


def observe_into(
    state: _HistState, buckets: Sequence[float], value: float
) -> None:
    """The one bucket-accumulation definition (shared by ``Histogram``
    and embedded states).  NOT thread-safe; callers hold their lock."""
    idx = bisect.bisect_left(buckets, value)
    if idx < len(buckets):
        state.counts[idx] += 1
    state.total += 1
    state.sum += value


class Histogram:
    def __init__(self, metric: _Metric, labels: Tuple[str, ...] = ()):
        self._m = metric
        self._labels = labels

    def with_labels(self, *label_values: str) -> "Histogram":
        return Histogram(self._m, self._m._labels_key(label_values))

    def observe(self, value: float) -> None:
        buckets = self._m.opts.buckets  # type: ignore[attr-defined]
        with self._m.lock:
            state = self._m.series.get(self._labels)
            if state is None:
                state = new_histogram_state(buckets)
                self._m.series[self._labels] = state
            observe_into(state, buckets, value)


class Provider:
    """SPI: NewCounter/NewGauge/NewHistogram (provider.go:11-22)."""

    def new_counter(self, opts: MetricOpts) -> Counter:
        raise NotImplementedError

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        raise NotImplementedError

    def new_histogram(self, opts: HistogramOpts) -> Histogram:
        raise NotImplementedError


class PrometheusProvider(Provider):
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, opts: MetricOpts, kind: str) -> _Metric:
        name = opts.fq_name()
        if not name:
            raise ValueError("metric name is required")
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind:
                    raise ValueError(
                        f"metric {name} already registered as {existing.kind}"
                    )
                return existing
            metric = _Metric(opts, kind)
            self._metrics[name] = metric
            return metric

    def new_counter(self, opts: MetricOpts) -> Counter:
        return Counter(self._register(opts, "counter"))

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return Gauge(self._register(opts, "gauge"))

    def new_histogram(self, opts: HistogramOpts) -> Histogram:
        return Histogram(self._register(opts, "histogram"))

    def gather(self) -> str:
        """Prometheus text exposition format, sorted for determinism."""
        out: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, metric in metrics:
            if metric.opts.help:
                out.append(f"# HELP {name} {metric.opts.help}")
            out.append(f"# TYPE {name} {metric.kind}")
            with metric.lock:
                series = sorted(metric.series.items())
                for labels, value in series:
                    label_str = _format_labels(metric.opts.label_names, labels)
                    if metric.kind == "histogram":
                        assert isinstance(value, _HistState)
                        buckets = metric.opts.buckets  # type: ignore
                        cum = 0
                        for ub, c in zip(buckets, value.counts):
                            cum += c
                            le = _format_labels(
                                metric.opts.label_names + ("le",),
                                labels + (_fmt_float(ub),),
                            )
                            out.append(f"{name}_bucket{le} {cum}")
                        inf = _format_labels(
                            metric.opts.label_names + ("le",),
                            labels + ("+Inf",),
                        )
                        out.append(f"{name}_bucket{inf} {value.total}")
                        out.append(f"{name}_sum{label_str} {_fmt_float(value.sum)}")
                        out.append(f"{name}_count{label_str} {value.total}")
                    else:
                        out.append(f"{name}{label_str} {_fmt_float(value)}")
        return "\n".join(out) + ("\n" if out else "")


def _fmt_float(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(v)


def _format_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


class StatsdProvider(Provider):
    """Formats per-event statsd lines into ``sink(line)`` (reference
    common/metrics/statsd). Bucket names come from statsd_format with
    ``%{#fqname}`` and ``%{label}`` substitutions."""

    def __init__(self, sink: Callable[[str], None], prefix: str = ""):
        self._sink = sink
        self._prefix = prefix

    def _bucket(self, opts: MetricOpts, labels: Tuple[str, ...]) -> str:
        fmt = opts.statsd_format or "%{#fqname}"
        name = fmt.replace("%{#fqname}", opts.fq_name().replace("_", "."))
        for label_name, value in zip(opts.label_names, labels):
            name = name.replace("%{" + label_name + "}", value)
        return f"{self._prefix}.{name}" if self._prefix else name

    def new_counter(self, opts: MetricOpts) -> Counter:
        provider = self

        class _C(Counter):
            def __init__(self, labels: Tuple[str, ...] = ()):
                self._labels = labels

            def with_labels(self, *label_values: str) -> "Counter":
                return _C(validate_label_values(opts, label_values))

            def add(self, delta: float = 1.0) -> None:
                provider._sink(
                    f"{provider._bucket(opts, self._labels)}:{_fmt_float(delta)}|c"
                )

        return _C()

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        provider = self

        class _G(Gauge):
            def __init__(self, labels: Tuple[str, ...] = ()):
                self._labels = labels

            def with_labels(self, *label_values: str) -> "Gauge":
                return _G(validate_label_values(opts, label_values))

            def set(self, value: float) -> None:
                provider._sink(
                    f"{provider._bucket(opts, self._labels)}:{_fmt_float(value)}|g"
                )

            def add(self, delta: float) -> None:
                provider._sink(
                    f"{provider._bucket(opts, self._labels)}:{_fmt_float(delta)}|g"
                )

        return _G()

    def new_histogram(self, opts: HistogramOpts) -> Histogram:
        provider = self

        class _H(Histogram):
            def __init__(self, labels: Tuple[str, ...] = ()):
                self._labels = labels

            def with_labels(self, *label_values: str) -> "Histogram":
                return _H(validate_label_values(opts, label_values))

            def observe(self, value: float) -> None:
                provider._sink(
                    f"{provider._bucket(opts, self._labels)}:{_fmt_float(value)}|ms"
                )

        return _H()


class _DisabledCounter(Counter):
    """True no-op: ``with_labels`` returns SELF, so the labeled child is
    just as disabled as the parent.  (The old per-instance lambda patch
    only disabled the parent — ``with_labels()`` handed back a LIVE
    base-class Counter that silently recorded and accumulated series
    memory on a 'disabled' provider.)"""

    def __init__(self):  # no backing _Metric at all: nothing to leak into
        pass

    def with_labels(self, *label_values: str) -> "Counter":
        return self

    def add(self, delta: float = 1.0) -> None:
        return None


class _DisabledGauge(Gauge):
    def __init__(self):
        pass

    def with_labels(self, *label_values: str) -> "Gauge":
        return self

    def set(self, value: float) -> None:
        return None

    def add(self, delta: float) -> None:
        return None


class _DisabledHistogram(Histogram):
    def __init__(self):
        pass

    def with_labels(self, *label_values: str) -> "Histogram":
        return self

    def observe(self, value: float) -> None:
        return None


class DisabledProvider(Provider):
    def new_counter(self, opts: MetricOpts) -> Counter:
        return _DisabledCounter()

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return _DisabledGauge()

    def new_histogram(self, opts: HistogramOpts) -> Histogram:
        return _DisabledHistogram()
