"""ECDSA signature DER codec with Go encoding/asn1 parse semantics.

The reference unmarshals signatures with Go's asn1.Unmarshal into
struct{R, S *big.Int} and then requires R > 0 and S > 0
(bccsp/utils/ecdsa.go UnmarshalECDSASignature). To be bit-exact on the
accept/reject decision we replicate Go's quirks precisely:

- definite lengths only; long-form lengths must be minimal, and short
  lengths must use the short form ("non-minimal length" errors);
- INTEGER contents must be minimally encoded two's complement
  ("integer not minimally-encoded");
- negative integers parse fine at the ASN.1 layer but are rejected by the
  R.Sign()/S.Sign() checks;
- extra bytes at the end of the SEQUENCE are ALLOWED (Go tolerates them
  for compatibility with old x509 implementations);
- trailing bytes after the SEQUENCE are ignored (Unmarshal returns `rest`
  and the reference drops it).
"""

from __future__ import annotations

from typing import Tuple


class DerError(ValueError):
    """Raised when a signature fails to parse the way Go's asn1 would fail."""


def _parse_length(data: bytes, off: int) -> Tuple[int, int]:
    """Parse a BER/DER length at data[off]; returns (length, new_offset)."""
    if off >= len(data):
        raise DerError("truncated length")
    b = data[off]
    off += 1
    if b & 0x80 == 0:
        return b, off
    num = b & 0x7F
    if num == 0:
        raise DerError("indefinite length found (not DER)")
    length = 0
    for _ in range(num):
        if off >= len(data):
            raise DerError("truncated length")
        if length >= 1 << 23:
            raise DerError("length too large")
        length = (length << 8) | data[off]
        if length == 0:
            raise DerError("superfluous leading zeros in length")
        off += 1
    if length < 0x80:
        raise DerError("non-minimal length")
    return length, off


def _parse_int(data: bytes, off: int, end: int) -> Tuple[int, int]:
    """Parse one ASN.1 INTEGER element; returns (value, new_offset)."""
    if off >= end:
        raise DerError("truncated element")
    if data[off] != 0x02:  # universal, primitive, INTEGER
        raise DerError("expected INTEGER tag")
    length, off = _parse_length(data, off + 1)
    if off + length > end:
        raise DerError("integer overruns sequence")
    content = data[off : off + length]
    if len(content) == 0:
        raise DerError("empty integer")
    if len(content) > 1 and (
        (content[0] == 0x00 and content[1] & 0x80 == 0)
        or (content[0] == 0xFF and content[1] & 0x80 == 0x80)
    ):
        raise DerError("integer not minimally-encoded")
    value = int.from_bytes(content, "big", signed=True)
    return value, off + length


def unmarshal_signature(raw: bytes) -> Tuple[int, int]:
    """Parse (r, s) with reference semantics; raises DerError on reject.

    Mirrors bccsp/utils/ecdsa.go UnmarshalECDSASignature: after ASN.1
    parsing, R and S must be strictly positive.
    """
    if len(raw) == 0:
        raise DerError("empty signature")
    if raw[0] != 0x30:  # universal, constructed, SEQUENCE
        raise DerError("expected SEQUENCE tag")
    seq_len, off = _parse_length(raw, 1)
    end = off + seq_len
    if end > len(raw):
        raise DerError("sequence overruns input")
    r, off = _parse_int(raw, off, end)
    s, off = _parse_int(raw, off, end)
    # Extra bytes inside the SEQUENCE and after it are tolerated (Go quirk).
    if r <= 0:
        raise DerError("invalid signature, R must be larger than zero")
    if s <= 0:
        raise DerError("invalid signature, S must be larger than zero")
    return r, s


def _encode_int(v: int) -> bytes:
    if v == 0:
        return b"\x02\x01\x00"
    nbytes = (v.bit_length() + 8) // 8  # room for sign bit
    content = v.to_bytes(nbytes, "big")
    if len(content) > 1 and content[0] == 0 and content[1] & 0x80 == 0:
        content = content[1:]
    return b"\x02" + _encode_len(len(content)) + content


def _encode_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def marshal_signature(r: int, s: int) -> bytes:
    """DER-encode (r, s) the way Go asn1.Marshal does for positive ints."""
    if r < 0 or s < 0:
        raise ValueError("r and s must be non-negative")
    body = _encode_int(r) + _encode_int(s)
    return b"\x30" + _encode_len(len(body)) + body
