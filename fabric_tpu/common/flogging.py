"""Runtime-tunable logging registry (reference common/flogging).

The reference wraps zap with a global registry whose per-logger levels can
be mutated at runtime through a "level spec" string, served over the
operations HTTP endpoint /logspec (common/flogging/loggerlevels.go,
core/operations/system.go:149). This module provides the same contract on
top of the stdlib ``logging`` package:

* ``must_get_logger(name)`` — hierarchical loggers ("gossip.state").
* ``activate_spec(spec)`` — spec grammar matching the reference's
  ``logger1,logger2=level:logger3=level:defaultlevel``; the last bare
  level (no ``=``) sets the default; prefixes apply to whole subtrees.
* ``spec()`` — the currently-active spec string (round-trips).

Levels accepted (case-insensitive): debug, info, warn/warning, error,
panic/dpanic/fatal (mapped to CRITICAL).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "panic": logging.CRITICAL,
    "dpanic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
}
_LEVEL_NAMES = {
    logging.DEBUG: "debug",
    logging.INFO: "info",
    logging.WARNING: "warn",
    logging.ERROR: "error",
    logging.CRITICAL: "fatal",
}

ROOT = "fabric_tpu"
_lock = threading.Lock()
_default_level = logging.INFO
_overrides: Dict[str, int] = {}  # logger-name prefix -> level
_configured = False


class InvalidSpecError(ValueError):
    pass


def _ensure_handler() -> None:
    global _configured
    if _configured:
        return
    root = logging.getLogger(ROOT)
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname).4s [%(name)s] %(message)s",
                datefmt="%Y-%m-%d %H:%M:%S",
            )
        )
        root.addHandler(handler)
    root.propagate = False
    _configured = True


def _apply_locked() -> None:
    """Re-derive effective levels for every known logger under ROOT."""
    _ensure_handler()
    logging.getLogger(ROOT).setLevel(_default_level)
    # Reset previously-touched loggers to inherit, then set overrides.
    manager = logging.Logger.manager
    for name, logger in list(manager.loggerDict.items()):
        if not isinstance(logger, logging.Logger):
            continue
        if name == ROOT or not name.startswith(ROOT + "."):
            continue
        logger.setLevel(_level_for(name[len(ROOT) + 1 :]))


def _level_for(short_name: str) -> int:
    """Longest-prefix override match, else the default level."""
    best, best_len = _default_level, -1
    for prefix, level in _overrides.items():
        if short_name == prefix or short_name.startswith(prefix + "."):
            if len(prefix) > best_len:
                best, best_len = level, len(prefix)
    return best


def must_get_logger(name: str) -> logging.Logger:
    """A named logger under the fabric_tpu hierarchy, levels governed by
    the active spec."""
    with _lock:
        _ensure_handler()
        logger = logging.getLogger(f"{ROOT}.{name}")
        logger.setLevel(_level_for(name))
        return logger


def activate_spec(spec_str: str) -> None:
    """Parse and apply a level spec (common/flogging/loggerlevels.go:28).

    Grammar: colon-separated fields; ``a,b=level`` overrides loggers a,b
    (and their subtrees); a bare ``level`` field sets the default.
    """
    global _default_level
    new_default = logging.INFO
    new_overrides: Dict[str, int] = {}
    for field in spec_str.split(":"):
        field = field.strip()
        if not field:
            continue
        if "=" in field:
            names, _, level_name = field.rpartition("=")
            level = _LEVELS.get(level_name.strip().lower())
            if level is None or not names:
                raise InvalidSpecError(f"invalid logging specification: {field!r}")
            for name in names.split(","):
                name = name.strip().rstrip(".")
                if not name:
                    raise InvalidSpecError(
                        f"invalid logging specification: {field!r}"
                    )
                new_overrides[name] = level
        else:
            level = _LEVELS.get(field.lower())
            if level is None:
                raise InvalidSpecError(f"invalid logging specification: {field!r}")
            new_default = level
    with _lock:
        _default_level = new_default
        _overrides.clear()
        _overrides.update(new_overrides)
        _apply_locked()


def spec() -> str:
    """The active spec string (mirrors LoggerLevels.Spec)."""
    with _lock:
        fields = [
            f"{name}={_LEVEL_NAMES[level]}"
            for name, level in sorted(_overrides.items())
        ]
        fields.append(_LEVEL_NAMES[_default_level])
        return ":".join(fields)


def reset() -> None:
    """Test helper: back to info-everything."""
    activate_spec("info")
