"""Shared retry/backoff discipline (fabchaos hardening).

One policy object, four consumers:

- deliver failover (``deliver.client``): the reference's exponential
  backoff (base 1.2 from blocksprovider.go:109) expressed as a
  :class:`RetryPolicy` instead of inline arithmetic;
- the VerifyBatcher's dispatch path: a transient launch failure (pool
  hiccup, injected fault) retries a bounded number of times before the
  error fans out to every waiting resolver;
- the hostec/hostec_np pool degrade paths: a :class:`CooldownGate`
  keeps a freshly-broken pool from being rebuilt in a hot loop;
- the serve plane's circuits: the sidecar client's dial gate and the
  fleet router's per-endpoint health gates (``serve/router.py``) are
  both :class:`CooldownGate` instances — one blackholed endpoint costs
  one failure, then exponentially-spaced probes, never a per-batch
  connect timeout.

Determinism: jitter draws from a ``random.Random(seed)`` stream and the
deadline is accounted against *nominal* (requested) sleep time, so a
fake sleeper replays bit-identically — the fabchaos scorecard depends
on it.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import InjectedFault

#: Exception types a retry layer may treat as transient by default.
#: Deliberately narrow: a ValueError/KeyError is a bug, not weather.
TRANSIENT_ERRORS: Tuple[Type[BaseException], ...] = (
    ConnectionError,
    TimeoutError,
    OSError,
    InjectedFault,
)


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with a total-delay deadline.

    delay(n) = min(base_s * multiplier**(n-1), cap_s), n = 1, 2, ...
    jittered by ±(jitter * delay) when jitter > 0.  The sequence stops
    when ``max_attempts`` retries have been taken or when the cumulative
    nominal delay would exceed ``deadline_s`` — the deadline is a budget
    on time *spent waiting*, matching the reference deliverer's
    total-sleep accounting (blocksprovider.go:141)."""

    base_s: float = 0.05
    multiplier: float = 2.0
    cap_s: float = 10.0
    deadline_s: float = 60.0
    max_attempts: Optional[int] = None
    jitter: float = 0.0


#: The reference deliver backoff: 1.2**n * 50ms capped at 10s, one hour
#: of total sleep (deliver/client.py historical constants).
DELIVER_POLICY = RetryPolicy(
    base_s=0.06, multiplier=1.2, cap_s=10.0, deadline_s=3600.0
)

#: Bounded in-process retry for a device/pool launch: fail fast — the
#: batcher's waiting resolvers are backpressure on live traffic.
DISPATCH_POLICY = RetryPolicy(
    base_s=0.005, multiplier=4.0, cap_s=0.1, deadline_s=0.5, max_attempts=3
)


class Backoff:
    """Stateful delay sequence for one retry loop.

    ``sleep()`` takes the next delay (returns False with no sleep once
    the policy budget is exhausted); ``reset()`` re-arms after a success
    (the deliverer resets on every delivered block)."""

    def __init__(
        self,
        policy: RetryPolicy,
        seed: Optional[int] = None,
        sleeper: Callable[[float], None] = time.sleep,
    ):
        self.policy = policy
        self._sleeper = sleeper
        self._rng = random.Random(seed) if policy.jitter > 0 else None
        self.attempts = 0  # retries taken since the last reset
        self.total_delay_s = 0.0  # nominal, never reset (deadline budget)

    def next_delay(self) -> Optional[float]:
        """The delay the next sleep() would take, or None if exhausted."""
        p = self.policy
        if p.max_attempts is not None and self.attempts >= p.max_attempts:
            return None
        # exponent clamp: with an infinite deadline the attempt count is
        # unbounded and multiplier**n would overflow a float around
        # n=1024 — past ~64 the min() is decided by cap_s anyway
        delay = min(p.base_s * p.multiplier ** min(self.attempts, 64), p.cap_s)
        if self.total_delay_s + delay > p.deadline_s:
            return None
        return delay

    def sleep(self) -> bool:
        delay = self.next_delay()
        if delay is None:
            return False
        # a Backoff is confined to the one retry loop that constructed
        # it (deliverer run(), call_with_retry frame) — never shared
        self.attempts += 1  # fabdep: disable=unguarded-shared-write  # loop-scoped instance, single owner thread
        self.total_delay_s += delay  # fabdep: disable=unguarded-shared-write  # loop-scoped instance, single owner thread
        # obs: retries are where backpressure and flaps become visible;
        # the NOMINAL delay is recorded so fake sleepers chart the same
        fabobs.obs_count("fabric_retry_attempts_total")
        fabobs.obs_observe("fabric_retry_backoff_seconds", delay)
        if self._rng is not None:
            delay *= 1.0 + self.policy.jitter * (2.0 * self._rng.random() - 1.0)
        if delay > 0:
            self._sleeper(delay)
        return True

    def reset(self) -> None:
        """Success: restart the exponential ramp (the total-delay
        deadline budget intentionally keeps accruing)."""
        self.attempts = 0  # fabdep: disable=unguarded-shared-write  # loop-scoped instance, single owner thread


def call_with_retry(
    fn: Callable[[int], object],
    policy: RetryPolicy = DISPATCH_POLICY,
    retry_on: Tuple[Type[BaseException], ...] = TRANSIENT_ERRORS,
    seed: Optional[int] = None,
    sleeper: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[BaseException, int], None]] = None,
):
    """Run ``fn(attempt)`` (attempt = 0, 1, ...) until it returns,
    retrying ``retry_on`` failures per the policy.  The terminal failure
    re-raises unchanged once the budget is spent; non-transient
    exceptions propagate immediately."""
    bo = Backoff(policy, seed=seed, sleeper=sleeper)
    while True:
        attempt = bo.attempts
        try:
            return fn(attempt)
        except retry_on as exc:
            if not bo.sleep():
                raise
            if on_retry is not None:
                on_retry(exc, attempt)


class CooldownGate:
    """Failure-driven circuit for expensive rebuilds (process pools)
    and dial attempts (the serve client).

    ``ready()`` answers "may we rebuild now?"; each ``record_failure()``
    opens the gate for an exponentially longer cooldown (policy delays),
    ``record_success()`` closes it and resets the ramp.  Thread-safe on
    its own (leaf lock, acquired around state only — safe to call under
    any caller lock): gates are now shared across serve worker threads,
    not just callers that already hold a pool lock."""

    def __init__(
        self,
        policy: Optional[RetryPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy or RetryPolicy(
            base_s=0.5, multiplier=2.0, cap_s=30.0, deadline_s=float("inf")
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._open_until = 0.0

    def ready(self) -> bool:
        with self._lock:
            return self._clock() >= self._open_until

    def record_failure(self) -> None:
        p = self.policy
        with self._lock:
            # clamp: a persistently-broken environment (this gate's
            # whole reason to exist) grows _failures without bound, and
            # multiplier**1024 raises OverflowError as a float
            cooldown = min(
                p.base_s * p.multiplier ** min(self._failures, 64), p.cap_s
            )
            self._failures += 1
            self._open_until = self._clock() + cooldown

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._open_until = 0.0
