"""envreg — the central registry of every ``FABRIC_TPU_*`` environment
variable the system reads.

PRs 1–10 grew ~two dozen env knobs across the backend ladder, the
pools, the batcher, fault injection, observability and the serve plane
— each read at its consumer with a local default, none declared
anywhere a tool (or an operator) could enumerate.  This module is the
single declarative source of truth: one :class:`EnvVar` row per knob
carrying its name, value type, default, consuming module(s) and a
one-line doc.  The README env-var table is generated from
:func:`env_table`, and ``fabric_tpu.tools.fabreg`` closes the loop
statically both ways:

* ``env-undeclared`` — an ``os.environ``/``os.getenv`` read of a
  ``FABRIC_TPU_*`` name that has no row here is a gate failure, and
* ``env-dead`` — a row with no surviving reader anywhere in the tree
  (bench.py, scripts and tests count, as deprecation grace) is too.

Dependency-free by design (stdlib ``dataclasses`` only): the tools
layer AST-parses this file rather than importing it, and runtime
consumers may import it without pulling numpy/jax/cryptography.

The shared read discipline (README "Design decisions"): malformed
values warn or silently fall back to the default — an env typo must
degrade a knob, never break an import or a verify path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class EnvVar:
    """One declared environment knob.

    ``type`` is the value vocabulary (``bool`` means the consumer's
    truthy convention, usually ``"1"``; ``enum(...)`` lists the
    accepted tokens).  ``default`` is the *effective* behavior when the
    variable is unset, as a human-readable string.  ``consumer`` names
    the reading module(s) — the place to look for exact semantics."""

    name: str
    type: str
    default: str
    consumer: str
    doc: str


ENV_VARS: Tuple[EnvVar, ...] = (
    # -- backend ladder selection --------------------------------------
    EnvVar(
        "FABRIC_TPU_EC_BACKEND",
        "enum(fastec|hostec_np|hostec|p256|serve|auto)", "auto",
        "crypto/bccsp.py select_ec_backend",
        "pin the ECDSA batch-verify rung; auto walks the ladder "
        "fastec->hostec_np->hostec->p256 (unknown values warn, never "
        "raise)",
    ),
    EnvVar(
        "FABRIC_TPU_IDEMIX_BACKEND",
        "enum(hostbn|scheme|auto)", "auto",
        "crypto/bccsp.py select_idemix_backend",
        "pin the Idemix batch-verify rung; auto prefers hostbn when "
        "numpy is importable",
    ),
    EnvVar(
        "FABRIC_TPU_SERVE_ADDR", "addr", "(unset: in-process ladder)",
        "crypto/bccsp.py _default_provider_locked, serve/client.py, "
        "serve/server.py __main__",
        "resident-sidecar address (unix:/path or host:port); routes "
        "default_provider() through the warm sidecar, degrading to the "
        "in-process ladder when unreachable",
    ),
    EnvVar(
        "FABRIC_TPU_OPS_ADDR", "addr", "(unset: no ops server)",
        "serve/server.py __main__",
        "mount the operations/metrics HTTP server inside the sidecar "
        "process at this address",
    ),
    EnvVar(
        "FABRIC_TPU_SERVE_ENDPOINTS", "addr list",
        "(unset: single-sidecar or in-process)",
        "serve/router.py endpoints_from_env, crypto/bccsp.py "
        "_default_provider_locked",
        "comma-separated sidecar fleet addresses; routes "
        "default_provider() through the bucket-aware failover router "
        "(wins over FABRIC_TPU_SERVE_ADDR when both are set)",
    ),
    EnvVar(
        "FABRIC_TPU_SERVE_QOS", "map", "(unset: every channel normal)",
        "serve/qos.py qos_map_from_env (read by serve/client.py and "
        "serve/router.py)",
        "channel->admission-class map for protocol rev 2, e.g. "
        "'paychan=high;spam*=bulk;*=normal' (exact, prefix* and * "
        "patterns; malformed maps warn and resolve to the default "
        "class)",
    ),
    EnvVar(
        "FABRIC_TPU_SERVE_DRAIN_S", "float", "5",
        "serve/server.py main",
        "rolling-restart drain budget: how long SIGTERM/OP_DRAIN waits "
        "for in-flight verify requests to settle with real verdicts "
        "before the sidecar exits (malformed values fall back)",
    ),
    EnvVar(
        "FABRIC_TPU_SERVE_DEADLINE_MS", "int", "0 (no deadline)",
        "serve/client.py deadline_ms_from_env (read by SidecarProvider "
        "and serve/router.py SidecarRouter)",
        "per-batch wire latency budget (protocol rev 3): every per-hop "
        "wait — reply wait, busy-retry pacing, hedge polling — derives "
        "from the remaining budget, the server sheds provably-"
        "unfinishable work ST_BUSY, and an expired budget hands the "
        "batch to the in-process ladder (malformed values disable the "
        "knob)",
    ),
    EnvVar(
        "FABRIC_TPU_SERVE_HEDGE_FRACTION", "float", "0.05",
        "serve/router.py hedge_fraction_from_env",
        "global hedge budget: extra (hedged) requests as a fraction of "
        "primary requests, enforced by a count-based token bucket so "
        "hedging can never amplify an overloaded fleet into collapse "
        "(0 disables hedging; malformed values fall back)",
    ),
    EnvVar(
        "FABRIC_TPU_SERVE_HEDGE_MIN_MS", "float", "20",
        "serve/router.py hedge_min_ms_from_env",
        "floor on the per-endpoint hedge delay (the delay itself is "
        "2x the endpoint's observed p95, never a static knob): below "
        "this a hedge would race ordinary jitter, not a gray failure "
        "(malformed values fall back)",
    ),
    # -- device kernels -------------------------------------------------
    EnvVar(
        "FABRIC_TPU_KERNEL_VARIANT", "enum(inline|micro|microcond|auto)",
        "auto",
        "ops/p256_kernel.py _kernel_variant",
        "force the ECDSA kernel trace shape; auto picks micro off-CPU "
        "(small enough for the remote-compile service) and inline on "
        "CPU",
    ),
    EnvVar(
        "FABRIC_TPU_CIOS_UNROLL", "enum(0|1)", "(auto: unrolled off-CPU)",
        "ops/bignum.py _unroll_cios (bench.py and tests/conftest.py pin "
        "it)",
        "force the CIOS Montgomery multiply trace shape: 1 = 20 "
        "unrolled iterations (fastest at runtime), 0 = lax.fori_loop "
        "(10x faster to compile on CPU)",
    ),
    # -- host crypto pools ----------------------------------------------
    EnvVar(
        "FABRIC_TPU_HOSTEC_PROCS", "int", "min(cpu_count, cap)",
        "crypto/hostec.py pool_procs",
        "hostec process-pool worker count (1 disables the pool); "
        "malformed values fall back to the default",
    ),
    EnvVar(
        "FABRIC_TPU_HOSTEC_NP_PROCS", "int",
        "(falls back to FABRIC_TPU_HOSTEC_PROCS)",
        "crypto/hostec_np.py pool_procs",
        "hostec_np (numpy limb-matrix engine) pool worker count",
    ),
    EnvVar(
        "FABRIC_TPU_HOSTEC_NP_MIN_LANES", "int", "1024",
        "crypto/hostec_np.py verify_parsed_batch_sharded",
        "batches below this lane count delegate down-ladder to hostec's "
        "list engine (the matrix engine's fixed costs amortize above "
        "~1k lanes)",
    ),
    EnvVar(
        "FABRIC_TPU_HOSTEC_START", "enum(forkserver|spawn)", "forkserver",
        "crypto/hostec.py, crypto/hostec_np.py, idemix/batch.py",
        "multiprocessing start method for the crypto pools (fork is "
        "forbidden: live gRPC/XLA threads wedge forked workers)",
    ),
    EnvVar(
        "FABRIC_TPU_HOSTBN_PROCS", "int", "min(cpu_count, cap)",
        "idemix/batch.py pool_procs",
        "hostbn pairing-engine pool worker count (1 disables the pool)",
    ),
    EnvVar(
        "FABRIC_TPU_HOSTBN_MIN_POOL", "int", "64",
        "idemix/batch.py _verify_batch_hostbn",
        "Idemix batches below this size verify inline instead of "
        "round-tripping the process pool",
    ),
    EnvVar(
        "FABRIC_TPU_HOSTBN_MIN_SHARD", "int", "16",
        "idemix/batch.py _shard_plan",
        "never split a pooled Idemix batch into shards smaller than "
        "this",
    ),
    # -- batcher / dispatch ----------------------------------------------
    EnvVar(
        "FABRIC_TPU_BATCHER_MODE", "enum(auto|coalesce|passthrough)",
        "auto",
        "parallel/batcher.py VerifyBatcher",
        "force the transport mode; auto coalesces when the observed "
        "device RTT makes batching pay",
    ),
    EnvVar(
        "FABRIC_TPU_BATCHER_RTT_MS", "float", "25",
        "parallel/batcher.py VerifyBatcher",
        "assumed device round-trip ms before the EWMA has samples "
        "(auto-mode threshold seed)",
    ),
    EnvVar(
        "FABRIC_TPU_DISPATCH_RETRIES", "int", "3",
        "crypto/tpu_provider.py dispatch",
        "bounded retry attempts for a transient device-dispatch "
        "failure before degrading to the host ladder",
    ),
    # -- device probe -----------------------------------------------------
    EnvVar(
        "FABRIC_TPU_PROBE_TIMEOUT_S", "float", "60",
        "utils/deviceprobe.py probe_timeout_s",
        "hard wall-clock cap on the subprocess device probe (a hung "
        "PJRT plugin is killed, not waited on)",
    ),
    # -- fault injection (fabchaos) ---------------------------------------
    EnvVar(
        "FABRIC_TPU_FAULTS", "plan", "(unset: injection disabled)",
        "common/faults.py plan_from_env",
        "fault-injection plan: site=action[:prob][:param=int] entries "
        "joined by ';' (actions raise|delay|corrupt|drop); malformed "
        "plans warn and install nothing",
    ),
    EnvVar(
        "FABRIC_TPU_FAULTS_SEED", "int", "0",
        "common/faults.py plan_from_env",
        "seed for the deterministic per-site fault decision streams "
        "(same seed = same injections, regardless of thread "
        "interleaving)",
    ),
    EnvVar(
        "FABRIC_TPU_CRASH_SITES", "site[@block] list",
        "(unset: no kill points)",
        "common/faults.py _install_from_env",
        "fabcrash kill-point selector: 'site[@block]' entries joined by "
        "';' — sugar for site=kill:max=1[:at=block] fault specs; the "
        "process os._exit(137)s at the armed seam (the crash matrix's "
        "deterministic SIGKILL stand-in); malformed values warn and "
        "install nothing",
    ),
    EnvVar(
        "FABRIC_TPU_RECOVERY_STRICT", "enum(0|1)", "1",
        "ledger/blockstore.py recovery_strict (read by ledger/"
        "pvtdatastore.py and ledger/kvledger.py)",
        "crash-recovery strictness: 1 (default) refuses to open a store "
        "whose damage one interrupted append cannot explain (fail "
        "closed, loud log + refusal counter); 0 is operator-forced "
        "salvage — truncate to the last whole record / rebuild derived "
        "state from the chain, for forensics and manual repair",
    ),
    # -- observability (fabobs) -------------------------------------------
    EnvVar(
        "FABRIC_TPU_OBS", "bool", "(unset: disabled)",
        "common/fabobs.py _install_from_env",
        "enable the process-wide observability registry at import "
        "(PrometheusProvider + span flight ring); malformed values "
        "warn and install nothing",
    ),
    EnvVar(
        "FABRIC_TPU_OBS_RING", "int", "4096",
        "common/fabobs.py _install_from_env",
        "flight-recorder ring size (spans kept for /trace and trigger "
        "dumps)",
    ),
    EnvVar(
        "FABRIC_TPU_OBS_DUMP_DIR", "path", "(unset: no auto dumps)",
        "common/fabobs.py _install_from_env",
        "directory for automatic Chrome-trace dumps on degrade/"
        "fail-closed triggers (capped per process)",
    ),
    # -- test/bench harness knobs -----------------------------------------
    EnvVar(
        "FABRIC_TPU_CACHE_DEBUG", "enum(0|1)", "0",
        "tests/conftest.py",
        "log every XLA persistent-compilation-cache hit/miss/write "
        "with its key (the PR 8 tier-1 budget forensics switch)",
    ),
    EnvVar(
        "FABRIC_TPU_PAIRING_TESTS", "enum(0|1)", "(unset: tier-1 set)",
        "tests/test_pairing_kernel.py",
        "0 skips the pairing kernel tests entirely; 1 additionally "
        "enables the two deep-debug differentials (per-step Miller "
        "values, idemix batch e2e)",
    ),
)

ENV_BY_NAME: Dict[str, EnvVar] = {v.name: v for v in ENV_VARS}


def env_table() -> List[Dict[str, str]]:
    """The registry as rows (README table generation + gates), the
    same shape discipline as ``fabobs.metric_table``."""
    return [
        {
            "name": v.name,
            "type": v.type,
            "default": v.default,
            "consumer": v.consumer,
            "doc": v.doc,
        }
        for v in ENV_VARS
    ]
