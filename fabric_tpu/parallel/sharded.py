"""The batched ECDSA-P256 verify kernel, jitted over a device mesh.

Two entry points:

- `verify_flat`: one channel's (tx x sig) batch, lanes sharded over the
  mesh's "data" axis. The output mask is replicated, so XLA inserts the
  all-gather of per-shard masks over ICI (SURVEY.md §2.13 P6).
- `verify_channels`: a (channel, lane) stack — the kernel vmapped over a
  leading channel axis, channels sharded over "channel" and lanes over
  "data" (SURVEY.md §2.13 P3; reference channel objects are fully
  independent, core/peer/peer.go:337-408, so a pure batch dim is the
  exact semantic match).

Shapes must divide the mesh: lanes % data-axis == 0 and channels %
channel-axis == 0 (use `pad_lanes` / callers' bucket padding).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from fabric_tpu.parallel.mesh import CHANNEL_AXIS, DATA_AXIS


def pad_lanes(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


class ShardedVerify:
    """Holds the per-mesh jitted programs (one compile per shape, persisted
    in the XLA compilation cache)."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._flat = None
        self._channels = None

    # ------------------------------------------------------------------
    @property
    def data_size(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def channel_size(self) -> int:
        return self.mesh.shape.get(CHANNEL_AXIS, 1)

    # ------------------------------------------------------------------
    def _sharding(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(*spec))

    def _build_flat(self):
        import jax

        from fabric_tpu.ops.p256_kernel import verify_batch_device

        limb = self._sharding(None, DATA_AXIS)  # (20, B)
        mask = self._sharding(DATA_AXIS)  # (B,)
        replicated = self._sharding()
        return jax.jit(
            verify_batch_device,
            in_shardings=(limb,) * 5 + (mask,),
            out_shardings=replicated,  # all-gather of per-shard masks (P6)
        )

    def _build_channels(self):
        import jax

        from fabric_tpu.ops.p256_kernel import verify_batch_device

        if CHANNEL_AXIS in self.mesh.shape:
            limb = self._sharding(CHANNEL_AXIS, None, DATA_AXIS)  # (C, 20, B)
            mask = self._sharding(CHANNEL_AXIS, DATA_AXIS)  # (C, B)
        else:
            limb = self._sharding(None, None, DATA_AXIS)
            mask = self._sharding(None, DATA_AXIS)
        return jax.jit(
            jax.vmap(verify_batch_device),
            in_shardings=(limb,) * 5 + (mask,),
            out_shardings=mask,
        )

    # ------------------------------------------------------------------
    def verify_flat(
        self,
        e: np.ndarray,
        r: np.ndarray,
        s: np.ndarray,
        qx: np.ndarray,
        qy: np.ndarray,
        ok: np.ndarray,
    ) -> np.ndarray:
        """(20, B) limb arrays + (B,) mask -> (B,) bool, B % data == 0."""
        if e.shape[1] % self.data_size:
            raise ValueError(
                f"lane count {e.shape[1]} not divisible by data axis {self.data_size}"
            )
        if self._flat is None:
            self._flat = self._build_flat()
        with self.mesh:
            return np.asarray(self._flat(e, r, s, qx, qy, ok))

    def verify_channels(
        self,
        e: np.ndarray,
        r: np.ndarray,
        s: np.ndarray,
        qx: np.ndarray,
        qy: np.ndarray,
        ok: np.ndarray,
    ) -> np.ndarray:
        """(C, 20, B) limb stacks + (C, B) mask -> (C, B) bool."""
        c, _, b = e.shape
        if b % self.data_size or c % self.channel_size:
            raise ValueError(
                f"stack ({c}, {b}) not divisible by mesh "
                f"({self.channel_size}, {self.data_size})"
            )
        if self._channels is None:
            self._channels = self._build_channels()
        with self.mesh:
            return np.asarray(self._channels(e, r, s, qx, qy, ok))


def channel_stack(
    batches: Tuple[Tuple[np.ndarray, ...], ...],
    lanes: int,
    channels: int,
) -> Tuple[np.ndarray, ...]:
    """Pad each channel's (e, r, s, qx, qy, ok) arrays to `lanes` lanes,
    stack to (channels, ...) with dead (ok=False) rows for missing
    channels."""
    import fabric_tpu.ops.bignum as bn

    n_real = len(batches)
    out_limbs = [
        np.zeros((channels, bn.NLIMBS, lanes), dtype=np.uint32) for _ in range(5)
    ]
    out_ok = np.zeros((channels, lanes), dtype=bool)
    for c, batch in enumerate(batches):
        *limb_arrays, ok = batch
        n = ok.shape[0]
        for dst, src in zip(out_limbs, limb_arrays):
            dst[c, :, :n] = src
        out_ok[c, :n] = ok
    assert n_real <= channels
    return (*out_limbs, out_ok)
