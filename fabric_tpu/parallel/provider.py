"""Mesh-aware BCCSP provider: one channel's (tx x sig) batch spread over
every device on the mesh's "data" axis (SURVEY.md §2.13 P2 -> P6)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from fabric_tpu.crypto.tpu_provider import TPUProvider, _bucket
from fabric_tpu.parallel.sharded import ShardedVerify, pad_lanes


class MeshTPUProvider(TPUProvider):
    """TPUProvider whose device batches run sharded over a mesh.

    Occupies the same bccsp-factory slot as TPUProvider; buckets are
    additionally aligned to the data-axis size so every shard gets equal
    fixed-shape work.
    """

    def __init__(self, mesh=None):
        super().__init__()
        if mesh is None:
            from fabric_tpu.parallel.mesh import flat_mesh

            mesh = flat_mesh()
        self.sharded = ShardedVerify(mesh)

    def _run_kernel(self, limbs: Sequence[np.ndarray]) -> List[bool]:
        n = limbs[-1].shape[0]
        size = pad_lanes(_bucket(n), self.sharded.data_size)
        out = self.sharded.verify_flat(*self.pad_limbs(limbs, size))
        return list(out[:n])
