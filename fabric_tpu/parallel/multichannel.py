"""Multi-channel validation in one device step (SURVEY.md §2.13 P3;
BASELINE config #5: 4 channels x 2k-tx blocks sharded over the mesh).

The reference validates channels in fully independent per-channel
Channel objects (core/peer/peer.go:337-408) — process-level parallelism.
The TPU-native form: collect one block per channel, host-parse each,
flatten every channel's signature jobs to fixed-shape lanes, stack on a
leading channel axis, and run ONE sharded program; per-channel masks
come back in a single device step, then each channel finishes its
host-side phases (principal matching, policy circuits, dup-TxID) exactly
as in the single-channel path.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from fabric_tpu.crypto.tpu_provider import TPUProvider, _bucket
from fabric_tpu.parallel.sharded import ShardedVerify, channel_stack, pad_lanes
from fabric_tpu.protos import common_pb2
from fabric_tpu.validation.blockparse import parse_block
from fabric_tpu.common.txflags import ValidationFlags
from fabric_tpu.validation.validator import BlockValidator


class MultiChannelValidator:
    """Validates one block per channel in a single sharded device batch."""

    def __init__(self, mesh, validators: Dict[str, BlockValidator]):
        self.validators = dict(validators)
        self.sharded = ShardedVerify(mesh)
        # host prep (DER parse, key-limb cache) shared across channels
        self._prep = TPUProvider()
        # device-busy wall time of the last validate() call's sharded
        # step (launch -> masks materialized), for duty-cycle reporting
        self.last_device_ms = 0.0

    def validate(
        self, blocks: Dict[str, common_pb2.Block]
    ) -> Dict[str, ValidationFlags]:
        channels = sorted(blocks)
        unknown = [c for c in channels if c not in self.validators]
        if unknown:
            raise KeyError(f"no validator for channels {unknown}")

        # phase 1+2 host prep per channel
        per_channel = {}
        lanes = 0
        for ch in channels:
            validator = self.validators[ch]
            block = blocks[ch]
            parsed = parse_block(list(block.data.data))
            jobs, job_identity, keys, sigs, digests = (
                validator.collect_sig_jobs(parsed)
            )
            limbs = self._prep.prep_limbs(keys, sigs, digests)
            per_channel[ch] = (validator, block, parsed, jobs, job_identity, limbs)
            lanes = max(lanes, limbs[-1].shape[0])

        # one fixed-shape device step for every channel
        lanes = pad_lanes(_bucket(max(lanes, 1)), self.sharded.data_size)
        n_channels = pad_lanes(len(channels), self.sharded.channel_size)
        stacked = channel_stack(
            tuple(per_channel[ch][5] for ch in channels), lanes, n_channels
        )
        import time as _time

        t_dev = _time.perf_counter()
        masks = np.asarray(self.sharded.verify_channels(*stacked))
        self.last_device_ms = (_time.perf_counter() - t_dev) * 1000.0

        # per-channel host epilogue
        out: Dict[str, ValidationFlags] = {}
        for c, ch in enumerate(channels):
            validator, block, parsed, jobs, job_identity, limbs = per_channel[ch]
            n = limbs[-1].shape[0]
            # masks is already a host ndarray (materialized once above)
            ok_list = [bool(v) for v in masks[c, :n]]
            sig_results = validator.finish_sig_results(
                jobs, job_identity, ok_list
            )
            out[ch] = validator.validate(block, parsed, sig_results=sig_results)
        return out
