"""Device-mesh construction for sharded validation.

Axis names mirror the two parallelism axes the reference exposes
(SURVEY.md §2.13): "data" = the flattened (tx x sig) lane dimension
(reference P1/P2, goroutine-per-tx + per-endorsement verify loops), and
"channel" = fully independent per-channel validators (reference P3,
core/peer/peer.go:337-408).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

DATA_AXIS = "data"
CHANNEL_AXIS = "channel"


def _device_pool(devices):
    import jax

    return list(devices) if devices is not None else jax.devices()


def flat_mesh(devices: Optional[Sequence] = None):
    """One-dimensional mesh: every device on the "data" axis."""
    from jax.sharding import Mesh

    pool = _device_pool(devices)
    return Mesh(np.array(pool), axis_names=(DATA_AXIS,))


def grid_mesh(
    channel: int,
    data: Optional[int] = None,
    devices: Optional[Sequence] = None,
):
    """Two-dimensional (channel, data) mesh.

    `channel` groups of `data` devices each; defaults to using the whole
    pool (data = n // channel).
    """
    from jax.sharding import Mesh

    pool = _device_pool(devices)
    if data is None:
        if len(pool) % channel:
            raise ValueError(
                f"{len(pool)} devices not divisible into {channel} channel groups"
            )
        data = len(pool) // channel
    if channel * data > len(pool):
        raise ValueError(
            f"mesh {channel}x{data} needs {channel * data} devices, have {len(pool)}"
        )
    arr = np.array(pool[: channel * data]).reshape(channel, data)
    return Mesh(arr, axis_names=(CHANNEL_AXIS, DATA_AXIS))
