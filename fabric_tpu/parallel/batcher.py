"""Cross-channel verify coalescing with bounded-queue backpressure
(SURVEY §2.13 P7).

The device wants few, large, fixed-shape launches; the peer produces
many small, bursty verify requests (one per block, per channel, plus
endorsement-path singles).  This batcher sits between them:

- requests enqueue onto ONE bounded queue (backpressure: submitters
  block when the device is behind — the reference achieves the same
  with its validator semaphore, core/ledger/kvledger/kv_ledger.go
  commit throttling);
- a dispatcher thread drains the queue into bucketed batches: it takes
  whatever is queued, lingers a few ms for stragglers while the bucket
  is small, then launches ONE device program for the whole batch via
  the provider's async path (overlapping host prep of the next batch
  with device execution of the current one, like the P4 pipeline);
- each request gets a resolver (future) for exactly its lanes.

Coalescing across channels keeps lanes/launch high even when individual
blocks are small — the multi-channel aggregate (BASELINE config #5)
benefits most.

Transport-regime auto-detection (round-5, from round-3 measurements):
coalescing WINS when launches are compute-bound (attached chip, ~1.1x)
and LOSES when a fixed per-launch RTT dominates (the TPU tunnel:
0.45-0.87x — serializing small requests behind one queue costs more
than the lane-count gain). The batcher therefore measures the RTT of
its own small launches (dispatch -> verdicts, lanes <= RTT_PROBE_LANES
so device compute is negligible) and switches itself between:

- "coalesce": linger + merge (low-RTT regime);
- "passthrough": every request launches immediately as its own async
  program, overlapping in flight exactly like independent callers —
  while the bounded-lane admission (the P7 backpressure contract)
  stays in force in both modes.

FABRIC_TPU_BATCHER_MODE=coalesce|passthrough|auto (default auto)
forces a mode; FABRIC_TPU_BATCHER_RTT_MS (default 25) is the auto
threshold, chosen between attached-chip RTTs (<10ms) and tunnel RTTs
(100-300ms) with hysteresis against flapping.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.retry import DISPATCH_POLICY, RetryPolicy, call_with_retry

logger = must_get_logger("batcher")


class _Request:
    __slots__ = (
        "keys", "sigs", "digests", "event", "result", "error", "permits",
        "t_submit", "on_dispatch", "deadline_s",
    )

    def __init__(self, keys, sigs, digests, on_dispatch=None,
                 deadline_s=None):
        self.keys = keys
        self.sigs = sigs
        self.digests = digests
        self.event = threading.Event()
        self.result: Optional[List[bool]] = None
        self.error: Optional[BaseException] = None
        self.permits = 0
        self.t_submit = time.perf_counter()
        # fired exactly when this request's lane permits are released
        # (dispatcher pickup) — the serve sidecar's per-class QoS
        # ledger mirrors the batcher's admission window through it
        self.on_dispatch = on_dispatch
        # wire-deadline discipline (serve protocol rev 3): the absolute
        # time.monotonic() moment this request's budget expires, or
        # None.  The dispatcher caps its coalescing linger by the
        # TIGHTEST deadline in the batch — lanes with a live budget are
        # launched, never lingered past it.
        self.deadline_s = deadline_s

    def resolve(self) -> List[bool]:
        self.event.wait()  # fablife: disable=blocking-unbudgeted  # bounded by the batcher lifetime, not a wire budget: stop() settles every admitted request fail-closed (event.set), so this wait can never outlive the batcher; wire deadlines cap it upstream via deadline_s
        if self.error is not None:
            raise self.error
        assert self.result is not None
        return self.result

    def fail_closed(self) -> None:
        """Settle with all-False verdicts — a stopped/hung batcher must
        never leave resolve() blocked and must never guess True.  A race
        with a real settlement is benign: whichever lands first wins and
        both outcomes are fail-closed (real verdicts or all-False)."""
        if not self.event.is_set():
            self.result = [False] * len(self.keys)  # fabdep: disable=unguarded-shared-write  # documented benign race: both settlements are fail-closed, event.set publishes
            self.event.set()


class VerifyBatcher:
    """submit() returns a resolver; call it to block for the verdicts of
    exactly the submitted lanes."""

    def __init__(
        self,
        provider,
        max_batch: int = 16384,
        linger_s: float = 0.002,
        max_pending_lanes: int = 65536,
        dispatch_retry: Optional[RetryPolicy] = None,
        join_timeout_s: float = 10.0,
    ):
        self.provider = provider
        self.max_batch = max_batch
        self.linger_s = linger_s
        # stop()'s patience for the dispatcher thread before settling
        # stragglers fail-closed (shorten in tests with hung resolvers)
        self.join_timeout_s = join_timeout_s
        # bounded transient retry for a failed launch (pool hiccup,
        # injected fault) before the error fans out to every resolver
        self.dispatch_retry = dispatch_retry or DISPATCH_POLICY
        self._q: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self._stop_lock = threading.Lock()
        # every admitted-but-unsettled request, so stop() can settle
        # stragglers fail-closed; guarded by its own lock (stop() holds
        # _stop_lock around the sentinel put — reusing it here would
        # deadlock the dispatcher's settle path against stop's join)
        self._req_lock = threading.Lock()
        self._inflight: set = set()
        self._max_pending_lanes = max_pending_lanes
        # all-or-nothing admission under one condition variable: a
        # per-lane semaphore loop would let two concurrent large submits
        # each grab a partial allocation and deadlock
        self._lanes_cv = threading.Condition()
        self._lanes_free = max_pending_lanes
        self._stopped = False
        self.launches = 0  # introspection: device programs dispatched
        self.lanes = 0  # total lanes verified
        # transport-regime detection (see module docstring)
        self._forced_mode = os.environ.get("FABRIC_TPU_BATCHER_MODE", "auto")
        self._rtt_threshold_ms = float(
            os.environ.get("FABRIC_TPU_BATCHER_RTT_MS", "25")
        )
        self.rtt_ema_ms: Optional[float] = None
        # today _observe_rtt runs only on the dispatcher thread (every
        # _settle call site is inside _run); the lock pins the EWMA
        # read-modify-write as the invariant rather than an accident of
        # the current call graph, so a future settle-from-elsewhere
        # cannot silently introduce the race
        self._rtt_lock = threading.Lock()
        # probe only launches small enough that device compute is
        # negligible next to transport RTT even on an attached chip
        # (64 lanes at ~65k verifies/s is ~1ms of compute; a 2048-lane
        # coalesced launch is ~30ms of COMPUTE and would mis-flip a
        # low-RTT chip into passthrough)
        self.RTT_PROBE_LANES = 64
        self._thread = threading.Thread(
            target=self._run, name="verify-batcher", daemon=True
        )
        self._thread.start()

    @property
    def mode(self) -> str:
        if self._forced_mode in ("coalesce", "passthrough"):
            return self._forced_mode
        if self.rtt_ema_ms is None:
            return "coalesce"  # no signal yet: original default
        # hysteresis band around the threshold stops mode flapping
        if self.rtt_ema_ms > self._rtt_threshold_ms * 1.2:
            return "passthrough"
        if self.rtt_ema_ms < self._rtt_threshold_ms * 0.8:
            return "coalesce"
        return self._last_mode

    _last_mode = "coalesce"

    def _observe_rtt(self, lanes: int, elapsed_s: float) -> None:
        if lanes > self.RTT_PROBE_LANES:
            return
        ms = elapsed_s * 1000.0
        with self._rtt_lock:
            self.rtt_ema_ms = (
                ms
                if self.rtt_ema_ms is None
                else 0.8 * self.rtt_ema_ms + 0.2 * ms
            )
            self._last_mode = self.mode

    @property
    def pending_lanes(self) -> int:
        """Lanes currently admitted but not yet dispatched — the
        admission-control fill signal the serve sidecar scales its
        retry_after hint by."""
        with self._lanes_cv:
            return self._max_pending_lanes - self._lanes_free

    def submit(
        self,
        keys: Sequence,
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> Callable[[], List[bool]]:
        resolver = self._admit(keys, signatures, digests, block=True)
        assert resolver is not None  # blocking admission never rejects
        return resolver

    def try_submit(
        self,
        keys: Sequence,
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
        on_dispatch: Optional[Callable[[], None]] = None,
        deadline_s: Optional[float] = None,
    ) -> Optional[Callable[[], List[bool]]]:
        """Non-blocking admission (the serve sidecar's front door): the
        resolver when the lane budget admits the request NOW, else None
        — the caller turns that into an explicit reject-with-retry-after
        instead of stalling a socket thread on the condition variable.
        ``on_dispatch`` fires when the dispatcher picks the request up
        (the moment its lane permits are released) — callers keeping a
        parallel admission ledger release theirs in the same window.
        ``deadline_s`` (absolute ``time.monotonic()``) caps how long the
        dispatcher may linger this request for coalescing company."""
        return self._admit(
            keys, signatures, digests, block=False, on_dispatch=on_dispatch,
            deadline_s=deadline_s,
        )

    def _admit(
        self,
        keys: Sequence,
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
        block: bool,
        on_dispatch: Optional[Callable[[], None]] = None,
        deadline_s: Optional[float] = None,
    ) -> Optional[Callable[[], List[bool]]]:
        n = len(keys)
        if n == 0:
            return list
        # chaos seam: an injected submit fault fails the CALLER before
        # any batcher state is touched (no lanes to leak); unkeyed — a
        # per-site seeded stream, not all-or-nothing per request size
        fault_point("batcher.submit")
        # bounded admission: lanes are taken atomically (all or nothing)
        # and released at dispatch. An oversized request is capped so it
        # can't demand more lanes than exist.
        req = _Request(
            list(keys), list(signatures), list(digests),
            on_dispatch=on_dispatch, deadline_s=deadline_s,
        )
        req.permits = min(n, self._max_pending_lanes)
        with self._lanes_cv:
            while self._lanes_free < req.permits:
                # stop() notifies this cv: an admission-blocked submitter
                # must not wait forever on permits a wedged dispatcher
                # will never release
                if self._stopped:
                    raise RuntimeError("batcher stopped")
                if not block:
                    fabobs.obs_count("fabric_batcher_busy_rejects_total")
                    return None
                self._lanes_cv.wait()  # fablife: disable=blocking-unbudgeted  # released by dispatch (lane permits freed) and by stop(), which sets _stopped and notify_all()s this cv — the loop re-checks _stopped every wake, so the wait is bounded by batcher teardown
            self._lanes_free -= req.permits
            pending = self._max_pending_lanes - self._lanes_free
        fabobs.obs_gauge("fabric_batcher_pending_lanes", pending)
        # the stop lock orders every put against the stop sentinel: no
        # request can land behind the None the dispatcher exits on
        with self._stop_lock:
            if self._stopped:
                with self._lanes_cv:
                    self._lanes_free += req.permits
                    self._lanes_cv.notify_all()
                raise RuntimeError("batcher stopped")
            with self._req_lock:
                self._inflight.add(req)
            self._q.put(req)
        return req.resolve

    def verify_batch(self, keys, signatures, digests) -> List[bool]:
        return self.submit(keys, signatures, digests)()

    # -- dispatcher ------------------------------------------------------
    def _take_batch(self) -> Optional[List[_Request]]:
        first = self._q.get()  # fablife: disable=blocking-unbudgeted  # the dispatcher's idle park, not a request hop: stop() posts the None sentinel this get() returns on, after settling in-flight work fail-closed
        if first is None:
            return None
        batch = [first]
        lanes = len(first.keys)
        if self.mode == "passthrough":
            # high-RTT regime: dispatch immediately, one launch per
            # request, overlapping in flight (admission control already
            # happened at submit)
            return batch
        waiter = (
            threading.Event()
        )  # fresh event as a precise, interruptible sleep
        while lanes < self.max_batch:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                if lanes >= self.max_batch // 2:
                    break  # big enough: don't trade latency for lanes
                # the linger window respects the TIGHTEST wire deadline
                # in the batch: a budgeted request is dispatched, never
                # lingered past the moment its client walks away
                linger = self.linger_s
                tightest = min(
                    (r.deadline_s for r in batch
                     if r.deadline_s is not None),
                    default=None,
                )
                if tightest is not None:
                    linger = min(linger, tightest - time.monotonic())
                if linger > 0:
                    waiter.wait(linger)
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
            if nxt is None:
                self._q.put(None)  # re-post the stop token
                break
            batch.append(nxt)
            lanes += len(nxt.keys)
        return batch

    def _run(self) -> None:
        # entries: (requests, resolver, dispatch_time, lanes)
        pending: List[Tuple] = []
        while True:
            batch = self._take_batch()
            if batch is None:
                for entry in pending:
                    self._settle(*entry)
                return
            keys: List = []
            sigs: List[bytes] = []
            digests: List[bytes] = []
            for r in batch:
                keys.extend(r.keys)
                sigs.extend(r.sigs)
                digests.extend(r.digests)
            with self._lanes_cv:
                self._lanes_free += sum(r.permits for r in batch)
                self._lanes_cv.notify_all()
                released = self._max_pending_lanes - self._lanes_free
            fabobs.obs_gauge("fabric_batcher_pending_lanes", released)
            for r in batch:
                if r.on_dispatch is not None:
                    try:
                        r.on_dispatch()
                    except Exception as exc:  # noqa: BLE001 - a ledger hook must never kill the dispatcher
                        logger.warning("on_dispatch hook failed: %s", exc)
            try:
                with fabobs.span(
                    "batcher.launch", lanes=len(keys), requests=len(batch)
                ):
                    resolver = self._launch(keys, sigs, digests)
            except BaseException as exc:  # fablint: disable=broad-except  # error propagated to every waiting caller via r.error
                for r in batch:
                    self._settle_error(r, exc)
                if self._q.empty():
                    # mirror the success path's idle drain: without it,
                    # earlier launches still in `pending` would strand
                    # their resolvers behind the blocking q.get() until
                    # unrelated traffic (or stop) arrived
                    while pending:
                        self._settle(*pending.pop(0))
                continue
            self.launches += 1
            self.lanes += len(keys)
            fabobs.obs_count("fabric_batcher_launches_total", mode=self.mode)
            fabobs.obs_observe("fabric_batcher_batch_lanes", len(keys))
            pending.append((batch, resolver, time.perf_counter(), len(keys)))
            # depth-4 pipeline: keep up to three launches in flight before
            # settling the oldest — on high-RTT transports (the TPU
            # tunnel) serializing launches costs more than coalescing
            # saves, so small batches overlap like independent callers
            # would while large ones still coalesce
            while len(pending) > 3:
                self._settle(*pending.pop(0))
            if self._q.empty():
                # idle: drain so callers aren't left waiting on us
                while pending:
                    self._settle(*pending.pop(0))

    def _launch(self, keys: List, sigs: List[bytes], digests: List[bytes]):
        """One device/provider launch with bounded transient retry: a
        flapping backend (pool hiccup, injected fault) gets
        dispatch_retry's capped-backoff attempts before the failure fans
        out to every waiting resolver.  The fault site is unkeyed: the
        per-site seeded stream re-rolls the decision on every attempt,
        so a probabilistic plan models a flap the retry can ride out
        (a batch-content key would re-fire identically per attempt)."""
        dispatch = getattr(self.provider, "batch_verify_async", None)

        def attempt(n: int):
            fault_point("batcher.dispatch")
            if dispatch is None:
                # provider without an async seam: compute now, hand back
                # a trivial resolver (SoftwareProvider HAS
                # batch_verify_async — on the hostec_np/hostec tiers it
                # shards across the process pool and resolves later)
                verdicts = self.provider.batch_verify(keys, sigs, digests)
                return lambda v=verdicts: v
            return dispatch(keys, sigs, digests)

        def on_retry(exc: BaseException, attempt_n: int) -> None:
            fabobs.obs_count("fabric_batcher_dispatch_retries_total")
            fabobs.obs_event(
                "batcher.dispatch_retry",
                attempt=attempt_n, error=type(exc).__name__,
            )

        return call_with_retry(
            attempt, policy=self.dispatch_retry, on_retry=on_retry
        )

    def _settle_error(self, r: _Request, exc: BaseException) -> None:
        if not r.event.is_set():
            r.error = exc
            r.event.set()
        with self._req_lock:
            self._inflight.discard(r)

    def _settle(
        self,
        reqs: List[_Request],
        resolver: Callable,
        t0: float = 0.0,
        lanes: int = 0,
    ) -> None:
        try:
            with fabobs.span("batcher.settle", lanes=lanes):
                out = list(resolver())
            if t0:
                self._observe_rtt(lanes, time.perf_counter() - t0)
        except BaseException as exc:  # fablint: disable=broad-except  # error propagated to every waiting caller via r.error
            for r in reqs:
                self._settle_error(r, exc)
            return
        now = time.perf_counter()
        off = 0
        for r in reqs:
            n = len(r.keys)
            if not r.event.is_set():  # stop() may have settled fail-closed
                r.result = out[off : off + n]
                r.event.set()
                fabobs.obs_observe(
                    "fabric_batcher_submit_wait_seconds", now - r.t_submit
                )
            off += n
            with self._req_lock:
                self._inflight.discard(r)

    def stop(self) -> None:
        """Idempotent shutdown.  After the dispatcher exits (or the join
        times out on a hung resolver), every still-unsettled request is
        settled fail-closed (all-False verdicts) so no resolve() caller
        blocks forever and no lane is ever guessed VALID."""
        with self._stop_lock:
            first = not self._stopped
            self._stopped = True
            if first:
                self._q.put(None)
        # wake submitters blocked on lane admission so they observe the
        # stop instead of waiting for permits that will never come back
        with self._lanes_cv:
            self._lanes_cv.notify_all()
        self._thread.join(timeout=self.join_timeout_s)
        with self._req_lock:
            leftovers = list(self._inflight)
            self._inflight.clear()
        for r in leftovers:
            r.fail_closed()
        if leftovers:
            # a fail-closed settlement is exactly the moment worth a
            # flight-recorder snapshot: what led up to the hang is in
            # the ring right now
            fabobs.obs_count(
                "fabric_batcher_fail_closed_total", len(leftovers)
            )
            fabobs.obs_trigger(
                "batcher.fail_closed", requests=len(leftovers)
            )


class BatchingProvider:
    """BCCSP-provider adapter over a shared VerifyBatcher: every channel
    validator on the node funnels its batch_verify through ONE batcher
    (and thus one device-launch queue), while single verify/sign/hash
    calls pass straight through to the wrapped provider."""

    def __init__(self, provider, **batcher_kwargs):
        self._provider = provider
        self.batcher = VerifyBatcher(provider, **batcher_kwargs)

    def batch_verify(self, keys, signatures, digests):
        return self.batcher.verify_batch(keys, signatures, digests)

    def batch_verify_async(self, keys, signatures, digests):
        return self.batcher.submit(keys, signatures, digests)

    def stop(self) -> None:
        self.batcher.stop()

    def __getattr__(self, name):
        return getattr(self._provider, name)
