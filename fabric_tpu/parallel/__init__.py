"""Mesh-sharded execution (SURVEY.md §2.13 P3/P6).

The reference scales by channel-level process parallelism
(core/peer/peer.go:337-408: independent Channel objects) and per-tx
goroutines. The TPU-native equivalents here:

- `mesh`: device-mesh construction ("data" and "channel" axes).
- `sharded.ShardedVerify`: the batched ECDSA kernel jitted over a mesh —
  batch lanes sharded over "data" (P2/P6), whole channels sharded over
  "channel" (P3), masks all-gathered over ICI.
- `provider.MeshTPUProvider`: drop-in BCCSP provider that spreads one
  channel's (tx x sig) batch over every device.
- `multichannel.MultiChannelValidator`: validates one block per channel
  in a single device step (BASELINE config #5: 4 channels x 2k tx).
- `batcher.VerifyBatcher`: cross-channel verify coalescing with bounded
  backpressure (P7) — few large launches instead of many small ones.
"""

from fabric_tpu.parallel.mesh import (
    CHANNEL_AXIS,
    DATA_AXIS,
    flat_mesh,
    grid_mesh,
)
from fabric_tpu.parallel.sharded import ShardedVerify
from fabric_tpu.parallel.provider import MeshTPUProvider
from fabric_tpu.parallel.multichannel import MultiChannelValidator
from fabric_tpu.parallel.batcher import BatchingProvider, VerifyBatcher

# CHANNEL_AXIS/DATA_AXIS dropped from __all__: mesh-internal axis
# names nothing outside this package references (fabdep dead-export)
__all__ = [
    "BatchingProvider",
    "flat_mesh",
    "grid_mesh",
    "ShardedVerify",
    "MeshTPUProvider",
    "MultiChannelValidator",
    "VerifyBatcher",
]
