"""gRPC service adapters: AtomicBroadcast (orderer), Endorser + Deliver
(peer), wired onto the in-process handlers (reference
orderer/common/server/server.go Broadcast/Deliver,
core/peer/deliverevents.go, core/endorser as a gRPC service).

Service/method names and message framing match fabric-protos, so stock
SDK clients interoperate: /orderer.AtomicBroadcast/{Broadcast,Deliver},
/protos.Endorser/ProcessProposal, /protos.Deliver/{Deliver,
DeliverFiltered}.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional

from fabric_tpu.comm.server import STREAM_STREAM, UNARY, GRPCServer
from fabric_tpu.deliver.server import DeliverHandler, deliver_filtered
from fabric_tpu.protos import ab_pb2, common_pb2, peer_pb2


def register_atomic_broadcast(
    server: GRPCServer,
    broadcast_handler,  # orderer.broadcast.BroadcastHandler
    deliver_handler: DeliverHandler,
) -> None:
    def broadcast(request_iterator, context) -> Iterator[ab_pb2.BroadcastResponse]:
        for env in request_iterator:
            status, info = broadcast_handler.process_message(env)
            resp = ab_pb2.BroadcastResponse()
            resp.status = status
            resp.info = info
            yield resp

    def deliver(request_iterator, context) -> Iterator[ab_pb2.DeliverResponse]:
        for env in request_iterator:
            yield from deliver_handler.deliver_blocks(env)

    server.register(
        "orderer.AtomicBroadcast",
        {
            "Broadcast": (
                STREAM_STREAM,
                broadcast,
                common_pb2.Envelope.FromString,
                ab_pb2.BroadcastResponse.SerializeToString,
            ),
            "Deliver": (
                STREAM_STREAM,
                deliver,
                common_pb2.Envelope.FromString,
                ab_pb2.DeliverResponse.SerializeToString,
            ),
        },
    )


def register_endorser(server: GRPCServer, endorser) -> None:
    def process_proposal(request: peer_pb2.SignedProposal, context):
        return endorser.process_proposal(request)

    server.register(
        "protos.Endorser",
        {
            "ProcessProposal": (
                UNARY,
                process_proposal,
                peer_pb2.SignedProposal.FromString,
                peer_pb2.ProposalResponse.SerializeToString,
            ),
        },
    )


def register_peer_deliver(
    server: GRPCServer,
    deliver_handler: DeliverHandler,
    pvt_entries=None,
    pvt_policy_checker=None,
) -> None:
    """The peer's Deliver service (block + filtered-block +
    block-and-private-data events to SDKs, core/peer/deliverevents.go:239
    and :270).  `pvt_entries(channel_id, block_num) -> [PvtEntry]` backs
    DeliverWithPrivateData; when absent that method serves empty maps.
    `pvt_policy_checker(channel_id, SignedData)` raises to deny access to
    the private-data stream (required signed requests)."""
    from fabric_tpu.deliver.server import deliver_with_pvtdata

    def deliver(request_iterator, context):
        for env in request_iterator:
            yield from deliver_handler.deliver_blocks(env)

    def deliver_filtered_rpc(request_iterator, context):
        for env in request_iterator:
            yield from deliver_filtered(deliver_handler, env)

    def deliver_pvt_rpc(request_iterator, context):
        source = pvt_entries or (lambda ch, num: [])
        for env in request_iterator:
            yield from deliver_with_pvtdata(
                deliver_handler, env, source, pvt_policy_checker
            )

    server.register(
        "protos.Deliver",
        {
            "Deliver": (
                STREAM_STREAM,
                deliver,
                common_pb2.Envelope.FromString,
                ab_pb2.DeliverResponse.SerializeToString,
            ),
            "DeliverFiltered": (
                STREAM_STREAM,
                deliver_filtered_rpc,
                common_pb2.Envelope.FromString,
                ab_pb2.DeliverResponse.SerializeToString,
            ),
            "DeliverWithPrivateData": (
                STREAM_STREAM,
                deliver_pvt_rpc,
                common_pb2.Envelope.FromString,
                ab_pb2.DeliverResponse.SerializeToString,
            ),
        },
    )


# ---------------------------------------------------------------------------
# Client helpers (SDK-side: broadcast a tx, pull blocks)
# ---------------------------------------------------------------------------


def broadcast_envelope(channel, env: common_pb2.Envelope) -> ab_pb2.BroadcastResponse:
    """One-shot Broadcast over a grpc.Channel."""
    stub = channel.stream_stream(
        "/orderer.AtomicBroadcast/Broadcast",
        request_serializer=common_pb2.Envelope.SerializeToString,
        response_deserializer=ab_pb2.BroadcastResponse.FromString,
    )
    responses = stub(iter([env]))
    return next(responses)


def deliver_stream(
    channel,
    envelope: common_pb2.Envelope,
    service: str = "orderer.AtomicBroadcast",
    method: str = "Deliver",
) -> Iterator[ab_pb2.DeliverResponse]:
    stub = channel.stream_stream(
        f"/{service}/{method}",
        request_serializer=common_pb2.Envelope.SerializeToString,
        response_deserializer=ab_pb2.DeliverResponse.FromString,
    )
    return stub(iter([envelope]))


def process_proposal(channel, signed: peer_pb2.SignedProposal) -> peer_pb2.ProposalResponse:
    stub = channel.unary_unary(
        "/protos.Endorser/ProcessProposal",
        request_serializer=peer_pb2.SignedProposal.SerializeToString,
        response_deserializer=peer_pb2.ProposalResponse.FromString,
    )
    return stub(signed)


def register_snapshot_service(
    server: GRPCServer,
    managers,
    policy_checker=None,
) -> None:
    """The peer's Snapshot admin service (reference
    core/ledger/snapshotgrpc/snapshot_service.go:25-87: Generate, Cancel,
    QueryPendings over SignedSnapshotRequest).

    ``managers(channel_id)`` resolves the channel's
    SnapshotRequestManager; ``policy_checker(channel_id, SignedData)``
    raises to deny (the reference checks the snapshot/* ACL resources
    against the channel admins)."""
    from google.protobuf import empty_pb2

    from fabric_tpu.policy.manager import SignedData

    def _open(signed: peer_pb2.SignedSnapshotRequest, msg_cls):
        req = msg_cls()
        req.ParseFromString(signed.request)
        if policy_checker is not None:
            shdr = common_pb2.SignatureHeader()
            shdr.ParseFromString(req.signature_header)
            policy_checker(
                req.channel_id,
                SignedData(signed.request, shdr.creator, signed.signature),
            )
        mgr = managers(req.channel_id)
        if mgr is None:
            raise KeyError(f"channel {req.channel_id} not found")
        return req, mgr

    def generate(signed, context):
        req, mgr = _open(signed, peer_pb2.SnapshotRequest)
        mgr.submit(req.block_number)
        return empty_pb2.Empty()

    def cancel(signed, context):
        req, mgr = _open(signed, peer_pb2.SnapshotRequest)
        mgr.cancel(req.block_number)
        return empty_pb2.Empty()

    def query_pendings(signed, context):
        _req, mgr = _open(signed, peer_pb2.SnapshotQuery)
        return peer_pb2.QueryPendingSnapshotsResponse(
            block_numbers=mgr.pending()
        )

    server.register(
        "protos.Snapshot",
        {
            "Generate": (
                UNARY,
                generate,
                peer_pb2.SignedSnapshotRequest.FromString,
                empty_pb2.Empty.SerializeToString,
            ),
            "Cancel": (
                UNARY,
                cancel,
                peer_pb2.SignedSnapshotRequest.FromString,
                empty_pb2.Empty.SerializeToString,
            ),
            "QueryPendings": (
                UNARY,
                query_pendings,
                peer_pb2.SignedSnapshotRequest.FromString,
                peer_pb2.QueryPendingSnapshotsResponse.SerializeToString,
            ),
        },
    )
