from fabric_tpu.comm.server import GRPCServer, tls_server_credentials  # noqa: F401
