"""gRPC server interceptors (reference common/grpclogging zap
interceptors + common/grpcmetrics): per-RPC logs with durations and
status, and RPC counters/duration histograms over the metrics SPI.
Unary and streaming RPCs get separate metric families, mirroring
grpcmetrics' unary_*/stream_* split; outcomes are recorded in `finally`
so client-cancelled streams (GeneratorExit) still count."""

from __future__ import annotations

import time
from typing import Optional

import grpc

from fabric_tpu.common import flogging
from fabric_tpu.common.metrics import CounterOpts, HistogramOpts, Provider


def _split_method(full_method: str):
    # "/orderer.AtomicBroadcast/Broadcast" -> ("orderer.AtomicBroadcast",
    # "Broadcast")
    parts = full_method.lstrip("/").split("/", 1)
    if len(parts) == 2:
        return parts[0], parts[1]
    return full_method, ""


def _wrap_handler(handler, around):
    """Wrap whichever of the four handler kinds this is with `around`,
    which receives (behavior, kind) and returns a new behavior."""
    if handler is None:
        return None
    if handler.unary_unary:
        return grpc.unary_unary_rpc_method_handler(
            around(handler.unary_unary, "unary_unary"),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    if handler.unary_stream:
        return grpc.unary_stream_rpc_method_handler(
            around(handler.unary_stream, "unary_stream"),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    if handler.stream_unary:
        return grpc.stream_unary_rpc_method_handler(
            around(handler.stream_unary, "stream_unary"),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    return grpc.stream_stream_rpc_method_handler(
        around(handler.stream_stream, "stream_stream"),
        request_deserializer=handler.request_deserializer,
        response_serializer=handler.response_serializer,
    )


class LoggingInterceptor(grpc.ServerInterceptor):
    """grpclogging analog: one log line per completed RPC with service,
    method, duration and outcome.

    Payload logging (grpclogging/server.go payloadLogger): when the
    `comm.grpc.payload` logger is at DEBUG — via the /logspec flogging
    spec, like the reference's `grpc.payload=debug` — every request and
    response message is logged with its type and serialized size."""

    PAYLOAD_LOGGER = "comm.grpc.payload"

    def __init__(self, logger=None, payload_logger=None):
        self.logger = logger or flogging.must_get_logger("comm.grpc")
        self.payload_logger = payload_logger or flogging.must_get_logger(
            self.PAYLOAD_LOGGER
        )

    def _log_payload(self, service, method, direction, msg) -> None:
        plog = self.payload_logger
        if not plog.isEnabledFor(10):  # logging.DEBUG
            return
        try:
            size = len(msg.SerializeToString())
        except Exception:  # noqa: BLE001 - non-proto payloads
            size = -1
        plog.debug(
            "payload %s grpc.service=%s grpc.method=%s type=%s bytes=%d",
            direction,
            service,
            method,
            type(msg).__name__,
            size,
        )

    def _tap(self, service, method, direction, iterator):
        for msg in iterator:
            self._log_payload(service, method, direction, msg)
            yield msg

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        service, method = _split_method(handler_call_details.method)
        logger = self.logger
        log_payload = self._log_payload
        tap = self._tap

        def around(behavior, kind):
            streaming_resp = kind.endswith("_stream")
            streaming_req = kind.startswith("stream")
            shape = "streaming" if "stream" in kind else "unary"

            def log(start, outcome):
                logger.debug(
                    "%s call %s grpc.service=%s grpc.method=%s "
                    "grpc.call_duration=%.3fms",
                    shape,
                    outcome,
                    service,
                    method,
                    (time.perf_counter() - start) * 1000,
                )

            def observe_request(request_or_iterator):
                if streaming_req:
                    return tap(service, method, "recv", request_or_iterator)
                log_payload(service, method, "recv", request_or_iterator)
                return request_or_iterator

            def unary(request_or_iterator, context):
                start = time.perf_counter()
                outcome = "failed"
                try:
                    out = behavior(observe_request(request_or_iterator), context)
                    log_payload(service, method, "send", out)
                    outcome = "completed"
                    return out
                finally:
                    log(start, outcome)

            def streaming(request_or_iterator, context):
                start = time.perf_counter()
                outcome = "failed"
                try:
                    yield from tap(
                        service,
                        method,
                        "send",
                        behavior(observe_request(request_or_iterator), context),
                    )
                    outcome = "completed"
                except GeneratorExit:
                    outcome = "cancelled"
                    raise
                finally:
                    log(start, outcome)

            return streaming if streaming_resp else unary

        return _wrap_handler(handler, around)


class MetricsInterceptor(grpc.ServerInterceptor):
    """grpcmetrics analog: requests_received/requests_completed counters
    and request_duration histograms, labeled (service, method[, code]),
    with separate unary_* and stream_* families."""

    def __init__(self, provider: Provider):
        def families(prefix):
            return (
                provider.new_counter(
                    CounterOpts(
                        namespace="grpc",
                        subsystem="server",
                        name=f"{prefix}_requests_received",
                        help=f"The number of {prefix} requests received.",
                        label_names=("service", "method"),
                    )
                ),
                provider.new_counter(
                    CounterOpts(
                        namespace="grpc",
                        subsystem="server",
                        name=f"{prefix}_requests_completed",
                        help=f"The number of {prefix} requests completed.",
                        label_names=("service", "method", "code"),
                    )
                ),
                provider.new_histogram(
                    HistogramOpts(
                        namespace="grpc",
                        subsystem="server",
                        name=f"{prefix}_request_duration",
                        help=f"The time to complete a {prefix} request.",
                        label_names=("service", "method"),
                    )
                ),
            )

        self._unary = families("unary")
        self._stream = families("stream")

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        service, method = _split_method(handler_call_details.method)

        def around(behavior, kind):
            streaming_resp = kind.endswith("_stream")
            received, completed, duration = (
                self._stream if "stream" in kind else self._unary
            )

            def observe(start, code):
                duration.with_labels(
                    "service", service, "method", method
                ).observe(time.perf_counter() - start)
                completed.with_labels(
                    "service", service, "method", method, "code", code
                ).add(1)

            def unary(request_or_iterator, context):
                received.with_labels(
                    "service", service, "method", method
                ).add(1)
                start = time.perf_counter()
                code = "Unknown"
                try:
                    out = behavior(request_or_iterator, context)
                    code = "OK"
                    return out
                finally:
                    observe(start, code)

            def streaming(request_or_iterator, context):
                received.with_labels(
                    "service", service, "method", method
                ).add(1)
                start = time.perf_counter()
                code = "Unknown"
                try:
                    yield from behavior(request_or_iterator, context)
                    code = "OK"
                except GeneratorExit:
                    code = "Canceled"
                    raise
                finally:
                    observe(start, code)

            return streaming if streaming_resp else unary

        return _wrap_handler(handler, around)
