"""gRPC server/client plumbing (reference usable-inter-nal/pkg/comm:
GRPCServer with mutual TLS, keepalive and max-message-size settings).

Services register by their Fabric wire names ("orderer.AtomicBroadcast",
"protos.Endorser", ...) through generic method handlers, so the wire
format matches stock Fabric SDK expectations without generated *_grpc
stubs (grpc_tools is not available in this environment; serializers are
the plain protobuf SerializeToString/FromString pair).
"""

from __future__ import annotations

from concurrent import futures
from typing import Callable, Dict, Optional, Tuple

import grpc

MAX_MSG_SIZE = 100 * 1024 * 1024  # reference comm defaults: 100MB

UNARY = "unary"
STREAM_STREAM = "stream_stream"
UNARY_STREAM = "unary_stream"


def _options():
    return [
        ("grpc.max_send_message_length", MAX_MSG_SIZE),
        ("grpc.max_receive_message_length", MAX_MSG_SIZE),
        ("grpc.keepalive_time_ms", 300_000),
    ]


def tls_server_credentials(
    cert_pem: bytes, key_pem: bytes, client_ca_pem: Optional[bytes] = None
) -> grpc.ServerCredentials:
    """Server TLS, optionally requiring client certs (mutual TLS —
    reference comm/creds.go)."""
    return grpc.ssl_server_credentials(
        [(key_pem, cert_pem)],
        root_certificates=client_ca_pem,
        require_client_auth=client_ca_pem is not None,
    )


class CertReloader:
    """File-backed server cert source with hot reload (reference
    usable-inter-nal/pkg/comm/server.go:44 SetServerCertificate: certs
    rotate without restarting the server). gRPC asks the fetcher for a
    fresh certificate configuration on every new TLS handshake; this
    one re-reads the PEMs only when an mtime changed, so rotation is a
    file swap (the k8s secret-mount pattern)."""

    def __init__(
        self,
        cert_path: str,
        key_path: str,
        client_ca_path=None,  # str | Sequence[str] (reference dialect:
        # ClientRootCAs is a LIST; multiple PEMs concatenate)
    ):
        import os as _os

        self._os = _os
        self._cert_path = cert_path
        self._key_path = key_path
        if isinstance(client_ca_path, (str, bytes)) or client_ca_path is None:
            self._ca_paths = [client_ca_path] if client_ca_path else []
        else:
            self._ca_paths = list(client_ca_path)
        self._mtimes = None
        self._config = None
        self.reloads = 0  # introspection for tests/ops
        self._fetch(strict=True)  # misconfigured paths fail at startup

    @property
    def requires_client_auth(self) -> bool:
        return bool(self._ca_paths)

    def _stat(self):
        paths = [self._cert_path, self._key_path, *self._ca_paths]
        return tuple(self._os.stat(p).st_mtime_ns for p in paths)

    def _fetch(self, strict: bool = False):
        try:
            mtimes = self._stat()
            if self._config is None or mtimes != self._mtimes:
                with open(self._key_path, "rb") as f:
                    key = f.read()
                with open(self._cert_path, "rb") as f:
                    cert = f.read()
                ca = None
                if self._ca_paths:
                    parts = []
                    for p in self._ca_paths:
                        with open(p, "rb") as f:
                            parts.append(f.read())
                    ca = b"".join(parts)
                self._config = grpc.ssl_server_certificate_configuration(
                    [(key, cert)], root_certificates=ca
                )
                self._mtimes = mtimes
                self.reloads += 1
        except OSError:
            if strict:
                raise  # startup: surface the misconfiguration now
            # rotation in progress (file momentarily absent): keep
            # serving the last good configuration
        return self._config

    def credentials(self) -> grpc.ServerCredentials:
        return grpc.dynamic_ssl_server_credentials(
            self._config,
            self._fetch,
            require_client_authentication=self.requires_client_auth,
        )


def tls_credentials_from_config(tls_cfg) -> Optional[grpc.ServerCredentials]:
    """One TLS-config dialect for BOTH node CLIs (accepts the peer's
    cert/key/clientRootCAs and the orderer's Certificate/PrivateKey/
    ClientRootCAs spellings). Enabled-but-incomplete is a HARD error —
    the reference refuses to start rather than silently serving
    plaintext when the operator asked for TLS."""
    if not tls_cfg:
        return None
    enabled = tls_cfg.get("enabled", tls_cfg.get("Enabled"))
    cert = tls_cfg.get("cert") or tls_cfg.get("Certificate")
    key = tls_cfg.get("key") or tls_cfg.get("PrivateKey")
    if enabled is False:
        return None
    if enabled is None and not (cert or key):
        return None
    if not cert or not key:
        raise ValueError(
            "TLS is enabled but cert/key paths are incomplete "
            f"(cert={cert!r}, key={key!r})"
        )
    cas = tls_cfg.get("clientRootCAs") or tls_cfg.get("ClientRootCAs")
    return CertReloader(cert, key, cas).credentials()


class ConcurrencyLimiter(grpc.ServerInterceptor):
    """Per-service concurrent-RPC limits (reference
    usable-inter-nal/peer/node/grpc_limiters.go: the endorser and
    deliver services get independent caps so one flooded service cannot
    starve the node). Over-limit RPCs are refused with
    RESOURCE_EXHAUSTED rather than queued — backpressure the client can
    see, like the reference's limiter returning ErrLimitExceeded."""

    def __init__(self, limits: Dict[str, int]):
        import threading

        self._sems = {
            svc: threading.BoundedSemaphore(n) for svc, n in limits.items()
        }

    def intercept_service(self, continuation, handler_call_details):
        # method: "/service.Name/Method"
        parts = handler_call_details.method.split("/")
        svc = parts[1] if len(parts) > 1 else ""
        sem = self._sems.get(svc)
        handler = continuation(handler_call_details)
        if sem is None or handler is None:
            return handler
        return _limited_handler(handler, sem, svc)


def _limited_handler(handler, sem, svc: str):
    def wrap_unary(behavior):
        def limited(request, context):
            if not sem.acquire(blocking=False):
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"concurrency limit reached for {svc}",
                )
            try:
                return behavior(request, context)
            finally:
                sem.release()

        return limited

    def wrap_stream(behavior):
        def limited(request_or_iterator, context):
            if not sem.acquire(blocking=False):
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"concurrency limit reached for {svc}",
                )
            try:
                yield from behavior(request_or_iterator, context)
            finally:
                sem.release()

        return limited

    if handler.unary_unary:
        return grpc.unary_unary_rpc_method_handler(
            wrap_unary(handler.unary_unary),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    if handler.unary_stream:
        return grpc.unary_stream_rpc_method_handler(
            wrap_stream(handler.unary_stream),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    if handler.stream_unary:
        return grpc.stream_unary_rpc_method_handler(
            wrap_unary(handler.stream_unary),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    return grpc.stream_stream_rpc_method_handler(
        wrap_stream(handler.stream_stream),
        request_deserializer=handler.request_deserializer,
        response_serializer=handler.response_serializer,
    )


class GRPCServer:
    def __init__(
        self,
        address: str = "127.0.0.1:0",
        credentials: Optional[grpc.ServerCredentials] = None,
        max_workers: int = 32,
        interceptors=(),  # comm.interceptors logging/metrics
    ):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_options(),
            interceptors=tuple(interceptors),
        )
        if credentials is not None:
            self._port = self._server.add_secure_port(address, credentials)
        else:
            self._port = self._server.add_insecure_port(address)
        host = address.rsplit(":", 1)[0]
        self.addr = f"{host}:{self._port}"

    def register(
        self,
        service_name: str,
        methods: Dict[str, Tuple[str, Callable, Callable, Callable]],
    ) -> None:
        """methods: name -> (kind, handler, request_deserializer,
        response_serializer). Handler signatures follow grpc generic
        handlers: unary (request, context) -> response; stream_stream
        (request_iterator, context) -> response iterator."""
        handlers = {}
        for name, (kind, fn, req_des, resp_ser) in methods.items():
            if kind == UNARY:
                handlers[name] = grpc.unary_unary_rpc_method_handler(
                    fn, request_deserializer=req_des, response_serializer=resp_ser
                )
            elif kind == UNARY_STREAM:
                handlers[name] = grpc.unary_stream_rpc_method_handler(
                    fn, request_deserializer=req_des, response_serializer=resp_ser
                )
            elif kind == STREAM_STREAM:
                handlers[name] = grpc.stream_stream_rpc_method_handler(
                    fn, request_deserializer=req_des, response_serializer=resp_ser
                )
            else:
                raise ValueError(f"unknown method kind {kind}")
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(service_name, handlers),)
        )

    def start(self) -> str:
        self._server.start()
        return self.addr

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


def channel_to(
    addr: str,
    root_ca_pem: Optional[bytes] = None,
    client_cert: Optional[Tuple[bytes, bytes]] = None,
) -> grpc.Channel:
    """Client channel (reference comm/client.go); TLS when a root CA is
    given, mutual TLS when a client (key, cert) pair is too."""
    if root_ca_pem is None:
        return grpc.insecure_channel(addr, options=_options())
    if client_cert is not None:
        key, cert = client_cert
        creds = grpc.ssl_channel_credentials(root_ca_pem, key, cert)
    else:
        creds = grpc.ssl_channel_credentials(root_ca_pem)
    return grpc.secure_channel(addr, creds, options=_options())
