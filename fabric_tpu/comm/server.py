"""gRPC server/client plumbing (reference usable-inter-nal/pkg/comm:
GRPCServer with mutual TLS, keepalive and max-message-size settings).

Services register by their Fabric wire names ("orderer.AtomicBroadcast",
"protos.Endorser", ...) through generic method handlers, so the wire
format matches stock Fabric SDK expectations without generated *_grpc
stubs (grpc_tools is not available in this environment; serializers are
the plain protobuf SerializeToString/FromString pair).
"""

from __future__ import annotations

from concurrent import futures
from typing import Callable, Dict, Optional, Tuple

import grpc

MAX_MSG_SIZE = 100 * 1024 * 1024  # reference comm defaults: 100MB

UNARY = "unary"
STREAM_STREAM = "stream_stream"
UNARY_STREAM = "unary_stream"


def _options():
    return [
        ("grpc.max_send_message_length", MAX_MSG_SIZE),
        ("grpc.max_receive_message_length", MAX_MSG_SIZE),
        ("grpc.keepalive_time_ms", 300_000),
    ]


def tls_server_credentials(
    cert_pem: bytes, key_pem: bytes, client_ca_pem: Optional[bytes] = None
) -> grpc.ServerCredentials:
    """Server TLS, optionally requiring client certs (mutual TLS —
    reference comm/creds.go)."""
    return grpc.ssl_server_credentials(
        [(key_pem, cert_pem)],
        root_certificates=client_ca_pem,
        require_client_auth=client_ca_pem is not None,
    )


class GRPCServer:
    def __init__(
        self,
        address: str = "127.0.0.1:0",
        credentials: Optional[grpc.ServerCredentials] = None,
        max_workers: int = 32,
        interceptors=(),  # comm.interceptors logging/metrics
    ):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_options(),
            interceptors=tuple(interceptors),
        )
        if credentials is not None:
            self._port = self._server.add_secure_port(address, credentials)
        else:
            self._port = self._server.add_insecure_port(address)
        host = address.rsplit(":", 1)[0]
        self.addr = f"{host}:{self._port}"

    def register(
        self,
        service_name: str,
        methods: Dict[str, Tuple[str, Callable, Callable, Callable]],
    ) -> None:
        """methods: name -> (kind, handler, request_deserializer,
        response_serializer). Handler signatures follow grpc generic
        handlers: unary (request, context) -> response; stream_stream
        (request_iterator, context) -> response iterator."""
        handlers = {}
        for name, (kind, fn, req_des, resp_ser) in methods.items():
            if kind == UNARY:
                handlers[name] = grpc.unary_unary_rpc_method_handler(
                    fn, request_deserializer=req_des, response_serializer=resp_ser
                )
            elif kind == UNARY_STREAM:
                handlers[name] = grpc.unary_stream_rpc_method_handler(
                    fn, request_deserializer=req_des, response_serializer=resp_ser
                )
            elif kind == STREAM_STREAM:
                handlers[name] = grpc.stream_stream_rpc_method_handler(
                    fn, request_deserializer=req_des, response_serializer=resp_ser
                )
            else:
                raise ValueError(f"unknown method kind {kind}")
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(service_name, handlers),)
        )

    def start(self) -> str:
        self._server.start()
        return self.addr

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


def channel_to(
    addr: str,
    root_ca_pem: Optional[bytes] = None,
    client_cert: Optional[Tuple[bytes, bytes]] = None,
) -> grpc.Channel:
    """Client channel (reference comm/client.go); TLS when a root CA is
    given, mutual TLS when a client (key, cert) pair is too."""
    if root_ca_pem is None:
        return grpc.insecure_channel(addr, options=_options())
    if client_cert is not None:
        key, cert = client_cert
        creds = grpc.ssl_channel_credentials(root_ca_pem, key, cert)
    else:
        creds = grpc.ssl_channel_credentials(root_ca_pem)
    return grpc.secure_channel(addr, creds, options=_options())
