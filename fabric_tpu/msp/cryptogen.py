"""Test crypto-material generator (reference cmd/cryptogen +
usable-inter-nal/cryptogen/ca): per-org ECDSA P-256 root CA, node/user
certs with NodeOU subject entries, ready-made MSPConfig objects.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

try:  # guarded: cert generation needs the cryptography package, but the
    # module must import (for type references) in minimal environments
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
except ImportError:  # pragma: no cover - exercised in minimal envs
    x509 = hashes = serialization = ec = NameOID = None  # type: ignore

from fabric_tpu.msp.identity import MSP, MSPConfig, NodeOUs


def _require_crypto() -> None:
    if x509 is None:
        raise RuntimeError(
            "the 'cryptography' package is required to generate X.509 "
            "org material (cryptogen)"
        )


def _name(common_name: str, org: str, ou: Optional[str] = None) -> x509.Name:
    attrs = [
        x509.NameAttribute(NameOID.COUNTRY_NAME, "US"),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
    ]
    if ou:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, ou))
    attrs.append(x509.NameAttribute(NameOID.COMMON_NAME, common_name))
    return x509.Name(attrs)


def _pem_cert(cert: x509.Certificate) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


@dataclass
class TLSPair:
    """One node's TLS material (cert/key PEM for grpc, DER for the
    gossip handshake's tls_cert_hash binding, issuing CA PEM)."""

    cert_pem: bytes
    key_pem: bytes
    cert_der: bytes
    ca_pem: bytes


@dataclass
class NodeIdentity:
    name: str
    cert_pem: bytes
    # None for HSM deployments: the private key lives on a PKCS#11
    # token, addressed by token_ski (bccsp/pkcs11 getECKey by SKI)
    key: Optional[ec.EllipticCurvePrivateKey]
    msp_id: str
    token_ski: bytes = b""

    @property
    def priv_scalar(self) -> int:
        if self.key is None:
            raise ValueError(
                f"identity {self.name} is token-resident (SKI "
                f"{self.token_ski.hex()}); no in-process private scalar"
            )
        return self.key.private_numbers().private_value


class OrgCA:
    """A self-signed org root CA that can enroll node/user identities."""

    def __init__(self, org_name: str, msp_id: str):
        _require_crypto()
        self.org_name = org_name
        self.msp_id = msp_id
        self.key = ec.generate_private_key(ec.SECP256R1())
        subject = _name(f"ca.{org_name}", org_name)
        now = datetime.datetime.now(datetime.timezone.utc)
        self.cert = (
            x509.CertificateBuilder()
            .subject_name(subject)
            .issuer_name(subject)
            .public_key(self.key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True,
                    key_cert_sign=True,
                    crl_sign=True,
                    content_commitment=False,
                    key_encipherment=False,
                    data_encipherment=False,
                    key_agreement=False,
                    encipher_only=False,
                    decipher_only=False,
                ),
                critical=True,
            )
            .sign(self.key, hashes.SHA256())
        )
        self.cert_pem = _pem_cert(self.cert)
        self._revoked: List[x509.Certificate] = []

    def enroll(self, name: str, ou: str = "peer") -> NodeIdentity:
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(name, self.org_name, ou=ou))
            .issuer_name(self.cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
            .sign(self.key, hashes.SHA256())
        )
        return NodeIdentity(name, _pem_cert(cert), key, self.msp_id)

    def enroll_tls(self, name: str) -> "TLSPair":
        """TLS server/client pair for a node (reference cryptogen's
        tls/ folder; here the org CA doubles as the TLS CA). SANs cover
        localhost + 127.0.0.1 so grpc hostname verification passes on
        loopback topologies; extended key usage allows both server and
        client auth (one pair per node, like Fabric's tls/server.crt)."""
        import ipaddress

        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(name, self.org_name, ou="tls"))
            .issuer_name(self.cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(
                x509.BasicConstraints(ca=False, path_length=None), critical=True
            )
            .add_extension(
                x509.SubjectAlternativeName(
                    [
                        x509.DNSName("localhost"),
                        x509.DNSName(name),
                        x509.IPAddress(ipaddress.IPv4Address("127.0.0.1")),
                    ]
                ),
                critical=False,
            )
            .add_extension(
                x509.ExtendedKeyUsage(
                    [
                        x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
                        x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH,
                    ]
                ),
                critical=False,
            )
            .sign(self.key, hashes.SHA256())
        )
        key_pem = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
        return TLSPair(
            cert_pem=_pem_cert(cert),
            key_pem=key_pem,
            cert_der=cert.public_bytes(serialization.Encoding.DER),
            ca_pem=self.cert_pem,
        )

    def revoke(self, identity: NodeIdentity) -> None:
        self._revoked.append(x509.load_pem_x509_certificate(identity.cert_pem))

    def crl_pem(self) -> bytes:
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateRevocationListBuilder()
            .issuer_name(self.cert.subject)
            .last_update(now - datetime.timedelta(hours=1))
            .next_update(now + datetime.timedelta(days=365))
        )
        for cert in self._revoked:
            builder = builder.add_revoked_certificate(
                x509.RevokedCertificateBuilder()
                .serial_number(cert.serial_number)
                .revocation_date(now - datetime.timedelta(minutes=5))
                .build()
            )
        crl = builder.sign(self.key, hashes.SHA256())
        return crl.public_bytes(serialization.Encoding.PEM)


@dataclass
class Org:
    """One generated organization: CA + standard identities + MSP."""

    ca: OrgCA
    admin: NodeIdentity
    peers: List[NodeIdentity]
    users: List[NodeIdentity]

    @property
    def msp_id(self) -> str:
        return self.ca.msp_id

    def msp_config(self, with_crl: bool = False) -> MSPConfig:
        return MSPConfig(
            msp_id=self.ca.msp_id,
            root_certs=[self.ca.cert_pem],
            admins=[self.admin.cert_pem],
            revocation_list=[self.ca.crl_pem()] if with_crl else [],
            node_ous=NodeOUs(enable=True),
        )

    def msp(self, provider=None, with_crl: bool = False) -> MSP:
        return MSP(self.msp_config(with_crl=with_crl), provider=provider)


def generate_org(
    org_name: str,
    msp_id: Optional[str] = None,
    num_peers: int = 1,
    num_users: int = 1,
) -> Org:
    ca = OrgCA(org_name, msp_id or f"{org_name}MSP")
    admin = ca.enroll(f"Admin@{org_name}", ou="admin")
    peers = [ca.enroll(f"peer{i}.{org_name}", ou="peer") for i in range(num_peers)]
    users = [ca.enroll(f"User{i}@{org_name}", ou="client") for i in range(num_users)]
    return Org(ca, admin, peers, users)
