"""Signing identities (reference msp SigningIdentity + signer package)."""

from __future__ import annotations

import secrets
from typing import Optional

from fabric_tpu.crypto import der
from fabric_tpu.crypto.bccsp import Provider, default_provider, ec_backend
from fabric_tpu.msp.cryptogen import NodeIdentity
from fabric_tpu.protos import protoutil


class SigningIdentity:
    """An identity that can sign: wraps a NodeIdentity's cert + key."""

    def __init__(self, node: NodeIdentity, provider: Optional[Provider] = None):
        self.node = node
        self.msp_id = node.msp_id
        self._provider = provider or default_provider()
        self._serialized = protoutil.serialize_identity(node.msp_id, node.cert_pem)

    def serialize(self) -> bytes:
        return self._serialized

    def sign(self, msg: bytes) -> bytes:
        """SHA-256 digest then low-S ECDSA, DER-encoded (the reference
        signer path: bccsp Hash + Sign, msp/identities.go Sign). A
        token-resident key (NodeIdentity.token_ski set, HSM deployment)
        signs THROUGH the provider's PKCS#11 session — the scalar never
        exists in process memory (bccsp/pkcs11 signECDSA)."""
        digest = self._provider.hash(msg)
        token_ski = getattr(self.node, "token_ski", b"")
        if token_ski:
            sign_by_ski = getattr(self._provider, "sign_by_ski", None)
            if sign_by_ski is None:
                raise ValueError(
                    "identity key is token-resident but the provider "
                    "has no PKCS#11 session (configure BCCSP PKCS11)"
                )
            return sign_by_ski(token_ski, digest)
        r, s = ec_backend().sign_digest(self.node.priv_scalar, digest)
        return der.marshal_signature(r, s)

    def new_nonce(self) -> bytes:
        return secrets.token_bytes(24)
