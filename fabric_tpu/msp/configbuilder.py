"""Filesystem MSP material (reference msp/configbuilder.go + the
cryptogen output layout integration/nwo consumes).

Directory layout written/read here matches Fabric's crypto-config tree:

  <root>/<org-domain>/
    msp/cacerts/ca.<domain>-cert.pem
    msp/admincerts/Admin@<domain>-cert.pem
    peers/<peer>.<domain>/msp/{signcerts,keystore,cacerts}
    users/<user>@<domain>/msp/{signcerts,keystore,cacerts}

Keys are PKCS#8 PEM (cryptogen's output format).
"""

from __future__ import annotations

import os
from typing import List, Optional

try:  # guarded: the PEM/X.509 material here needs the cryptography
    # package, but the module must import in minimal environments so
    # tier-1 collection stays clean (ladder: crypto/bccsp.py)
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization
except ImportError:  # pragma: no cover - exercised in minimal envs
    x509 = serialization = None  # type: ignore

from fabric_tpu.msp.cryptogen import NodeIdentity, Org
from fabric_tpu.msp.identity import MSP, MSPConfig, NodeOUs
from fabric_tpu.msp.signer import SigningIdentity


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _key_pem(node: NodeIdentity) -> bytes:
    return node.key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def write_org_dir(org: Org, root: str) -> str:
    """cryptogen generate: materialize one org's tree; returns org dir."""
    org_dir = os.path.join(root, org.ca.org_name)
    _write(
        os.path.join(org_dir, "msp", "cacerts", f"ca.{org.ca.org_name}-cert.pem"),
        org.ca.cert_pem,
    )
    _write(
        os.path.join(
            org_dir, "msp", "admincerts", f"Admin@{org.ca.org_name}-cert.pem"
        ),
        org.admin.cert_pem,
    )
    for kind, nodes in (("peers", org.peers), ("users", [org.admin] + org.users)):
        for node in nodes:
            base = os.path.join(org_dir, kind, node.name, "msp")
            _write(
                os.path.join(base, "signcerts", f"{node.name}-cert.pem"),
                node.cert_pem,
            )
            _write(os.path.join(base, "keystore", "priv_sk"), _key_pem(node))
            _write(
                os.path.join(base, "cacerts", f"ca.{org.ca.org_name}-cert.pem"),
                org.ca.cert_pem,
            )
            if kind == "peers":
                # TLS material alongside the MSP (reference cryptogen's
                # tls/ folder: server.crt/server.key/ca.crt) so TLS
                # configs have files to point at out of the box
                pair = org.ca.enroll_tls(node.name)
                tls_dir = os.path.join(org_dir, kind, node.name, "tls")
                _write(os.path.join(tls_dir, "server.crt"), pair.cert_pem)
                _write(os.path.join(tls_dir, "server.key"), pair.key_pem)
                _write(os.path.join(tls_dir, "ca.crt"), pair.ca_pem)
    return org_dir


def load_msp_config(org_msp_dir: str, msp_id: str) -> MSPConfig:
    """msp/configbuilder.go GetVerifyingMspConfig: read cacerts/admincerts
    from an org-level msp dir."""

    def read_all(sub: str) -> List[bytes]:
        d = os.path.join(org_msp_dir, sub)
        if not os.path.isdir(d):
            return []
        return [
            open(os.path.join(d, f), "rb").read() for f in sorted(os.listdir(d))
        ]

    roots = read_all("cacerts")
    if not roots:
        raise ValueError(f"no cacerts in {org_msp_dir}")
    return MSPConfig(
        msp_id=msp_id,
        root_certs=roots,
        intermediate_certs=read_all("intermediatecerts"),
        admins=read_all("admincerts"),
        revocation_list=read_all("crls"),
        node_ous=NodeOUs(),
    )


def _default_msp_provider():
    """MSP cert-chain checks and local signing are single-op host
    crypto — work TPUProvider delegates to the software path anyway —
    so config-loaded MSPs/signers default to the SOFTWARE provider
    rather than default_provider(): the latter probes for an
    accelerator, and a hung tunnel must never stall a CLI client or a
    node's MSP setup (observed as 60s client hangs). Callers that
    really want a device-backed provider pass it explicitly."""
    from fabric_tpu.crypto.bccsp import SoftwareProvider

    return SoftwareProvider()


def load_msp(org_msp_dir: str, msp_id: str, provider=None) -> MSP:
    return MSP(
        load_msp_config(org_msp_dir, msp_id),
        provider or _default_msp_provider(),
    )


def load_signing_identity(
    node_msp_dir: str, msp_id: str, provider=None
) -> SigningIdentity:
    """msp/configbuilder.go GetLocalMspConfig: signcerts + keystore."""
    if x509 is None:
        raise RuntimeError(
            "the 'cryptography' package is required to load X.509 "
            "signing material (configbuilder)"
        )
    sign_dir = os.path.join(node_msp_dir, "signcerts")
    certs = sorted(os.listdir(sign_dir))
    if not certs:
        raise ValueError(f"no signcerts in {node_msp_dir}")
    cert_pem = open(os.path.join(sign_dir, certs[0]), "rb").read()
    cert = x509.load_pem_x509_certificate(cert_pem)
    name = cert.subject.get_attributes_for_oid(
        x509.NameOID.COMMON_NAME
    )[0].value
    key_dir = os.path.join(node_msp_dir, "keystore")
    keys = sorted(os.listdir(key_dir)) if os.path.isdir(key_dir) else []
    key = None
    token_ski = b""
    if keys:
        key = serialization.load_pem_private_key(
            open(os.path.join(key_dir, keys[0]), "rb").read(), password=None
        )
    elif provider is not None and hasattr(provider, "sign_by_ski"):
        # HSM deployment (reference msp + bccsp/pkcs11): no keystore on
        # disk — the private key lives on the token, addressed by the
        # SKI derived from the cert's public key (sha256 over the
        # uncompressed EC point, pkcs11.go's ski convention)
        import hashlib

        point = cert.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.UncompressedPoint,
        )
        token_ski = hashlib.sha256(point).digest()
    else:
        raise ValueError(f"no keystore entries in {node_msp_dir}")
    node = NodeIdentity(
        name=name,
        cert_pem=cert_pem,
        key=key,
        msp_id=msp_id,
        token_ski=token_ski,
    )
    return SigningIdentity(node, provider or _default_msp_provider())
