"""Idemix MSP: anonymous credentials as a membership service provider.

Reference: msp/idemixmsp.go + msp/idemix_roles.go + the bccsp idemix
bridge's attribute encoding (bccsp/idemix/bridge/credential.go:50-60:
bytes attributes enter the credential as HashModOrder(bytes), int
attributes as the integer itself).

The idemix credential carries 4 attributes (msp/idemixmsp.go:25-35):
  0: OU   (disclosed)   — organizational unit identifier
  1: Role (disclosed)   — idemix role bitmask (MEMBER=1, ADMIN=2, ...)
  2: EnrollmentId (hidden)
  3: RevocationHandle (hidden, rhIndex=3)

An identity serializes as SerializedIdentity{mspid,
SerializedIdemixIdentity{nym_x, nym_y, ou, role, proof}} where `proof`
is an idemix signature over the EMPTY message disclosing OU+Role —
the cryptographic association between the pseudonym and the issuer.
Message signatures (Identity.Verify) are pseudonym signatures.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from fabric_tpu import idemix
from fabric_tpu.crypto import fp256bn as bn
from fabric_tpu.protos import (
    identities_pb2,
    idemix_pb2,
    msp_config_pb2,
    msp_principal_pb2,
)

# idemix role bitmask (msp/idemix_roles.go:16-22)
ROLE_MEMBER = 1
ROLE_ADMIN = 2
ROLE_CLIENT = 4
ROLE_PEER = 8

ATTR_OU = 0
ATTR_ROLE = 1
ATTR_ENROLLMENT_ID = 2
ATTR_REVOCATION_HANDLE = 3
RH_INDEX = ATTR_REVOCATION_HANDLE

ATTRIBUTE_NAMES = ["OU", "Role", "EnrollmentId", "RevocationHandle"]

PROOF_DISCLOSURE = [1, 1, 0, 0]  # disclose OU + Role
_EMPTY_MSG = b""


class IdemixMSPError(Exception):
    pass


def _msp_role_to_idemix(role_type: int) -> int:
    """msp/idemix_roles.go getIdemixRoleFromMSPRoleValue."""
    if role_type == msp_principal_pb2.MSPRole.ADMIN:
        return ROLE_ADMIN
    if role_type == msp_principal_pb2.MSPRole.CLIENT:
        return ROLE_CLIENT
    if role_type == msp_principal_pb2.MSPRole.PEER:
        return ROLE_PEER
    return ROLE_MEMBER


def _attr_bytes(value: bytes) -> int:
    return bn.hash_mod_order(value)


@dataclass
class IdemixIdentity:
    """A deserialized anonymous identity."""

    msp_id: str
    nym: bn.G1Point
    ou: msp_principal_pb2.OrganizationUnit
    role: msp_principal_pb2.MSPRole
    proof: idemix_pb2.Signature
    raw: bytes  # the SerializedIdentity bytes

    def serialize(self) -> bytes:
        return self.raw

    @property
    def role_mask(self) -> int:
        return _msp_role_to_idemix(self.role.role)


class IdemixMSP:
    """Verification-side idemix MSP (reference idemixmsp.go Setup with no
    signer)."""

    def __init__(self, config: msp_config_pb2.IdemixMSPConfig, rev_pk=None):
        self.name = config.name
        self.epoch = config.epoch
        self.ipk = idemix_pb2.IssuerPublicKey()
        self.ipk.ParseFromString(config.ipk)
        idemix.check_issuer_public_key(self.ipk)
        if len(self.ipk.attribute_names) != len(ATTRIBUTE_NAMES) or list(
            self.ipk.attribute_names
        ) != ATTRIBUTE_NAMES:
            raise IdemixMSPError(
                "issuer public key must have attributes OU, Role, "
                "EnrollmentId, and RevocationHandle"
            )
        self.rev_pk = rev_pk  # ECDSA-P384 public key object or None

    # -- identity plane (msp.MSP surface) -----------------------------------

    def deserialize_identity(self, serialized: bytes) -> IdemixIdentity:
        sid = identities_pb2.SerializedIdentity()
        sid.ParseFromString(serialized)
        if sid.mspid != self.name:
            raise IdemixMSPError(
                f"expected MSP ID {self.name}, received {sid.mspid}"
            )
        inner = identities_pb2.SerializedIdemixIdentity()
        inner.ParseFromString(sid.id_bytes)
        if not inner.nym_x or not inner.nym_y:
            raise IdemixMSPError("pseudonym is invalid")
        nym = (bn.big_from_bytes(inner.nym_x), bn.big_from_bytes(inner.nym_y))
        if not bn.g1_is_on_curve(nym):
            raise IdemixMSPError("pseudonym is not on the curve")
        ou = msp_principal_pb2.OrganizationUnit()
        ou.ParseFromString(inner.ou)
        role = msp_principal_pb2.MSPRole()
        role.ParseFromString(inner.role)
        proof = idemix_pb2.Signature()
        proof.ParseFromString(inner.proof)
        return IdemixIdentity(self.name, nym, ou, role, proof, serialized)

    def validate(self, ident: IdemixIdentity) -> None:
        """Verify the association proof (idemixmsp.go verifyProof):
        disclosure = [OU, Role, hidden, hidden] over the empty message."""
        if ident.msp_id != self.name:
            raise IdemixMSPError(
                "the supplied identity does not belong to this msp"
            )
        attr_values = [
            _attr_bytes(ident.ou.organizational_unit_identifier.encode()),
            ident.role_mask,
            None,
            None,
        ]
        try:
            idemix.verify_signature(
                ident.proof,
                PROOF_DISCLOSURE,
                self.ipk,
                _EMPTY_MSG,
                attr_values,
                RH_INDEX,
                self.rev_pk,
                self.epoch,
            )
        except idemix.IdemixError as e:
            raise IdemixMSPError(f"identity proof invalid: {e}") from e

    def verify(self, ident: IdemixIdentity, msg: bytes, sig: bytes) -> None:
        """Identity.Verify: pseudonym signature over msg."""
        nym_sig = idemix_pb2.NymSignature()
        nym_sig.ParseFromString(sig)
        try:
            idemix.verify_nym_signature(nym_sig, ident.nym, self.ipk, msg)
        except idemix.IdemixError as e:
            raise IdemixMSPError(f"signature invalid: {e}") from e

    def satisfies_principal(
        self, ident: IdemixIdentity, principal: msp_principal_pb2.MSPPrincipal
    ) -> None:
        """idemixmsp.go SatisfiesPrincipal: validate, then match role/OU."""
        self.validate(ident)
        cls = principal.principal_classification
        if cls == msp_principal_pb2.MSPPrincipal.ROLE:
            role = msp_principal_pb2.MSPRole()
            role.ParseFromString(principal.principal)
            if role.msp_identifier != self.name:
                raise IdemixMSPError(
                    f"the identity is a member of a different MSP "
                    f"({role.msp_identifier})"
                )
            want = role.role
            if want == msp_principal_pb2.MSPRole.MEMBER:
                return
            if want == msp_principal_pb2.MSPRole.ADMIN:
                if ident.role_mask & ROLE_ADMIN:
                    return
                raise IdemixMSPError("user is not an admin")
            if want in (
                msp_principal_pb2.MSPRole.CLIENT,
                msp_principal_pb2.MSPRole.PEER,
            ):
                wanted_mask = _msp_role_to_idemix(want)
                if ident.role_mask & wanted_mask:
                    return
                raise IdemixMSPError("user does not have the required role")
            raise IdemixMSPError(f"invalid MSP role type {want}")
        if cls == msp_principal_pb2.MSPPrincipal.ORGANIZATION_UNIT:
            ou = msp_principal_pb2.OrganizationUnit()
            ou.ParseFromString(principal.principal)
            if ou.msp_identifier != self.name:
                raise IdemixMSPError(
                    "the identity is a member of a different MSP"
                )
            if (
                ou.organizational_unit_identifier
                != ident.ou.organizational_unit_identifier
            ):
                raise IdemixMSPError("OU identifier does not match")
            return
        raise IdemixMSPError(f"invalid principal type {cls}")


class IdemixSigningIdentity:
    """Signer side: a fresh pseudonym + the proof binding it to the
    issuer's credential (idemixSigningIdentity)."""

    def __init__(
        self,
        msp: IdemixMSP,
        signer_config: msp_config_pb2.IdemixMSPSignerConfig,
        rng: Optional[random.Random] = None,
    ):
        self.msp = msp
        self.rng = rng or random.SystemRandom()
        self.sk = bn.big_from_bytes(signer_config.sk)
        self.cred = idemix_pb2.Credential()
        self.cred.ParseFromString(signer_config.cred)
        self.ou_id = signer_config.organizational_unit_identifier
        self.enrollment_id = signer_config.enrollment_id
        self.role_mask = signer_config.role
        self.cri = idemix_pb2.CredentialRevocationInformation()
        self.cri.ParseFromString(signer_config.credential_revocation_information)

        idemix.verify_credential(self.cred, self.sk, msp.ipk)
        self.nym, self.r_nym = idemix.make_nym(self.sk, msp.ipk, self.rng)

        role = msp_principal_pb2.MSPRole()
        role.msp_identifier = msp.name
        role.role = (
            msp_principal_pb2.MSPRole.ADMIN
            if self.role_mask & ROLE_ADMIN
            else msp_principal_pb2.MSPRole.MEMBER
        )
        self._role = role
        ou = msp_principal_pb2.OrganizationUnit()
        ou.msp_identifier = msp.name
        ou.organizational_unit_identifier = self.ou_id
        self._ou = ou

        proof = idemix.new_signature(
            self.cred,
            self.sk,
            self.nym,
            self.r_nym,
            msp.ipk,
            PROOF_DISCLOSURE,
            _EMPTY_MSG,
            RH_INDEX,
            self.cri,
            self.rng,
        )

        inner = identities_pb2.SerializedIdemixIdentity()
        inner.nym_x = bn.big_to_bytes(self.nym[0])
        inner.nym_y = bn.big_to_bytes(self.nym[1])
        inner.ou = ou.SerializeToString()
        inner.role = role.SerializeToString()
        inner.proof = proof.SerializeToString()
        sid = identities_pb2.SerializedIdentity()
        sid.mspid = msp.name
        sid.id_bytes = inner.SerializeToString()
        self._serialized = sid.SerializeToString()

    def serialize(self) -> bytes:
        return self._serialized

    def sign(self, msg: bytes) -> bytes:
        """Pseudonym signature (idemixSigningIdentity.Sign)."""
        return idemix.new_nym_signature(
            self.sk, self.nym, self.r_nym, self.msp.ipk, msg, self.rng
        ).SerializeToString()


# --------------------------------------------------------------------------
# idemixgen analog (cmd/idemixgen): issuer + default signer config
# --------------------------------------------------------------------------


def generate_issuer(rng: Optional[random.Random] = None):
    """idemixgen ca-keygen: issuer key with the 4 fixed attributes +
    long-term revocation key."""
    rng = rng or random.SystemRandom()
    ikey = idemix.new_issuer_key(ATTRIBUTE_NAMES, rng)
    rev_key = idemix.generate_long_term_revocation_key()
    return ikey, rev_key


def generate_signer_config(
    ikey,
    rev_key,
    ou_id: str,
    role_mask: int,
    enrollment_id: str,
    rng: Optional[random.Random] = None,
) -> msp_config_pb2.IdemixMSPSignerConfig:
    """idemixgen signerconfig: run the issuance protocol locally."""
    rng = rng or random.SystemRandom()
    sk = bn.rand_mod_order(rng)
    issuer_nonce = bn.big_to_bytes(bn.rand_mod_order(rng))
    req = idemix.new_cred_request(sk, issuer_nonce, ikey.ipk, rng)
    rh = bn.rand_mod_order(rng)
    attrs = [
        _attr_bytes(ou_id.encode()),
        role_mask,
        _attr_bytes(enrollment_id.encode()),
        rh,
    ]
    cred = idemix.new_credential(ikey, req, attrs, rng)
    cri = idemix.create_cri(rev_key, [rh], 0, idemix.ALG_NO_REVOCATION, rng)

    out = msp_config_pb2.IdemixMSPSignerConfig()
    out.cred = cred.SerializeToString()
    out.sk = bn.big_to_bytes(sk)
    out.organizational_unit_identifier = ou_id
    out.role = role_mask
    out.enrollment_id = enrollment_id
    out.credential_revocation_information = cri.SerializeToString()
    return out


def generate_msp_config(
    name: str,
    ou_id: str = "OU1",
    role_mask: int = ROLE_MEMBER,
    enrollment_id: str = "user1",
    rng: Optional[random.Random] = None,
) -> Tuple[msp_config_pb2.IdemixMSPConfig, object]:
    """Full idemix MSP config (verification + default signer). Returns
    (config, revocation private key object)."""
    rng = rng or random.SystemRandom()
    ikey, rev_key = generate_issuer(rng)
    signer = generate_signer_config(
        ikey, rev_key, ou_id, role_mask, enrollment_id, rng
    )
    cfg = msp_config_pb2.IdemixMSPConfig()
    cfg.name = name
    cfg.ipk = ikey.ipk.SerializeToString()
    from cryptography.hazmat.primitives import serialization

    cfg.revocation_pk = rev_key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    )
    cfg.signer.CopyFrom(signer)
    return cfg, rev_key
