"""Membership Service Provider: X.509 identities, validation, principals."""

from fabric_tpu.msp.identity import MSP, Identity, MSPConfig

__all__ = ["MSP", "Identity", "MSPConfig"]
