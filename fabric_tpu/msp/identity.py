"""X.509 MSP (reference msp/ package, ~3.9k LoC Go -> host Python here).

The single choke point every signature check in the system flows through is
Identity.verify (reference msp/identities.go:169-196: digest = SHA-256(msg),
then bccsp.Verify). Here that routes to the pluggable provider — i.e. the
batched TPU path — while X.509 mechanics (deserialize, chain validation,
CRL, principal matching) stay host-side, with a deserialization cache
(reference msp/cache keyed by raw identity bytes, SURVEY.md §2.2).
"""

from __future__ import annotations

import datetime
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

try:  # guarded: X.509 mechanics need the cryptography package, but the
    # crypto/validation core (hostec tier) must import without it
    from cryptography import x509
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
except ImportError:  # pragma: no cover - exercised in minimal envs
    x509 = InvalidSignature = hashes = serialization = ec = None  # type: ignore

from fabric_tpu.crypto.bccsp import ECDSAPublicKey, Provider, default_provider
from fabric_tpu.protos import identities_pb2, msp_principal_pb2, protoutil


class MSPError(Exception):
    pass


def _require_crypto() -> None:
    if x509 is None:
        raise MSPError(
            "the 'cryptography' package is required for X.509 MSP "
            "operations (identity deserialization, chain validation)"
        )


# sentinel: "chain validation not yet succeeded" (None means validated OK;
# failures are never cached — they may be time-dependent)
_UNVALIDATED = object()


@dataclass(frozen=True)
class NodeOUs:
    """NodeOU classification (reference msp/mspimplsetup.go): OU strings
    that classify a cert as client/peer/admin/orderer."""

    enable: bool = False
    client_ou: str = "client"
    peer_ou: str = "peer"
    admin_ou: str = "admin"
    orderer_ou: str = "orderer"


@dataclass
class MSPConfig:
    msp_id: str
    root_certs: List[bytes]  # PEM
    intermediate_certs: List[bytes] = field(default_factory=list)
    admins: List[bytes] = field(default_factory=list)  # PEM certs
    revocation_list: List[bytes] = field(default_factory=list)  # PEM CRLs
    node_ous: NodeOUs = field(default_factory=NodeOUs)


class Identity:
    """A deserialized (MSPID, x509 cert) pair."""

    def __init__(self, msp_id: str, cert: x509.Certificate, provider: Provider):
        _require_crypto()
        self.msp_id = msp_id
        self.cert = cert
        self._provider = provider
        # memoized derived forms: identities are deserialized once per
        # distinct cert (MSP._deser_cache) but consulted per signature job
        # — a 1k-tx block touches the same few identities thousands of
        # times (reference msp/cache rationale)
        self._serialized: Optional[bytes] = None
        self._fingerprint: Optional[bytes] = None
        self._ou_values: Optional[List[str]] = None
        self._validation_err: object = _UNVALIDATED
        pub = cert.public_key()
        if not isinstance(pub, ec.EllipticCurvePublicKey) or not isinstance(
            pub.curve, ec.SECP256R1
        ):
            raise MSPError("only ECDSA P-256 identities supported")
        nums = pub.public_numbers()
        self.public_key = ECDSAPublicKey(nums.x, nums.y)

    @property
    def ou_values(self) -> List[str]:
        if self._ou_values is None:
            attrs = self.cert.subject.get_attributes_for_oid(
                x509.NameOID.ORGANIZATIONAL_UNIT_NAME
            )
            self._ou_values = [a.value for a in attrs]
        return self._ou_values

    def serialize(self) -> bytes:
        if self._serialized is None:
            pem = self.cert.public_bytes(serialization.Encoding.PEM)
            self._serialized = protoutil.serialize_identity(self.msp_id, pem)
        return self._serialized

    def fingerprint(self) -> bytes:
        """SHA-256 of the serialized identity (cache keys in the validator
        and policy layers)."""
        if self._fingerprint is None:
            self._fingerprint = hashlib.sha256(self.serialize()).digest()
        return self._fingerprint

    def verify(self, msg: bytes, sig: bytes) -> None:
        """Raises MSPError on failure (reference Identity.Verify returns
        error); success returns None."""
        digest = self._provider.hash(msg)
        try:
            ok = self._provider.verify(self.public_key, sig, digest)
        except Exception as e:
            raise MSPError(f"could not determine the validity of the signature: {e}")
        if not ok:
            raise MSPError("The signature is invalid")


class MSP:
    """bccspmsp analog: one organization's verification context."""

    def __init__(self, config: MSPConfig, provider: Optional[Provider] = None):
        _require_crypto()
        self.config = config
        self.msp_id = config.msp_id
        self._provider = provider or default_provider()
        self._roots = [x509.load_pem_x509_certificate(c) for c in config.root_certs]
        self._intermediates = [
            x509.load_pem_x509_certificate(c) for c in config.intermediate_certs
        ]
        self._admin_serialized = set()
        for pem in config.admins:
            cert = x509.load_pem_x509_certificate(pem)
            self._admin_serialized.add(
                protoutil.serialize_identity(
                    config.msp_id, cert.public_bytes(serialization.Encoding.PEM)
                )
            )
        self._revoked_serials = set()
        for crl_pem in config.revocation_list:
            crl = x509.load_pem_x509_crl(crl_pem)
            for revoked in crl:
                self._revoked_serials.add(revoked.serial_number)
        self._deser_cache: Dict[bytes, Identity] = {}

    # -- deserialization (msp/mspimpl.go DeserializeIdentity + msp/cache) --
    def deserialize_identity(self, serialized: bytes) -> Identity:
        cached = self._deser_cache.get(serialized)
        if cached is not None:
            return cached
        sid = protoutil.unmarshal(identities_pb2.SerializedIdentity, serialized)
        if sid.mspid != self.msp_id:
            raise MSPError(
                f"expected MSP ID {self.msp_id}, received {sid.mspid}"
            )
        try:
            cert = x509.load_pem_x509_certificate(sid.id_bytes)
        except Exception as e:
            raise MSPError(f"could not decode PEM certificate: {e}")
        ident = Identity(sid.mspid, cert, self._provider)
        if len(self._deser_cache) > 16384:
            self._deser_cache.clear()
        self._deser_cache[serialized] = ident
        return ident

    # -- validation (msp/mspimplvalidate.go) -------------------------------
    def validate(self, identity: Identity) -> None:
        """Chain walk + expiry + CRL.  SUCCESS is memoized on the identity
        for the process lifetime — the trade the reference makes in
        msp/cache (a block consults the same few identities thousands of
        times; chain building does an ECDSA verify per hop and dominated
        block validation before memoization).  FAILURES are NOT cached:
        'not yet valid' and expiry are time-dependent, and freezing a
        pre-validity verdict forever would diverge this peer's
        TRANSACTIONS_FILTER from peers that first saw the cert later."""
        if identity._validation_err is None:
            return
        self._validate_uncached(identity)
        identity._validation_err = None

    def _validate_uncached(self, identity: Identity) -> None:
        cert = identity.cert
        chain = self._build_chain(cert)
        now = datetime.datetime.now(datetime.timezone.utc)
        for c in [cert] + chain:
            if not (c.not_valid_before_utc <= now <= c.not_valid_after_utc):
                raise MSPError(f"certificate expired or not yet valid: {c.subject}")
        if cert.serial_number in self._revoked_serials:
            raise MSPError("The certificate has been revoked")

    def _build_chain(self, cert: x509.Certificate) -> List[x509.Certificate]:
        """Walk issuers through intermediates to a trusted root, checking
        each signature (Go x509 Verify analog, sans path constraints)."""
        chain: List[x509.Certificate] = []
        current = cert
        pool = self._intermediates + self._roots
        for _ in range(8):  # max depth
            issuer = None
            for cand in pool:
                if current.issuer == cand.subject:
                    try:
                        current.verify_directly_issued_by(cand)
                    except (InvalidSignature, ValueError, TypeError):
                        continue
                    issuer = cand
                    break
            if issuer is None:
                raise MSPError("could not obtain certification chain")
            chain.append(issuer)
            if any(issuer is r for r in self._roots):
                return chain
            current = issuer
        raise MSPError("certification chain too deep")

    # -- principal matching (msp/mspimpl.go SatisfiesPrincipal) ------------
    def satisfies_principal(
        self, identity: Identity, principal: msp_principal_pb2.MSPPrincipal
    ) -> None:
        cls = principal.principal_classification
        P = msp_principal_pb2.MSPPrincipal
        if cls == P.ROLE:
            role = protoutil.unmarshal(msp_principal_pb2.MSPRole, principal.principal)
            if role.msp_identifier != self.msp_id:
                raise MSPError(
                    f"the identity is a member of a different MSP "
                    f"(expected {role.msp_identifier}, got {self.msp_id})"
                )
            R = msp_principal_pb2.MSPRole
            if role.role == R.MEMBER:
                self.validate(identity)
                return
            if role.role == R.ADMIN:
                if identity.serialize() in self._admin_serialized:
                    return
                if self.config.node_ous.enable and self._has_ou(
                    identity, self.config.node_ous.admin_ou
                ):
                    self.validate(identity)
                    return
                raise MSPError("This identity is not an admin")
            if role.role in (R.CLIENT, R.PEER, R.ORDERER):
                if not self.config.node_ous.enable:
                    raise MSPError("NodeOUs not activated, cannot tell apart identities.")
                ou_name = {
                    R.CLIENT: self.config.node_ous.client_ou,
                    R.PEER: self.config.node_ous.peer_ou,
                    R.ORDERER: self.config.node_ous.orderer_ou,
                }[role.role]
                self.validate(identity)
                if not self._has_ou(identity, ou_name):
                    raise MSPError(f"The identity is not a {ou_name} under this MSP")
                return
            raise MSPError(f"invalid MSP role type {role.role}")
        if cls == P.IDENTITY:
            if identity.serialize() != principal.principal:
                raise MSPError("The identities do not match")
            return
        if cls == P.ORGANIZATION_UNIT:
            ou = protoutil.unmarshal(
                msp_principal_pb2.OrganizationUnit, principal.principal
            )
            if ou.msp_identifier != self.msp_id:
                raise MSPError("the identity is a member of a different MSP")
            self.validate(identity)
            if not self._has_ou(identity, ou.organizational_unit_identifier):
                raise MSPError("The identities do not match")
            return
        raise MSPError(f"principal type {cls} is not supported")

    def _has_ou(self, identity: Identity, ou_name: str) -> bool:
        return ou_name in identity.ou_values


class MSPManager:
    """Per-channel MSP registry (reference msp/mspmgrimpl.go)."""

    def __init__(self, msps: Sequence[MSP]):
        self._by_id = {m.msp_id: m for m in msps}

    def get_msp(self, msp_id: str) -> MSP:
        msp = self._by_id.get(msp_id)
        if msp is None:
            raise MSPError(f"MSP {msp_id} is unknown")
        return msp

    def deserialize_identity(self, serialized: bytes) -> Tuple[Identity, MSP]:
        sid = protoutil.unmarshal(identities_pb2.SerializedIdentity, serialized)
        msp = self.get_msp(sid.mspid)
        return msp.deserialize_identity(serialized), msp

    def msps(self) -> List[MSP]:
        return list(self._by_id.values())
