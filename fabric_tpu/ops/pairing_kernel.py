"""Batched Ate2 pairing check on device (Idemix BBS+ structure check).

Reference semantics: idemix/signature.go:288-296 —
    Fexp( Ate(W, APrime) * Inverse(Ate(GenG2, ABar)) ).Isunity()
with W (issuer key) and GenG2 FIXED G2 points; only the G1 arguments
(A', ABar) vary per signature.

Device design (NOT a port of amcl's pairing):

- Both G2 points are fixed, so the entire Miller-loop point chain runs
  ON THE HOST once per issuer key, emitting per-step LINE COEFFICIENTS:
  l(P) = A + B·px + py with A = λ·x_T − y_T, B = −λ (Fp12 constants;
  host `_line`).  The device never touches G2/Fp12 point arithmetic —
  each Miller step is one Fp12 squaring, a 12-row scalar multiply (the
  line evaluated at P), and an Fp12 multiply, batched over signatures.
- The ISSUER key's line schedule enters the program as RUNTIME INPUTS
  (a few hundred KB of (steps, 12, NLIMBS) arrays), so ONE compiled
  program serves every issuer key per lane bucket — a fresh issuer
  costs a ~1s host schedule build, not a ~230s TPU recompile.  Only
  the generator-G2 schedule and the add-step bit mask (properties of
  the curve, not the key) stay baked as constants.  Lane buckets are
  capped (8 or 16); larger batches chunk over the cached program.
- Both pairings run in ONE lax.scan (they share the |6u+2| bit
  schedule); add-steps are selected per step by a static mask.
- The final exponentiation mirrors the host oracle op-for-op
  (conj·inv easy part, frobenius², ~1020-bit hard-part power as a
  scan), so every intermediate is differential-testable.
- The Fp12 layer is the row-stacked fabric_tpu.ops.fp12: one gather +
  one stacked Montgomery multiply per tower op, keeping the graph
  small enough for the remote TPU compiler.

Differential contract (tests/test_pairing_kernel.py): device Miller
values equal host `miller_loop` bit-for-bit; unity verdicts equal the
host oracle's on valid, corrupted, and absent inputs.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fabric_tpu.common import fp256bn as host
from fabric_tpu.ops import bignum as bn
from fabric_tpu.ops import fp12 as f12

# ---------------------------------------------------------------------------
# Host-side line precomputation (per fixed G2 point)
# ---------------------------------------------------------------------------

_SIX_U_TWO = 6 * host.U + 2
_N_BITS = bin(abs(_SIX_U_TWO))[3:]  # loop bits after the implicit MSB


# (A, B) with l(P) = A + B·px + py — shared with crypto/hostbn, which
# precomputes the same per-issuer schedules for its numpy lanes
_line_coeffs = host.line_coeffs


def _fp12_to_mont_rows(v: host.Fp12) -> np.ndarray:
    """(12, NLIMBS) uint32 Montgomery rows, order [c0.re, c0.im, ...]."""
    rows = []
    for c in v:
        rows.append(f12.to_mont_int(c[0]))  # fabtrace: disable=transfer-in-loop  # tower-bounded: 12 Fp12 coefficients per value, a trace-time constant, not lane-bounded
        rows.append(f12.to_mont_int(c[1]))  # fabtrace: disable=transfer-in-loop  # tower-bounded: 12 Fp12 coefficients per value, a trace-time constant, not lane-bounded
    return np.stack(rows).astype(np.uint32)


class LineSchedule:
    """Per-G2-point precomputed Miller lines: arrays over the scan
    steps (doubling line always; addition line + has_add for '1' bits),
    plus the two post-conjugation frobenius correction lines."""

    def __init__(self, q: host.G2Point):
        qe = host._untwist(q)
        t = qe
        dbl_a, dbl_b, add_a, add_b, has_add = [], [], [], [], []
        zero12 = _fp12_to_mont_rows(host.FP12_ZERO)
        for bit in _N_BITS:
            a, b = _line_coeffs(t, t)
            dbl_a.append(_fp12_to_mont_rows(a))  # fabtrace: disable=transfer-in-loop  # one-time per-issuer schedule precompute (scan-step bounded, cached on the pool for the key's lifetime), never per lane
            dbl_b.append(_fp12_to_mont_rows(b))  # fabtrace: disable=transfer-in-loop  # one-time per-issuer schedule precompute (scan-step bounded, cached on the pool for the key's lifetime), never per lane
            t = host._e12_add(t, t)
            if bit == "1":
                a, b = _line_coeffs(t, qe)
                add_a.append(_fp12_to_mont_rows(a))  # fabtrace: disable=transfer-in-loop  # one-time per-issuer schedule precompute (scan-step bounded, cached on the pool for the key's lifetime), never per lane
                add_b.append(_fp12_to_mont_rows(b))  # fabtrace: disable=transfer-in-loop  # one-time per-issuer schedule precompute (scan-step bounded, cached on the pool for the key's lifetime), never per lane
                has_add.append(1)
                t = host._e12_add(t, qe)
            else:
                add_a.append(zero12)
                add_b.append(zero12)
                has_add.append(0)
        assert _SIX_U_TWO < 0  # FP256BN: u negative (SIGN_OF_X)
        t = (t[0], host.fp12_neg(t[1]))
        q1 = (
            host.fp12_frobenius(qe[0], 1),
            host.fp12_frobenius(qe[1], 1),
        )
        q2 = (
            host.fp12_frobenius(qe[0], 2),
            host.fp12_neg(host.fp12_frobenius(qe[1], 2)),
        )
        corr = []
        a, b = _line_coeffs(t, q1)
        corr.append((_fp12_to_mont_rows(a), _fp12_to_mont_rows(b)))
        t = host._e12_add(t, q1)
        a, b = _line_coeffs(t, q2)
        corr.append((_fp12_to_mont_rows(a), _fp12_to_mont_rows(b)))

        self.dbl_a = np.stack(dbl_a)  # (S, 12, NLIMBS)
        self.dbl_b = np.stack(dbl_b)
        self.add_a = np.stack(add_a)
        self.add_b = np.stack(add_b)
        self.has_add = np.array(has_add, dtype=np.uint32)
        self.corr = corr


# ---------------------------------------------------------------------------
# Device evaluation
# ---------------------------------------------------------------------------


def _bcast12(p: f12.Rows) -> f12.Rows:
    """(1-row or (B,)) G1 coordinate -> (12, B) rows."""
    return tuple(
        jnp.broadcast_to(l, (12,) + l.shape[-1:]) for l in p
    )


def _line_eval(a_mat, b_mat, px12: f12.Rows, py_rows: f12.Rows, like):
    """A + B·px + py, canonical output.  a_mat/b_mat are (12, NLIMBS)
    constants (traced scan slices); px12 is the G1 x broadcast to 12
    rows; py_rows has py at row 0 and zeros elsewhere."""
    a = f12.rows_of(a_mat, like)
    b = f12.rows_of(b_mat, like)
    bp = f12.rmul(b, px12)
    out = f12.radd(f12.radd(a, bp), py_rows)  # bound 3
    return f12.rreduce(out, 2)


def _miller2(
    w_arrs,
    sched_g: LineSchedule,
    p1x: f12.Rows,
    p1y: f12.Rows,
    p2x: f12.Rows,
    p2y: f12.Rows,
    like,
):
    """Both Miller loops in one scan (shared bit schedule); returns the
    host-bit-exact Miller values for (W,P1) and (g2,P2).

    `w_arrs` is the issuer schedule as TRACED arrays (dbl_a, dbl_b,
    add_a, add_b, corr_a, corr_b) so one program serves every issuer;
    the generator schedule and the add-step mask are compile-time
    constants (the mask is a property of |6u+2|'s bits, identical for
    every schedule)."""
    w_dbl_a, w_dbl_b, w_add_a, w_add_b, w_corr_a, w_corr_b = w_arrs
    p1x12, p2x12 = _bcast12(p1x), _bcast12(p2x)
    z11 = f12.rzero(11, like)
    p1y_rows = f12.rcat(tuple(l[None] for l in p1y), z11)
    p2y_rows = f12.rcat(tuple(l[None] for l in p2y), z11)

    xs = (
        w_dbl_a,
        w_dbl_b,
        w_add_a,
        w_add_b,
        jnp.asarray(sched_g.dbl_a),
        jnp.asarray(sched_g.dbl_b),
        jnp.asarray(sched_g.add_a),
        jnp.asarray(sched_g.add_b),
        jnp.asarray(sched_g.has_add),
    )

    def body(carry, step):
        f1_st, f2_st = carry
        (wda, wdb, waa, wab, gda, gdb, gaa, gab, has_add) = step
        f1 = f12.unpack(f1_st)
        f2 = f12.unpack(f2_st)
        f1 = f12.fp12_mul(
            f12.fp12_sqr(f1),
            _line_eval(wda, wdb, p1x12, p1y_rows, like),
        )
        f2 = f12.fp12_mul(
            f12.fp12_sqr(f2),
            _line_eval(gda, gdb, p2x12, p2y_rows, like),
        )
        f1a = f12.fp12_mul(
            f1, _line_eval(waa, wab, p1x12, p1y_rows, like)
        )
        f2a = f12.fp12_mul(
            f2, _line_eval(gaa, gab, p2x12, p2y_rows, like)
        )
        cond = has_add.astype(bool)
        f1 = f12.fp12_select(cond, f1a, f1)
        f2 = f12.fp12_select(cond, f2a, f2)
        return (f12.pack(f1), f12.pack(f2)), None

    one = f12.fp12_one(like)
    one = tuple(
        jnp.broadcast_to(l, (12,) + like.shape) for l in one
    )
    (f1_st, f2_st), _ = lax.scan(
        body, (f12.pack(one), f12.pack(one)), xs
    )
    f1 = f12.fp12_conj(f12.unpack(f1_st))
    f2 = f12.fp12_conj(f12.unpack(f2_st))
    for step, (ga, gb) in enumerate(sched_g.corr):
        f1 = f12.fp12_mul(
            f1,
            _line_eval(w_corr_a[step], w_corr_b[step], p1x12, p1y_rows, like),
        )
        f2 = f12.fp12_mul(
            f2,
            _line_eval(jnp.asarray(ga), jnp.asarray(gb), p2x12, p2y_rows, like),
        )
    return f1, f2


def _final_exp(f: f12.Rows) -> f12.Rows:
    """Bit-exact mirror of host final_exp."""
    easy = f12.fp12_mul(f12.fp12_conj(f), f12.fp12_inv(f))
    easy = f12.fp12_mul(f12.fp12_frobenius(easy, 2), easy)
    return f12.fp12_pow_const(easy, host._HARD_EXP)


def _unity_check(w_arrs, sched_g, p1x, p1y, p2x, p2y, ok):
    """The jitted core: (NLIMBS, B) stacked coords -> per-lane unity
    mask of Fexp(m1 · inv(m2))."""
    like = p1x[0]

    def tup(st):
        return tuple(st[i] for i in range(bn.NLIMBS))

    f1, f2 = _miller2(
        w_arrs, sched_g, tup(p1x), tup(p1y), tup(p2x), tup(p2y), like
    )
    m = f12.fp12_mul(f1, f12.fp12_inv(f2))
    out = _final_exp(m)
    one = f12.fp12_one(like)
    one = tuple(
        jnp.broadcast_to(l, (12,) + like.shape) for l in one
    )
    return f12.fp12_equal(out, one) & ok


# lane buckets: 8 / 16 / 64; bigger batches CHUNK over the cached
# 64-lane program instead of compiling ever-larger programs (each fresh
# bucket shape is a multi-minute TPU compile). The Miller loop is a
# fixed-length scan of lane-WIDE Fp12 ops, so widening lanes raises VPU
# utilization at near-constant step count — 64 lanes amortize the
# per-launch cost ~4-8x vs the old 8/16 buckets (VERDICT r4 #3: device
# ms/sig must beat an honest CPU column at batch >= 64).
_BUCKETS = (8, 16, 64)
_BUCKET_SMALL = _BUCKETS[0]
_BUCKET_MAX = _BUCKETS[-1]


@lru_cache(maxsize=1)
def _shared_fn():
    """THE pairing program (per lane-bucket shape, cached by jax): issuer
    schedule arrays are runtime inputs, so every issuer key shares it."""
    sched_g = _g2_schedule()

    def run(w_arrs, p1x, p1y, p2x, p2y, ok):
        return _unity_check(w_arrs, sched_g, p1x, p1y, p2x, p2y, ok)

    return jax.jit(run)


class Ate2Kernel:
    """Batched device evaluator of the Idemix pairing structure check
    for one issuer key W.  Construction costs one host schedule build
    (~1s of host Fp12 arithmetic); the compiled program is shared across
    ALL issuer keys per lane bucket."""

    def __init__(self, w: host.G2Point):
        self.sched_w = LineSchedule(w)
        self.sched_g = _g2_schedule()
        sw = self.sched_w
        # device-resident schedule inputs, shipped once per kernel
        self._w_arrs = tuple(
            jax.device_put(np.asarray(a))  # fabtrace: disable=transfer-in-loop  # one-time schedule shipping: 6 fixed arrays placed at pool construction, reused by every later launch
            for a in (
                sw.dbl_a,
                sw.dbl_b,
                sw.add_a,
                sw.add_b,
                np.stack([c[0] for c in sw.corr]),
                np.stack([c[1] for c in sw.corr]),
            )
        )
        self._fn = _shared_fn()
        self._sharded_fns = {}

    def check(
        self,
        pairs: Sequence[
            Optional[Tuple[host.G1Point, host.G1Point]]
        ],  # (A', ABar)
    ) -> List[bool]:
        n = len(pairs)
        if n == 0:
            return []
        # software pipeline across chunks: dispatch EVERY chunk's launch
        # before materializing any mask, so host Montgomery prep of
        # chunk k+1 overlaps device execution of chunk k and the
        # launches queue back-to-back on the accelerator
        dispatched = []
        # multi-chunk batches pad the tail to the SAME max-bucket shape
        # — a second bucket would mean a second multi-minute TPU compile
        # for lanes a few padded slots cover for free
        force = _BUCKET_MAX if n > _BUCKET_MAX else None
        for start in range(0, n, _BUCKET_MAX):
            chunk = pairs[start : start + _BUCKET_MAX]
            dispatched.append(
                (len(chunk), self._dispatch_chunk(chunk, force))
            )
        out: List[bool] = []
        for chunk_n, mask in dispatched:
            out.extend(bool(v) for v in np.asarray(mask)[:chunk_n])  # fabtrace: disable=transfer-in-loop  # chunk-granular drain (one materialization per _BUCKET_MAX-lane launch, not per lane) AFTER every launch is queued — the sync here is the pipeline's join point
        return out

    def check_sharded(self, pairs, mesh, axis: str = "data") -> List[bool]:
        """Lane-sharded pairing over a jax.sharding.Mesh (SURVEY P6):
        the per-lane Miller loop + final exponentiation have no cross-
        lane ops, so GSPMD splits the batch across the mesh's data axis
        — the multi-chip scale-out of the idemix verify column. Line
        schedules replicate (they are per-ISSUER, tiny next to the lane
        tensors); lanes pad to a bucket divisible by the axis size."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = len(pairs)
        if n == 0:
            return []
        ndev = mesh.shape[axis]
        bucket = next(
            (b for b in _BUCKETS if b >= n and b % ndev == 0),
            ((n + ndev - 1) // ndev) * ndev,
        )
        fn = self._sharded_fns.get((id(mesh), axis, bucket))
        if fn is None:
            sched_g = self.sched_g
            rep = NamedSharding(mesh, P())
            lane = NamedSharding(mesh, P(None, axis))  # (NLIMBS, B)
            mask = NamedSharding(mesh, P(axis))  # (B,)
            w_spec = tuple(rep for _ in self._w_arrs)

            def run(w_arrs, p1x, p1y, p2x, p2y, ok):
                return _unity_check(
                    w_arrs, sched_g, p1x, p1y, p2x, p2y, ok
                )

            fn = jax.jit(
                run,
                in_shardings=(w_spec, lane, lane, lane, lane, mask),
                out_shardings=rep,  # all-gather the per-shard verdicts
            )
            self._sharded_fns[(id(mesh), axis, bucket)] = fn
        cols = self._mont_cols(list(pairs), bucket)
        with bn.force_looped_cios():
            mask_out = fn(self._w_arrs, *cols)
        return [bool(v) for v in np.asarray(mask_out)[:n]]

    def _mont_cols(self, pairs, bucket):
        """(p1x, p1y, p2x, p2y, ok) kernel columns for `bucket` lanes."""
        gx, gy = host.G1_GEN
        cols = {"p1x": [], "p1y": [], "p2x": [], "p2y": [], "ok": []}
        for i in range(bucket):
            pair = pairs[i] if i < len(pairs) else None
            if pair is None or pair[0] is None or pair[1] is None:
                p1, p2, ok = (gx, gy), (gx, gy), False
            else:
                p1, p2, ok = pair[0], pair[1], True
            cols["p1x"].append(p1[0])
            cols["p1y"].append(p1[1])
            cols["p2x"].append(p2[0])
            cols["p2y"].append(p2[1])
            cols["ok"].append(ok)

        def mont(vals):
            return jnp.asarray(
                np.stack(
                    [f12.to_mont_int(v) for v in vals], axis=1  # fabtrace: disable=transfer-in-loop  # pairing-ingest worklist row (NOTES_BUILD PR 18): per-lane host Montgomery encode on the dispatch path — THE ingest tax the 2104.06968-style columnar refactor removes
                ).astype(np.uint32)
            )

        return (
            mont(cols["p1x"]),
            mont(cols["p1y"]),
            mont(cols["p2x"]),
            mont(cols["p2y"]),
            jnp.asarray(np.array(cols["ok"], dtype=bool)),
        )

    def _dispatch_chunk(self, pairs, force_bucket=None):
        n = len(pairs)
        bucket = force_bucket or next(b for b in _BUCKETS if n <= b)
        cols = self._mont_cols(pairs, bucket)
        with bn.force_looped_cios():
            # async dispatch: the mask materializes in check()'s drain
            return self._fn(self._w_arrs, *cols)


@lru_cache(maxsize=1)
def _g2_schedule() -> LineSchedule:
    return LineSchedule(host.G2_GEN)


@lru_cache(maxsize=8)
def kernel_for_issuer(w_bytes: bytes) -> Ate2Kernel:
    """Cached per-issuer kernel (W from its 128-byte amcl encoding)."""
    return Ate2Kernel(host.g2_from_bytes(w_bytes))


def miller2_host_values(
    w: host.G2Point, p1: host.G1Point, p2: host.G1Point
):
    """Test hook: device Miller values decoded to host ints (single
    lane), for bit-exact comparison with host.miller_loop."""
    k = Ate2Kernel(w)
    like = jnp.zeros((1,), dtype=jnp.uint32)

    def col(v):
        return tuple(
            jnp.asarray(np.full((1,), x, dtype=np.uint32))
            for x in f12.to_mont_int(v)
        )

    with bn.force_looped_cios():

        @jax.jit
        def run():
            return tuple(
                f12.pack(f)
                for f in _miller2(
                    k._w_arrs, k.sched_g,
                    col(p1[0]), col(p1[1]), col(p2[0]), col(p2[1]),
                    like,
                )
            )

        f1_st, f2_st = run()
    return (
        f12.fp12_to_host(f12.unpack(f1_st)),
        f12.fp12_to_host(f12.unpack(f2_st)),
    )
