"""Shared lazy-reduction field-element machinery for the EC kernels.

Both device curves (P-256 for ECDSA, FP256BN for Idemix) use the same
13-bit-limb Montgomery representation and the same RCB lazy-reduction
discipline; only the modulus context differs. `Field(ctx)` binds the FE
ops to one MontCtx so the bound bookkeeping, the one-hot table select
and the point pack/unpack helpers exist exactly once
(fabric_tpu/ops/{p256_kernel,bn256_kernel} instantiate it).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp

from fabric_tpu.ops import bignum as bn


class FE(NamedTuple):
    """A field element (unpacked limbs) with a static value bound
    (value < bound * p), tracked at trace time so the lazy-reduction
    rules of the RCB formulas are machine-checked."""

    limbs: tuple
    bound: int


class Point(NamedTuple):
    x: FE
    y: FE
    z: FE


class Field:
    def __init__(self, ctx: bn.MontCtx):
        self.ctx = ctx
        self.one_mont = bn.int_to_limbs((1 << bn.RADIX_BITS) % ctx.m)

    @staticmethod
    def fe(limbs: Sequence[jax.Array], bound: int = 1) -> FE:
        return FE(tuple(limbs), bound)

    def mul(self, a: FE, b: FE) -> FE:
        assert a.bound * b.bound <= 16, (a.bound, b.bound)
        return FE(tuple(bn.mont_mul_l(self.ctx, a.limbs, b.limbs, nreduce=1)), 1)

    def add(self, a: FE, b: FE) -> FE:
        assert a.bound + b.bound <= 8, (a.bound, b.bound)
        return FE(tuple(bn.add_raw_l(a.limbs, b.limbs)), a.bound + b.bound)

    def sub(self, a: FE, b: FE) -> FE:
        # a - b + bound(b)*p, then conditional subtracts back to canonical.
        return FE(
            tuple(
                bn.sub_mod_l(
                    self.ctx, a.limbs, b.limbs, b.bound,
                    nreduce=a.bound + b.bound - 1,
                )
            ),
            1,
        )

    def norm(self, a: FE) -> FE:
        if a.bound == 1:
            return a
        return FE(tuple(bn.reduce_canonical_l(self.ctx, a.limbs, a.bound - 1)), 1)

    # -- points -----------------------------------------------------------
    def identity_like(self, like: jax.Array) -> Point:
        return Point(
            FE(tuple(bn.bcast_l(bn.int_to_limbs(0), like)), 1),
            FE(tuple(bn.bcast_l(self.one_mont, like)), 1),
            FE(tuple(bn.bcast_l(bn.int_to_limbs(0), like)), 1),
        )


def pack_point(p: Point):
    return (p.x.limbs, p.y.limbs, p.z.limbs)


def unpack_point(
    c: Sequence[Sequence[jax.Array]], x_bound: int = 4
) -> Point:
    # c carries canonical 13-bit limbs (the pack_point contract fabflow
    # assumes and re-proves per kernel)
    return Point(FE(tuple(c[0]), x_bound), FE(tuple(c[1]), 1), FE(tuple(c[2]), 1))


def one_hot_select(table: jax.Array, idx: jax.Array, width: int) -> Point:
    """table (width, 3, NLIMBS, B) or (width, 3, NLIMBS); idx (B,) ->
    Point. One-hot contraction — gathers lower poorly on TPU;
    multiply-accumulate over the rows fuses."""
    oh = (
        jnp.arange(width, dtype=jnp.uint32)[:, None] == idx[None, :]
    ).astype(jnp.uint32)
    if table.ndim == 4:
        sel = (table * oh[:, None, None, :]).sum(axis=0)  # (3, NLIMBS, B)
    else:
        sel = jnp.einsum("kcl,kb->clb", table, oh)
    return Point(
        FE(tuple(sel[0, i] for i in range(bn.NLIMBS)), 1),
        FE(tuple(sel[1, i] for i in range(bn.NLIMBS)), 1),
        FE(tuple(sel[2, i] for i in range(bn.NLIMBS)), 1),
    )


def stack_point_rows(p: Point) -> jax.Array:
    """Point -> (3, NLIMBS, B) stacked array (for tables/outputs)."""
    return jnp.stack(
        [bn.restack(p.x.limbs), bn.restack(p.y.limbs), bn.restack(p.z.limbs)]
    )
