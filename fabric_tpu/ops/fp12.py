"""Device Fp2/Fp6/Fp12 tower for the FP256BN pairing (Idemix).

Mirrors the host oracle's value representation EXACTLY
(fabric_tpu/crypto/fp256bn.py): Fp2 = Fp[i]/(i^2+1); Fp12 =
Fp2[w]/(w^6 - xi) as 6 Fp2 coefficients, xi = 1 + i.  Every device
value decodes (Montgomery) to the oracle's integers — pinned by the
differential tests.

Layout (the whole point of this module): an Fp12 is a ROW-STACKED limb
tuple — NLIMBS arrays of shape (12, *batch), row order
[c0.re, c0.im, c1.re, c1.im, ...].  Tower ops act on whole row groups:
an Fp12 multiply is ONE row gather (the 108 Karatsuba operands), ONE
stacked Montgomery multiply, and a handful of vectorized fold ops —
not hundreds of per-coefficient calls.  That keeps the traced graph
small enough for the remote TPU compiler (the per-element FE version
of this module was SIGKILLed there) and maps the work onto wide
batched ops the MXU/VPU like.

Lazy-reduction bounds are static per row group and tracked by hand in
the code below (value < bound·p; limb arrays stay 13-bit canonical via
carries). bound 1 == canonical (< p).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fabric_tpu.common import fp256bn as host
from fabric_tpu.ops import bignum as bn

CTX = bn.MontCtx(host.P)
_R = 1 << bn.RADIX_BITS

# A row-stacked value: tuple of NLIMBS arrays, each (R, *batch).
Rows = Tuple[jax.Array, ...]


# ---------------------------------------------------------------------------
# row-group primitives
# ---------------------------------------------------------------------------


def to_mont_int(v: int) -> np.ndarray:
    return bn.int_to_limbs((v * _R) % host.P)


def const_rows(values: Sequence[int], like) -> Rows:
    """Host integers -> (len(values), *batch) Montgomery rows."""
    mat = np.stack([to_mont_int(v) for v in values])  # (R, NLIMBS)
    return tuple(
        jnp.broadcast_to(
            jnp.asarray(mat[:, i])[(...,) + (None,) * like.ndim],
            (mat.shape[0],) + like.shape,
        )
        for i in range(bn.NLIMBS)
    )


def rows_of(mat, like) -> Rows:
    """(R, NLIMBS) traced/const array -> broadcast Rows."""
    r = mat.shape[0]
    return tuple(
        jnp.broadcast_to(
            mat[:, i][(...,) + (None,) * like.ndim], (r,) + like.shape
        )
        for i in range(bn.NLIMBS)
    )


def rslice(x: Rows, sl) -> Rows:
    return tuple(l[sl] for l in x)


def rcat(*xs: Rows) -> Rows:
    return tuple(
        jnp.concatenate(parts, axis=0) for parts in zip(*xs)
    )


def rgather(x: Rows, idx: np.ndarray) -> Rows:
    return tuple(l[idx] for l in x)


def rcarry(x: Sequence[jax.Array]) -> Rows:
    limbs, top = bn.carry_l(list(x))
    # top carry must be zero for in-range values; it is by the bound
    # bookkeeping (values < 16p < 2^260)
    return tuple(limbs)


def radd(a: Rows, b: Rows) -> Rows:
    """a + b, limb-canonical (bounds add)."""
    return rcarry([x + y for x, y in zip(a, b)])


def rsub(a: Rows, b: Rows, b_bound: int, nreduce: int) -> Rows:
    """a - b (+ b_bound·p), reduced to canonical."""
    return tuple(bn.sub_mod_l(CTX, a, b, b_bound, nreduce=nreduce))


def rreduce(x: Rows, times: int) -> Rows:
    return tuple(bn.reduce_canonical_l(CTX, x, times))


def rmul(a: Rows, b: Rows) -> Rows:
    """Stacked Montgomery product, canonical output."""
    return tuple(bn.mont_mul_l(CTX, a, b, nreduce=1))


def rzero(r: int, like) -> Rows:
    return tuple(
        jnp.zeros((r,) + like.shape, dtype=jnp.uint32)
        for _ in range(bn.NLIMBS)
    )


def rselect(cond, a: Rows, b: Rows) -> Rows:
    """Per-lane select (cond broadcasts against (R, *batch))."""
    return tuple(jnp.where(cond, x, y) for x, y in zip(a, b))


def requal_all(a: Rows, b: Rows):
    """All rows, all limbs equal -> per-lane mask (inputs canonical)."""
    eq = None
    for x, y in zip(a, b):
        e = (x == y).all(axis=0)
        eq = e if eq is None else (eq & e)
    return eq


# ---------------------------------------------------------------------------
# Fp12 = 12 rows
# ---------------------------------------------------------------------------

# Karatsuba operand gather: x_ext rows = [12 coeff rows] + [6 sum rows]
# (sum row k = c_k.re + c_k.im). Product triple for (i, j):
#   p0 = x[2i]·y[2j], p1 = x[2i+1]·y[2j+1], p2 = xs[12+i]·ys[12+j]
_IA = np.array(
    [k for i in range(6) for j in range(6) for k in (2 * i, 2 * i + 1, 12 + i)],
    dtype=np.int32,
)
_IB = np.array(
    [k for i in range(6) for j in range(6) for k in (2 * j, 2 * j + 1, 12 + j)],
    dtype=np.int32,
)

# accumulation: Fp2 product (i,j) lands on coefficient i+j (0..10);
# pad each coefficient's term list to 6 with a zero row (index 36)
_ACC_IDX = np.full((11, 6), 36, dtype=np.int32)
for _l in range(11):
    _terms = [
        _i * 6 + _j
        for _i in range(6)
        for _j in range(6)
        if _i + _j == _l
    ]
    _ACC_IDX[_l, : len(_terms)] = _terms


def fp12_one(like) -> Rows:
    return const_rows([1] + [0] * 11, like)


def fp12_from_host(v: host.Fp12, like) -> Rows:
    vals: List[int] = []
    for c in v:
        vals.extend([c[0], c[1]])
    return const_rows(vals, like)


def _ext(v: Rows) -> Rows:
    """Append the 6 Karatsuba sum rows (c_k.re + c_k.im, bound 2)."""
    sums = rcarry([l[0::2] + l[1::2] for l in v])
    return rcat(v, sums)


def _karatsuba_fold(prods: Rows) -> Tuple[Rows, Rows]:
    """(3K, B) product triples -> (K, B) canonical (re, im) rows."""
    p0 = rslice(prods, np.s_[0::3])
    p1 = rslice(prods, np.s_[1::3])
    p2 = rslice(prods, np.s_[2::3])
    re = rsub(p0, p1, 1, 1)
    im = rsub(p2, radd(p0, p1), 2, 2)
    return re, im


def _combine(are: Rows, aim: Rows) -> Rows:
    """(11, B) canonical Fp2 accumulators -> 12-row Fp12 with the
    w^6 = xi fold: out[k] = acc[k] + xi·acc[k+6] (xi = 1+i)."""
    lo_re, hi_re = rslice(are, np.s_[:5]), rslice(are, np.s_[6:])
    lo_im, hi_im = rslice(aim, np.s_[:5]), rslice(aim, np.s_[6:])
    xi_re = rsub(hi_re, hi_im, 1, 1)
    xi_im = radd(hi_re, hi_im)  # bound 2
    out_re = rreduce(radd(lo_re, xi_re), 1)  # (5, B)
    out_im = rreduce(radd(lo_im, xi_im), 2)
    full_re = rcat(out_re, rslice(are, np.s_[5:6]))  # (6, B)
    full_im = rcat(out_im, rslice(aim, np.s_[5:6]))
    # interleave re/im rows back to [c0.re, c0.im, ...]
    return tuple(
        jnp.stack([r, i], axis=1).reshape((12,) + r.shape[1:])
        for r, i in zip(full_re, full_im)
    )


def fp12_mul(x: Rows, y: Rows) -> Rows:
    """One gather + one stacked Montgomery multiply + vectorized folds.
    x, y canonical (bound 1)."""
    lhs = rgather(_ext(x), _IA)  # (108, B); sum rows bound 2
    rhs = rgather(_ext(y), _IB)
    re, im = _karatsuba_fold(rmul(lhs, rhs))  # (36, B)

    def acc(v: Rows) -> Rows:
        ve = rcat(v, rzero(1, v[0][0]))  # zero pad row 36
        gathered = rgather(ve, _ACC_IDX)  # (11, 6, B)
        summed = rcarry([g.sum(axis=1) for g in gathered])  # bound 6
        return rreduce(summed, 5)  # canonical (11, B)

    return _combine(acc(re), acc(im))


# squaring: (i,j) and (j,i) products coincide, so only the 21 pairs
# with i <= j are multiplied (63 rows instead of 108); off-diagonal
# terms enter the accumulation doubled
_PAIRS_SQ = [(i, j) for i in range(6) for j in range(i, 6)]
_IA_SQ = np.array(
    [k for i, _ in _PAIRS_SQ for k in (2 * i, 2 * i + 1, 12 + i)],
    dtype=np.int32,
)
_IB_SQ = np.array(
    [k for _, j in _PAIRS_SQ for k in (2 * j, 2 * j + 1, 12 + j)],
    dtype=np.int32,
)
# gather into [plain (21) | doubled (21) | zero]: diagonal pairs use
# their plain row, off-diagonal pairs their doubled row
_ACC_SQ = np.full((11, 6), 42, dtype=np.int32)
for _l in range(11):
    _terms = [
        (k if i == j else 21 + k)
        for k, (i, j) in enumerate(_PAIRS_SQ)
        if i + j == _l
    ]
    _ACC_SQ[_l, : len(_terms)] = _terms


def fp12_sqr(x: Rows) -> Rows:
    xe = _ext(x)
    lhs = rgather(xe, _IA_SQ)  # (63, B)
    rhs = rgather(xe, _IB_SQ)
    re, im = _karatsuba_fold(rmul(lhs, rhs))  # (21, B)

    def acc(v: Rows) -> Rows:
        doubled = radd(v, v)  # bound 2
        ve = rcat(v, doubled, rzero(1, v[0][0]))
        gathered = rgather(ve, _ACC_SQ)  # (11, 6, B)
        summed = rcarry(
            [g.sum(axis=1) for g in gathered]
        )  # bound <= 6 (≤3 terms of bound ≤2)
        return rreduce(summed, 5)

    return _combine(acc(re), acc(im))


_NEG_ODD = np.array([2, 3, 6, 7, 10, 11])  # rows of odd-w coefficients
_IM_ROWS = np.array([1, 3, 5, 7, 9, 11])


def _negate_rows(x: Rows, rows: np.ndarray) -> Rows:
    neg = rsub(rzero(len(rows), x[0][0]), rgather(x, rows), 1, 1)
    # reassemble: gather from [original(12) | negated(len)] with a
    # static index map
    idx = np.arange(12)
    for pos, r in enumerate(rows):
        idx[r] = 12 + pos
    return rgather(rcat(x, neg), idx)


def fp12_conj(x: Rows) -> Rows:
    """Negate the odd-w coefficients (= x^(p^6))."""
    return _negate_rows(x, _NEG_ODD)


def fp12_select(cond, x: Rows, y: Rows) -> Rows:
    return rselect(cond, x, y)


def fp12_equal(x: Rows, y: Rows):
    return requal_all(x, y)


def _gamma_rows(n: int) -> np.ndarray:
    """(24, NLIMBS) rows: per coefficient k the 4 Montgomery constants
    [g_re, g_im] interleaved for the Fp2 multiply below."""
    out = []
    for k in range(6):
        g = host._FROB_GAMMA[n % 12][k]
        out.extend([g[0], g[1]])
    return np.stack([to_mont_int(v) for v in out])  # (12, NLIMBS)


def fp12_frobenius(x: Rows, n: int) -> Rows:
    """x -> x^(p^n): conjugate each Fp2 coefficient n%2 times, then
    multiply coefficient k by gamma_{n,k} (host fp12_frobenius)."""
    if n % 2 == 1:
        x = _negate_rows(x, _IM_ROWS)
    g = rows_of(jnp.asarray(_gamma_rows(n)), x[0][0])  # (12, B)
    # Fp2 mul by constants, schoolbook (4 products per coefficient):
    # re' = re·g_re − im·g_im ; im' = re·g_im + im·g_re
    re = rgather(x, np.arange(0, 12, 2))
    im = rgather(x, np.arange(1, 12, 2))
    gre = rgather(g, np.arange(0, 12, 2))
    gim = rgather(g, np.arange(1, 12, 2))
    lhs = rcat(re, im, re, im)  # (24, B)
    rhs = rcat(gre, gim, gim, gre)
    p = rmul(lhs, rhs)
    a = rslice(p, np.s_[0:6])  # re·g_re
    b = rslice(p, np.s_[6:12])  # im·g_im
    c = rslice(p, np.s_[12:18])  # re·g_im
    d = rslice(p, np.s_[18:24])  # im·g_re
    out_re = rsub(a, b, 1, 1)
    out_im = rreduce(radd(c, d), 1)
    return tuple(
        jnp.stack([r, i], axis=1).reshape((12,) + r.shape[1:])
        for r, i in zip(out_re, out_im)
    )


# ---------------------------------------------------------------------------
# Inversion (norm chain, mirrors host fp12_inv / _fp6_inv / fp2_inv)
# ---------------------------------------------------------------------------

_P_MINUS_2_BITS = np.array(
    [int(b) for b in bin(host.P - 2)[2:]], dtype=np.uint32
)


def _inv1(a: Rows) -> Rows:
    """Row-wise Fp inverse a^(p-2) (a: (R, B) canonical) via a
    square-and-multiply scan over the fixed exponent bits."""
    from jax import lax

    one = const_rows([1], a[0][0])
    one = tuple(jnp.broadcast_to(l, a[0].shape) for l in one)

    def body(carry, bit):
        o = tuple(carry)
        o2 = rmul(o, o)
        o2a = rmul(o2, a)
        nxt = rselect(bit.astype(bool), o2a, o2)
        return tuple(nxt), None

    carry, _ = lax.scan(body, one, jnp.asarray(_P_MINUS_2_BITS))
    return tuple(carry)


def _fp2_mul_rows(x: Rows, y: Rows) -> Rows:
    """K parallel Fp2 products: x, y are (2K, B) rows [re, im]...,
    schoolbook 4-product form."""
    re_x = rslice(x, np.s_[0::2])
    im_x = rslice(x, np.s_[1::2])
    re_y = rslice(y, np.s_[0::2])
    im_y = rslice(y, np.s_[1::2])
    p = rmul(
        rcat(re_x, im_x, re_x, im_x), rcat(re_y, im_y, im_y, re_y)
    )
    k = x[0].shape[0] // 2
    a = rslice(p, np.s_[0 * k : 1 * k])
    b = rslice(p, np.s_[1 * k : 2 * k])
    c = rslice(p, np.s_[2 * k : 3 * k])
    d = rslice(p, np.s_[3 * k : 4 * k])
    out_re = rsub(a, b, 1, 1)
    out_im = rreduce(radd(c, d), 1)
    return tuple(
        jnp.stack([r, i], axis=1).reshape((2 * k,) + r.shape[1:])
        for r, i in zip(out_re, out_im)
    )


def _fp2_mul_xi(x: Rows) -> Rows:
    """K parallel multiplies by xi = 1+i: (re−im, re+im)."""
    re = rslice(x, np.s_[0::2])
    im = rslice(x, np.s_[1::2])
    out_re = rsub(re, im, 1, 1)
    out_im = rreduce(radd(re, im), 1)
    k = x[0].shape[0] // 2
    return tuple(
        jnp.stack([r, i], axis=1).reshape((2 * k,) + r.shape[1:])
        for r, i in zip(out_re, out_im)
    )


def _fp2_inv_rows(x: Rows) -> Rows:
    """One Fp2 inverse (x: (2, B)): conj(x) / (re² + im²)."""
    sq = rmul(x, x)  # re², im²
    norm = rreduce(rcarry([l[0:1] + l[1:2] for l in sq]), 1)  # (1,B)
    ninv = _inv1(norm)
    re = rslice(x, np.s_[0:1])
    im_neg = rsub(rzero(1, x[0][0]), rslice(x, np.s_[1:2]), 1, 1)
    return rmul(rcat(re, im_neg), rcat(ninv, ninv))


def fp12_inv(x: Rows) -> Rows:
    """conj(x)·(x·conj(x))^{-1}; x·conj(x) lives in the even
    subalgebra -> one Fp6 inverse -> one Fp2 inverse -> one Fp inverse
    (host fp12_inv / _fp6_inv)."""
    xc = fp12_conj(x)
    ac = fp12_mul(x, xc)
    # Fp6 over v = w²: a = (ac[0], ac[2], ac[4]) as Fp2 rows
    a0 = rgather(ac, np.array([0, 1]))
    a1 = rgather(ac, np.array([4, 5]))
    a2 = rgather(ac, np.array([8, 9]))
    # c0 = a0² − xi·a1·a2 ; c1 = xi·a2² − a0·a1 ; c2 = a1² − a0·a2
    sq = _fp2_mul_rows(rcat(a0, a2, a1), rcat(a0, a2, a1))
    a0sq = rslice(sq, np.s_[0:2])
    a2sq = rslice(sq, np.s_[2:4])
    a1sq = rslice(sq, np.s_[4:6])
    cross = _fp2_mul_rows(rcat(a1, a0, a0), rcat(a2, a1, a2))
    a1a2 = rslice(cross, np.s_[0:2])
    a0a1 = rslice(cross, np.s_[2:4])
    a0a2 = rslice(cross, np.s_[4:6])
    c0 = rsub(a0sq, _fp2_mul_xi(a1a2), 1, 1)
    c1 = rsub(_fp2_mul_xi(a2sq), a0a1, 1, 1)
    c2 = rsub(a1sq, a0a2, 1, 1)
    # t = xi·(a2·c1 + a1·c2) + a0·c0
    tc = _fp2_mul_rows(rcat(a2, a1, a0), rcat(c1, c2, c0))
    s = rreduce(
        rcarry([l[0:2] + l[2:4] for l in tc]), 1
    )  # a2c1 + a1c2
    t = rreduce(
        radd(_fp2_mul_xi(s), rslice(tc, np.s_[4:6])), 1
    )
    ti = _fp2_inv_rows(t)
    inv6 = _fp2_mul_rows(
        rcat(c0, c1, c2), rcat(ti, ti, ti)
    )  # (6, B)
    # inv12 = (inv6[0], 0, inv6[1], 0, inv6[2], 0) over w²-coefficients
    z2 = rzero(2, x[0][0])
    inv12 = rcat(
        rslice(inv6, np.s_[0:2]), z2,
        rslice(inv6, np.s_[2:4]), z2,
        rslice(inv6, np.s_[4:6]), z2,
    )
    return fp12_mul(xc, inv12)


# ---------------------------------------------------------------------------
# Fixed-exponent power (final-exponentiation hard part)
# ---------------------------------------------------------------------------


def fp12_pow_const(x: Rows, e: int) -> Rows:
    """x^e, MSB-first square-and-multiply scan (host fp12_pow order)."""
    from jax import lax

    assert e > 0
    bits = jnp.asarray(
        np.array([int(b) for b in bin(e)[2:]], dtype=np.uint32)
    )
    one = fp12_one(x[0][0])
    one = tuple(jnp.broadcast_to(l, x[0].shape) for l in one)

    def body(carry, bit):
        o = tuple(carry)
        o2 = fp12_sqr(o)
        o2x = fp12_mul(o2, x)
        nxt = rselect(bit.astype(bool), o2x, o2)
        return tuple(nxt), None

    carry, _ = lax.scan(body, one, bits)
    return tuple(carry)


# ---------------------------------------------------------------------------
# host <-> device conversion (tests / kernel boundaries)
# ---------------------------------------------------------------------------


def pack(x: Rows) -> jax.Array:
    """Rows -> one (NLIMBS, R, *batch) array (scan carries, transport)."""
    return jnp.stack(list(x))


def unpack(a: jax.Array) -> Rows:
    return tuple(a[i] for i in range(bn.NLIMBS))


def fp12_to_host(x: Rows) -> host.Fp12:
    """Decode lane 0 to host integers (differential tests)."""
    std = tuple(bn.from_mont_l(CTX, x))
    std = tuple(bn.reduce_canonical_l(CTX, std, 1))
    mat = np.stack([np.asarray(l) for l in std])  # (NLIMBS, 12, ...)
    vals = []
    for r in range(12):
        v = 0
        for i in reversed(range(bn.NLIMBS)):
            v = (v << bn.LIMB_BITS) | int(mat[i, r].reshape(-1)[0])
        vals.append(v % host.P)
    return tuple(
        (vals[2 * k], vals[2 * k + 1]) for k in range(6)
    )
