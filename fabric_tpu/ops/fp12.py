"""Device Fp2/Fp6/Fp12 tower for the FP256BN pairing (Idemix).

Mirrors the host oracle's representation EXACTLY
(fabric_tpu/crypto/fp256bn.py): Fp2 = Fp[i]/(i^2+1) as (re, im);
Fp12 = Fp2[w]/(w^6 - xi) as 6 Fp2 coefficients, xi = 1 + i.  Every
device value is bit-comparable to the oracle after Montgomery decode,
which is what the differential tests pin.

The trace/compile discipline (the whole reason this module exists
instead of naive per-Fp mont_mul calls): every tower operation gathers
ALL of its independent Fp products and runs them as ONE stacked
`mont_mul_l` over a (K, *batch) axis — an Fp12 multiply is one 108-lane
Montgomery multiply, not 108 sequential ones.  Keep that invariant when
extending: one mont_mul_l per tower op.

Elements are FE tuples (fabric_tpu.ops.fieldops) in Montgomery form
with tracked lazy-reduction bounds; batch shape is uniform across all
limbs (constants are broadcast on entry).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from fabric_tpu.crypto import fp256bn as host
from fabric_tpu.ops import bignum as bn
from fabric_tpu.ops.fieldops import FE

CTX = bn.MontCtx(host.P)
_R = 1 << bn.RADIX_BITS

Fp2 = Tuple[FE, FE]
Fp12 = Tuple[Fp2, Fp2, Fp2, Fp2, Fp2, Fp2]


# ---------------------------------------------------------------------------
# Fp helpers (stacked-multiply core)
# ---------------------------------------------------------------------------


def to_mont_int(v: int) -> np.ndarray:
    return bn.int_to_limbs((v * _R) % host.P)


def fe_const(v: int, like) -> FE:
    """Host integer -> broadcast Montgomery FE."""
    return FE(tuple(bn.bcast_l(to_mont_int(v), like)), 1)


def fe_zero(like) -> FE:
    return FE(tuple(bn.bcast_l(bn.int_to_limbs(0), like)), 1)


def mul_many(pairs: Sequence[Tuple[FE, FE]]) -> List[FE]:
    """K independent Fp products in ONE Montgomery multiply."""
    if not pairs:
        return []
    for a, b in pairs:
        assert a.bound * b.bound <= 16, (a.bound, b.bound)
    a_st = tuple(
        jnp.stack([p[0].limbs[i] for p in pairs]) for i in range(bn.NLIMBS)
    )
    b_st = tuple(
        jnp.stack([p[1].limbs[i] for p in pairs]) for i in range(bn.NLIMBS)
    )
    out = bn.mont_mul_l(CTX, a_st, b_st, nreduce=1)
    return [
        FE(tuple(out[i][k] for i in range(bn.NLIMBS)), 1)
        for k in range(len(pairs))
    ]


def fe_add(a: FE, b: FE) -> FE:
    assert a.bound + b.bound <= 8, (a.bound, b.bound)
    return FE(tuple(bn.add_raw_l(a.limbs, b.limbs)), a.bound + b.bound)


def fe_sub(a: FE, b: FE) -> FE:
    return FE(
        tuple(
            bn.sub_mod_l(CTX, a.limbs, b.limbs, b.bound, nreduce=a.bound + b.bound - 1)
        ),
        1,
    )


def fe_norm(a: FE) -> FE:
    if a.bound == 1:
        return a
    return FE(tuple(bn.reduce_canonical_l(CTX, a.limbs, a.bound - 1)), 1)


def fe_neg(a: FE, like) -> FE:
    return fe_sub(fe_zero(like), a)


def fe_select(cond, a: FE, b: FE) -> FE:
    """Per-lane select between two canonical FEs."""
    a, b = fe_norm(a), fe_norm(b)
    return FE(
        tuple(jnp.where(cond, x, y) for x, y in zip(a.limbs, b.limbs)), 1
    )


def fe_equal(a: FE, b: FE):
    """Canonical equality mask. Inputs are reduced to the unique
    representative (< p) before comparison."""
    a = FE(tuple(bn.reduce_canonical_l(CTX, fe_norm(a).limbs, 1)), 1)
    b = FE(tuple(bn.reduce_canonical_l(CTX, fe_norm(b).limbs, 1)), 1)
    eq = None
    for x, y in zip(a.limbs, b.limbs):
        e = x == y
        eq = e if eq is None else (eq & e)
    return eq


# ---------------------------------------------------------------------------
# Fp2 (operand collection: most Fp2 ops defer their products to the
# caller's stacked multiply via *_pairs/*_fold helpers)
# ---------------------------------------------------------------------------


def fp2_add(x: Fp2, y: Fp2) -> Fp2:
    return (fe_add(x[0], y[0]), fe_add(x[1], y[1]))


def fp2_sub(x: Fp2, y: Fp2) -> Fp2:
    return (fe_sub(x[0], y[0]), fe_sub(x[1], y[1]))


def fp2_neg(x: Fp2, like) -> Fp2:
    return (fe_neg(x[0], like), fe_neg(x[1], like))


def fp2_norm(x: Fp2) -> Fp2:
    return (fe_norm(x[0]), fe_norm(x[1]))


def fp2_mul_xi(x: Fp2) -> Fp2:
    """x * (1 + i) = (re - im) + (re + im) i."""
    re, im = x
    return (fe_sub(re, im), fe_norm(fe_add(re, im)))


def _karatsuba_pairs(x: Fp2, y: Fp2):
    """The 3 Fp products of one Fp2 multiply (Karatsuba)."""
    return [
        (x[0], y[0]),
        (x[1], y[1]),
        (fe_norm(fe_add(x[0], x[1])), fe_norm(fe_add(y[0], y[1]))),
    ]


def _karatsuba_fold(p0: FE, p1: FE, p2: FE) -> Fp2:
    """(re, im) from the 3 products: re = p0 - p1, im = p2 - p0 - p1."""
    return (fe_sub(p0, p1), fe_sub(fe_sub(p2, p0), p1))


def fp2_mul(x: Fp2, y: Fp2) -> Fp2:
    out = mul_many(_karatsuba_pairs(x, y))
    return _karatsuba_fold(*out)


def fp2_conj(x: Fp2, like) -> Fp2:
    return (x[0], fe_neg(x[1], like))


def fp2_select(cond, x: Fp2, y: Fp2) -> Fp2:
    return (fe_select(cond, x[0], y[0]), fe_select(cond, x[1], y[1]))


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------


def fp12_zero(like) -> Fp12:
    z = (fe_zero(like), fe_zero(like))
    return (z,) * 6


def fp12_one(like) -> Fp12:
    one = (fe_const(1, like), fe_zero(like))
    z = (fe_zero(like), fe_zero(like))
    return (one, z, z, z, z, z)


def fp12_from_host(v: host.Fp12, like) -> Fp12:
    return tuple(
        (fe_const(c[0], like), fe_const(c[1], like)) for c in v
    )


def fp12_add(x: Fp12, y: Fp12) -> Fp12:
    return tuple(fp2_add(a, b) for a, b in zip(x, y))


def fp12_norm(x: Fp12) -> Fp12:
    return tuple(fp2_norm(c) for c in x)


def fp12_conj(x: Fp12, like) -> Fp12:
    return (
        x[0],
        fp2_neg(x[1], like),
        x[2],
        fp2_neg(x[3], like),
        x[4],
        fp2_neg(x[5], like),
    )


def fp12_select(cond, x: Fp12, y: Fp12) -> Fp12:
    return tuple(fp2_select(cond, a, b) for a, b in zip(x, y))


def fp12_mul(x: Fp12, y: Fp12) -> Fp12:
    """Schoolbook 6x6 over Fp2 with the w^6 = xi fold — 36 Fp2 products
    = 108 Fp products in ONE stacked multiply (mirrors host fp12_mul's
    accumulation order so values match bit-for-bit)."""
    pairs = []
    for i in range(6):
        for j in range(6):
            pairs.extend(_karatsuba_pairs(x[i], y[j]))
    prods = mul_many(pairs)
    acc: List = [None] * 11
    k = 0
    for i in range(6):
        for j in range(6):
            p = _karatsuba_fold(prods[k], prods[k + 1], prods[k + 2])
            k += 3
            idx = i + j
            acc[idx] = p if acc[idx] is None else fp2_add(acc[idx], p)
    out = []
    for k in range(6):
        c = acc[k]
        if k + 6 <= 10 and acc[k + 6] is not None:
            c = fp2_add(c, fp2_mul_xi(fp2_norm(acc[k + 6])))
        out.append(fp2_norm(c))
    return tuple(out)


def fp12_sqr(x: Fp12) -> Fp12:
    return fp12_mul(x, x)


# frobenius constants (host _FROB_GAMMA), Montgomery-encoded lazily
def _frob_gamma(n: int):
    return host._FROB_GAMMA[n % 12]


def fp12_frobenius(x: Fp12, n: int, like) -> Fp12:
    """Mirrors host fp12_frobenius: conjugate n%2 times, then multiply
    coefficient k by gamma_{n,k}."""
    gammas = _frob_gamma(n)
    coeffs = []
    pairs = []
    for k in range(6):
        c = x[k]
        if n % 2 == 1:
            c = fp2_conj(c, like)
        g = (fe_const(gammas[k][0], like), fe_const(gammas[k][1], like))
        pairs.extend(_karatsuba_pairs(c, g))
        coeffs.append(None)
    prods = mul_many(pairs)
    out = []
    for k in range(6):
        out.append(_karatsuba_fold(*prods[3 * k : 3 * k + 3]))
    return tuple(out)


# ---------------------------------------------------------------------------
# Inversion (norm chain, mirrors host fp12_inv/_fp6_inv/fp2_inv)
# ---------------------------------------------------------------------------

_P_MINUS_2_BITS = np.array(
    [int(b) for b in bin(host.P - 2)[2:]], dtype=np.uint32
)


def fe_inv(a: FE, like) -> FE:
    """a^(p-2) by square-and-multiply over the fixed exponent bits
    (lax.scan; MSB-first like the host's pow)."""
    from jax import lax

    a = fe_norm(a)
    out = fe_const(1, like)

    a_st = bn.restack(list(a.limbs))

    def body(carry, bit):
        o = FE(tuple(carry), 1)
        o2 = mul_many([(o, o)])[0]
        a_fe = FE(tuple(a_st[i] for i in range(bn.NLIMBS)), 1)
        o2a = mul_many([(o2, a_fe)])[0]
        nxt = fe_select(bit.astype(bool), o2a, o2)
        return tuple(nxt.limbs), None

    bits = jnp.asarray(_P_MINUS_2_BITS)
    carry, _ = lax.scan(body, tuple(out.limbs), bits)
    return FE(tuple(carry), 1)


def fp2_inv(x: Fp2, like) -> Fp2:
    """conj(x) / (re^2 + im^2)."""
    p = mul_many([(x[0], x[0]), (x[1], x[1])])
    norm = fe_norm(fe_add(p[0], p[1]))
    ninv = fe_inv(norm, like)
    out = mul_many([(x[0], ninv), (fe_neg(x[1], like), ninv)])
    return (out[0], out[1])


def _fp6_mul(x, y) -> Tuple[Fp2, Fp2, Fp2]:
    """Mirror of host _fp6_mul over v = w^2 (v^3 = xi)."""
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0 = fp2_mul(a0, b0)
    t1 = fp2_add(fp2_mul(a0, b1), fp2_mul(a1, b0))
    t2 = fp2_add(
        fp2_add(fp2_mul(a0, b2), fp2_mul(a1, b1)), fp2_mul(a2, b0)
    )
    t3 = fp2_add(fp2_mul(a1, b2), fp2_mul(a2, b1))
    t4 = fp2_mul(a2, b2)
    return (
        fp2_norm(fp2_add(t0, fp2_mul_xi(fp2_norm(t3)))),
        fp2_norm(fp2_add(t1, fp2_mul_xi(t4))),
        fp2_norm(t2),
    )


def _fp6_inv(x, like) -> Tuple[Fp2, Fp2, Fp2]:
    a0, a1, a2 = x
    c0 = fp2_sub(fp2_mul(a0, a0), fp2_mul_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul_xi(fp2_mul(a2, a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_mul(a1, a1), fp2_mul(a0, a2))
    t = fp2_add(
        fp2_mul_xi(
            fp2_norm(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2)))
        ),
        fp2_mul(a0, c0),
    )
    ti = fp2_inv(fp2_norm(t), like)
    return (fp2_mul(c0, ti), fp2_mul(c1, ti), fp2_mul(c2, ti))


def fp12_inv(x: Fp12, like) -> Fp12:
    """conj(x) * (x * conj(x))^{-1}, x*conj(x) living in the even
    subalgebra (host fp12_inv)."""
    xc = fp12_conj(x, like)
    ac = fp12_mul(x, xc)
    inv6 = _fp6_inv((ac[0], ac[2], ac[4]), like)
    z = (fe_zero(like), fe_zero(like))
    inv12: Fp12 = (inv6[0], z, inv6[1], z, inv6[2], z)
    return fp12_mul(xc, inv12)


# ---------------------------------------------------------------------------
# Fixed-exponent power (final-exponentiation hard part)
# ---------------------------------------------------------------------------


def _stack12(x: Fp12) -> jnp.ndarray:
    """(12, NLIMBS, *batch) canonical stack for scan carries."""
    rows = []
    for c in x:
        rows.append(bn.restack(list(fe_norm(c[0]).limbs)))
        rows.append(bn.restack(list(fe_norm(c[1]).limbs)))
    return jnp.stack(rows)


def _unstack12(a) -> Fp12:
    out = []
    for k in range(6):
        re = FE(tuple(a[2 * k][i] for i in range(bn.NLIMBS)), 1)
        im = FE(tuple(a[2 * k + 1][i] for i in range(bn.NLIMBS)), 1)
        out.append((re, im))
    return tuple(out)


def fp12_pow_const(x: Fp12, e: int, like) -> Fp12:
    """x^e for a compile-time exponent, MSB-first square-and-multiply in
    a lax.scan (bit-exact mirror of host fp12_pow)."""
    from jax import lax

    assert e > 0
    bits = jnp.asarray(
        np.array([int(b) for b in bin(e)[2:]], dtype=np.uint32)
    )
    x_st = _stack12(x)

    def body(carry, bit):
        o = _unstack12(carry)
        o2 = fp12_sqr(o)
        o2x = fp12_mul(o2, _unstack12(x_st))
        nxt = fp12_select(bit.astype(bool), o2x, o2)
        return _stack12(nxt), None

    carry, _ = lax.scan(body, _stack12(fp12_one(like)), bits)
    return _unstack12(carry)


def fp12_equal(x: Fp12, y: Fp12):
    eq = None
    for cx, cy in zip(x, y):
        for fx, fy in zip(cx, cy):
            e = fe_equal(fx, fy)
            eq = e if eq is None else (eq & e)
    return eq


def fp12_to_host(x: Fp12) -> host.Fp12:
    """Device -> host value (decodes Montgomery form; for tests)."""
    out = []
    for c in x:
        pair = []
        for f in c:
            limbs = bn.from_mont_l(CTX, fe_norm(f).limbs)
            limbs = bn.reduce_canonical_l(CTX, limbs, 1)
            v = 0
            for i in reversed(range(bn.NLIMBS)):
                v = (v << bn.LIMB_BITS) | int(np.asarray(limbs[i]).reshape(-1)[0])
            pair.append(v % host.P)
        out.append((pair[0], pair[1]))
    return tuple(out)
