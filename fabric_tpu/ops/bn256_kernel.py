"""Batched G1 arithmetic on the FP256BN pairing curve (the Idemix curve)
— the device half of SURVEY.md §7 Stage 5.

Reference semantics: fabric-amcl's FP256BN G1 (idemix/signature.go Ver
recomputes t1/t2/t3 via ~10 G1 scalar muls per signature). This kernel
evaluates batched multi-scalar multiplications Σ_k e_k·B_k with complete
a=0 projective formulas (Renes–Costello–Batina 2016, algorithms 7 and 9;
FP256BN has a=0, b=3), vmapped over the signature lanes, reusing the
13-bit-limb Montgomery machinery from fabric_tpu.ops.bignum with the BN
base-field modulus.

The pairing itself (Miller loop + final exponentiation in Fp12) stays on
the host oracle (fabric_tpu.crypto.fp256bn) for now; this kernel removes
the G1 multi-exponentiation bulk of Signature.Ver.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fabric_tpu.common import fp256bn as host
from fabric_tpu.ops import bignum as bn
from fabric_tpu.ops import fieldops as fo

CTX_Q = bn.MontCtx(host.P)

_R = 1 << bn.RADIX_BITS
B3_MONT = bn.int_to_limbs((3 * host.B_COEFF * _R) % host.P)
ONE_MONT_Q = bn.int_to_limbs(_R % host.P)

WINDOW_BITS = 2
NUM_WINDOWS = 128  # 256 bits / 2


# Shared lazy-reduction machinery bound to the BN base-field modulus.
FIELD = fo.Field(CTX_Q)
FE = fo.FE
fe = fo.Field.fe
fe_mul = FIELD.mul
fe_add = FIELD.add
fe_sub = FIELD.sub
fe_norm = FIELD.norm
Point = fo.Point
point_identity_like = FIELD.identity_like

_B3_FE = FE(bn.const_l(B3_MONT), 1)


def point_add(p: Point, q: Point) -> Point:
    """Complete addition, RCB 2016 algorithm 7 (a = 0)."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    b3 = _B3_FE

    t0 = fe_mul(x1, x2)
    t1 = fe_mul(y1, y2)
    t2 = fe_mul(z1, z2)
    t3 = fe_add(x1, y1)
    t4 = fe_add(x2, y2)
    t3 = fe_mul(t3, t4)
    t4 = fe_add(t0, t1)
    t3 = fe_sub(t3, t4)
    t4 = fe_add(y1, z1)
    x3 = fe_add(y2, z2)
    t4 = fe_mul(t4, x3)
    x3 = fe_add(t1, t2)
    t4 = fe_sub(t4, x3)
    x3 = fe_add(x1, z1)
    y3 = fe_add(x2, z2)
    x3 = fe_mul(x3, y3)
    y3 = fe_add(t0, t2)
    y3 = fe_sub(x3, y3)
    x3 = fe_add(t0, t0)
    t0 = fe_add(x3, t0)  # bound 3
    t2 = fe_mul(b3, t2)
    z3 = fe_add(t1, t2)  # bound 2
    t1 = fe_sub(t1, t2)
    y3 = fe_mul(b3, y3)
    x3 = fe_mul(t4, y3)
    t2 = fe_mul(t3, t1)
    x3 = fe_sub(t2, x3)
    y3 = fe_mul(y3, fe_norm(t0))
    t1 = fe_mul(t1, fe_norm(z3))
    y3 = fe_add(t1, y3)
    t0 = fe_mul(fe_norm(t0), t3)
    z3 = fe_mul(fe_norm(z3), t4)
    z3 = fe_add(z3, t0)  # bound 2
    return Point(x3, fe_norm(y3), fe_norm(z3))


def point_double(p: Point) -> Point:
    """Complete doubling, RCB 2016 algorithm 9 (a = 0)."""
    x, y, z = p
    b3 = _B3_FE

    t0 = fe_mul(y, y)
    z3 = fe_add(t0, t0)
    z3 = fe_add(z3, z3)
    z3 = fe_add(z3, z3)  # bound 8
    z3 = fe_norm(z3)
    t1 = fe_mul(y, z)
    t2 = fe_mul(z, z)
    t2 = fe_mul(b3, t2)
    x3 = fe_mul(t2, z3)
    y3 = fe_add(t0, t2)
    z3 = fe_mul(t1, z3)
    t1 = fe_add(t2, t2)
    t2 = fe_add(t1, t2)  # bound 3
    t0 = fe_sub(t0, t2)
    y3 = fe_mul(t0, fe_norm(y3))
    y3 = fe_add(x3, y3)
    t1 = fe_mul(x, y)
    x3 = fe_mul(t0, t1)
    x3 = fe_add(x3, x3)
    return Point(fe_norm(x3), fe_norm(y3), z3)


_pack = fo.pack_point


def _unpack(c: Sequence[Sequence[jax.Array]], bound: int = 4) -> Point:
    return Point(
        fe_norm(FE(tuple(c[0]), bound)), fe(c[1]), fe(c[2])
    )


# ---------------------------------------------------------------------------
# Host <-> device packing
# ---------------------------------------------------------------------------


def to_mont_int(v: int) -> int:
    return (v * _R) % host.P


def pack_points(pts: Sequence[host.G1Point]) -> np.ndarray:
    """Affine host points (or None = identity) -> (3, NLIMBS, B) uint32
    Montgomery projective."""
    xs, ys, zs = [], [], []
    for pt in pts:
        if pt is None:
            xs.append(0)
            ys.append(to_mont_int(1))  # fabtrace: disable=transfer-in-loop  # MSM point-ingest worklist row (NOTES_BUILD PR 18): per-point host Montgomery encode pending a columnar pack over the whole batch
            zs.append(0)
        else:
            xs.append(to_mont_int(pt[0]))  # fabtrace: disable=transfer-in-loop  # MSM point-ingest worklist row (NOTES_BUILD PR 18): per-point host Montgomery encode pending a columnar pack over the whole batch
            ys.append(to_mont_int(pt[1]))  # fabtrace: disable=transfer-in-loop  # MSM point-ingest worklist row (NOTES_BUILD PR 18): per-point host Montgomery encode pending a columnar pack over the whole batch
            zs.append(to_mont_int(1))  # fabtrace: disable=transfer-in-loop  # MSM point-ingest worklist row (NOTES_BUILD PR 18): per-point host Montgomery encode pending a columnar pack over the whole batch
    return np.stack(
        [bn.ints_to_limbs(xs), bn.ints_to_limbs(ys), bn.ints_to_limbs(zs)]
    )


def unpack_points(arr: np.ndarray):
    """(3, NLIMBS, B) device output -> list of affine host points/None."""
    arr = np.asarray(arr)
    xs = bn.limbs_to_ints(
        np.asarray(bn.from_mont(CTX_Q, jnp.asarray(arr[0])))
    )
    ys = bn.limbs_to_ints(
        np.asarray(bn.from_mont(CTX_Q, jnp.asarray(arr[1])))
    )
    zs = bn.limbs_to_ints(
        np.asarray(bn.from_mont(CTX_Q, jnp.asarray(arr[2])))
    )
    out = []
    for x, y, z in zip(xs, ys, zs):
        if z == 0:
            out.append(None)
        else:
            zi = pow(z, -1, host.P)
            out.append(((x * zi) % host.P, (y * zi) % host.P))
    return out


# ---------------------------------------------------------------------------
# Batched multi-scalar multiplication
# ---------------------------------------------------------------------------


def scalar_digits_msb(scalars: jax.Array) -> jax.Array:
    """(NLIMBS, B) limb scalars -> (NUM_WINDOWS, B) 2-bit digits MSB-first."""
    digits = []
    for w in range(NUM_WINDOWS):
        bit = 256 - WINDOW_BITS * (w + 1)
        limb, off = divmod(bit, bn.LIMB_BITS)
        # a 2-bit window never straddles >2 limbs with 13-bit limbs
        lo = scalars[limb] >> np.uint32(off)
        if off + WINDOW_BITS > bn.LIMB_BITS and limb + 1 < bn.NLIMBS:
            hi = scalars[limb + 1] << np.uint32(bn.LIMB_BITS - off)
            lo = lo | hi
        digits.append(lo & np.uint32((1 << WINDOW_BITS) - 1))
    return jnp.stack(digits)


def msm_batch_device(bases: jax.Array, scalars: jax.Array) -> tuple:
    """bases (K, 3, NLIMBS, B) Montgomery projective; scalars
    (K, NLIMBS, B) plain integers < R. Returns packed (3, NLIMBS, B)
    accumulator Σ_k scalars[k]·bases[k] per lane."""
    k_count, _, _, lanes = bases.shape
    lanes_like = bases[0, 0, 0]

    # per-base tables {identity, B, 2B, 3B} built as ONE flattened
    # (K*B)-lane batch (a vmapped build compiles far slower):
    flat = jnp.moveaxis(bases, 0, 2).reshape(3, bn.NLIMBS, k_count * lanes)
    p1 = Point(fe(bn.split(flat[0])), fe(bn.split(flat[1])), fe(bn.split(flat[2])))
    p2 = point_double(p1)
    p3 = point_add(p2, p1)
    ident = point_identity_like(flat[0, 0])
    rows = []
    for pt in (ident, p1, p2, p3):
        rows.append(
            jnp.stack(
                [
                    bn.restack(pt.x.limbs),
                    bn.restack(fe_norm(pt.y).limbs),
                    bn.restack(fe_norm(pt.z).limbs),
                ]
            )
        )
    # (4, 3, NLIMBS, K*B) -> (K, 4, 3, NLIMBS, B)
    tables = jnp.moveaxis(
        jnp.stack(rows).reshape(4, 3, bn.NLIMBS, k_count, lanes), 3, 0
    )
    flat_scalars = jnp.moveaxis(scalars, 0, 1).reshape(
        bn.NLIMBS, k_count * lanes
    )
    digits = scalar_digits_msb(flat_scalars).reshape(
        NUM_WINDOWS, k_count, lanes
    )

    def select(table, idx):
        return fo.one_hot_select(table, idx, 4)

    def window_body(carry, window_digits):
        acc = _unpack(carry)
        for _ in range(WINDOW_BITS):
            acc = point_double(acc)

        def base_body(j, packed):
            a = _unpack(packed)
            a = point_add(a, select(tables[j], window_digits[j]))
            return _pack(a)

        packed = lax.fori_loop(0, k_count, base_body, _pack(acc))
        return packed, None

    carry, _ = lax.scan(
        window_body, _pack(point_identity_like(lanes_like)), digits
    )
    final = _unpack(carry)
    return (
        jnp.stack([bn.restack(final.x.limbs), bn.restack(fe_norm(final.y).limbs), bn.restack(fe_norm(final.z).limbs)])
    )


msm_batch_jit = jax.jit(msm_batch_device)


def msm_host_batch(
    bases_per_lane: Sequence[Sequence], scalars_per_lane: Sequence[Sequence[int]]
) -> list:
    """Convenience host API: per-lane lists of (affine point | None) bases
    and int scalars, all lanes with the same K. Returns affine points."""
    b_count = len(bases_per_lane)
    k_count = len(bases_per_lane[0])
    bases = np.stack(
        [
            pack_points([bases_per_lane[i][k] for i in range(b_count)])  # fabtrace: disable=transfer-in-loop  # rides the pack_points MSM ingest worklist row: the per-K-column loop vectorizes together with the point encode it wraps
            for k in range(k_count)
        ]
    )
    scalars = np.stack(
        [
            bn.ints_to_limbs(
                [scalars_per_lane[i][k] % host.R for i in range(b_count)]
            )
            for k in range(k_count)
        ]
    )
    out = msm_batch_jit(jnp.asarray(bases), jnp.asarray(scalars))
    return unpack_points(out)
