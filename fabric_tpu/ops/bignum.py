"""Multi-limb modular arithmetic for JAX/TPU.

Replaces the Go-stdlib constant-time P-256 assembly the reference leans on
(SURVEY.md §2.12: crypto/elliptic P-256 under bccsp/sw) with batched,
compiler-friendly integer math. Design notes:

- **Radix 2^13, 20 limbs** (260 bits for 256-bit fields). 13-bit limbs make
  products fit comfortably in 32 bits (26-bit products), so a full CIOS
  Montgomery multiplication can run with *lazy carries* entirely in uint32:
  each of the 20 outer iterations adds two <2^27 products per limb, for a
  worst-case accumulator below 20 * 2^27 * (1 + eps) < 2^32.
- **Limb-major layout `(NLIMBS, *batch)`**: the batch dimension is the
  trailing (lane) dimension on the TPU VPU, carry chains walk the leading
  axis via `lax.scan`, and no transposes appear in the inner loop.
- **No constant-time requirement**: verification consumes public data
  (signatures, public keys, digests), so we freely use data-dependent
  selects — but never data-dependent *shapes* or control flow, keeping
  everything one fixed XLA program.

Values "at rest" are canonical: every limb < 2^13 and the value < modulus
unless a caller explicitly tracks a laxer bound (see fabric_tpu.ops.
p256_kernel.FE). Host-side conversions use Python ints (arbitrary
precision) and numpy.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

LIMB_BITS = 13
NLIMBS = 20
LIMB_MASK = (1 << LIMB_BITS) - 1
RADIX_BITS = LIMB_BITS * NLIMBS  # 260

# Fully unroll the 20-iteration CIOS outer loop at trace time. Costs trace
# size (and thus XLA compile time), removes per-limb loop overhead at run
# time. Defaults on; tests on the CPU backend export
# FABRIC_TPU_CIOS_UNROLL=0 where compile time dominates.
import os as _os

CIOS_UNROLL = _os.environ.get("FABRIC_TPU_CIOS_UNROLL", "1") != "0"


# ---------------------------------------------------------------------------
# Host conversions
# ---------------------------------------------------------------------------


def int_to_limbs(x: int, nlimbs: int = NLIMBS) -> np.ndarray:
    """Python int -> little-endian 13-bit limbs, shape (nlimbs,) uint32."""
    if x < 0:
        raise ValueError("negative")
    out = np.zeros(nlimbs, dtype=np.uint32)
    for i in range(nlimbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit in limbs")
    return out


def ints_to_limbs(xs, nlimbs: int = NLIMBS) -> np.ndarray:
    """Batch of ints -> (nlimbs, B) uint32 (limb-major)."""
    out = np.zeros((nlimbs, len(xs)), dtype=np.uint32)
    for j, x in enumerate(xs):
        out[:, j] = int_to_limbs(x, nlimbs)
    return out


def limbs_to_int(a) -> int:
    """(nlimbs,) limbs -> Python int."""
    a = np.asarray(a)
    val = 0
    for i in range(a.shape[0] - 1, -1, -1):
        val = (val << LIMB_BITS) | int(a[i])
    return val


def limbs_to_ints(a) -> list:
    """(nlimbs, B) -> list of B Python ints."""
    a = np.asarray(a)
    return [limbs_to_int(a[:, j]) for j in range(a.shape[1])]


# ---------------------------------------------------------------------------
# Carry propagation
# ---------------------------------------------------------------------------


def carry_u32(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Unsigned carry propagation along axis 0.

    Input limbs may be anything < 2^32 - 2^19 (so limb + incoming carry
    cannot wrap). Returns (canonical limbs, carry_out).
    """
    c0 = jnp.zeros(x.shape[1:], dtype=jnp.uint32)

    def body(c, xi):
        t = xi + c
        return t >> LIMB_BITS, t & LIMB_MASK

    c, ys = lax.scan(body, c0, x)
    return ys, c


def carry_i32(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Signed carry propagation along axis 0 (arithmetic shift = floor div,
    so negative limbs borrow correctly). Returns (canonical limbs in
    [0, 2^13), signed carry_out)."""
    c0 = jnp.zeros(x.shape[1:], dtype=jnp.int32)

    def body(c, xi):
        t = xi + c
        return t >> LIMB_BITS, t & LIMB_MASK

    c, ys = lax.scan(body, c0, x)
    return ys, c


# ---------------------------------------------------------------------------
# Montgomery context
# ---------------------------------------------------------------------------


class MontCtx:
    """Precomputed Montgomery constants for an odd modulus m < 2^256.

    R = 2^260 (one limb-width above 256 bits). All device constants are
    numpy arrays; they become XLA constants at trace time.
    """

    def __init__(self, modulus: int):
        if modulus % 2 == 0:
            raise ValueError("modulus must be odd")
        self.m = modulus
        r = 1 << RADIX_BITS
        self.m_limbs = int_to_limbs(modulus)
        self.m_limbs_i32 = self.m_limbs.astype(np.int32)
        self.r2_limbs = int_to_limbs((r * r) % modulus)
        self.one_mont = int_to_limbs(r % modulus)
        self.one = int_to_limbs(1)
        # m' = -m^-1 mod 2^13 for the REDC quotient digit.
        self.m0inv = np.uint32((-pow(modulus, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
        # k*m for the borrow-free subtraction path (k in 1..8).
        self.km_limbs_i32 = {
            k: int_to_limbs(k * modulus).astype(np.int32) for k in range(1, 9)
        }


def cond_sub(x: jax.Array, m_limbs_i32: np.ndarray) -> jax.Array:
    """One conditional subtract: x - m if x >= m else x (values canonical)."""
    d = x.astype(jnp.int32) - m_limbs_i32.reshape((NLIMBS,) + (1,) * (x.ndim - 1))
    limbs, c = carry_i32(d)
    keep = c < 0  # borrow out -> x < m
    return jnp.where(keep, x, limbs.astype(jnp.uint32))


def reduce_canonical(x: jax.Array, ctx: MontCtx, times: int) -> jax.Array:
    """Reduce a value known to be < (times+1)*m to canonical via repeated
    conditional subtraction (static count, data-dependent selects only)."""
    for _ in range(times):
        x = cond_sub(x, ctx.m_limbs_i32)
    return x


# ---------------------------------------------------------------------------
# Core multiply (CIOS Montgomery with lazy carries)
# ---------------------------------------------------------------------------


def mont_mul(ctx: MontCtx, a: jax.Array, b: jax.Array, nreduce: int = 1) -> jax.Array:
    """Montgomery product a*b*R^-1 mod m on canonical-limb inputs.

    Inputs may have value up to 4m (limbs canonical); with inputs <= c1*m,
    c2*m the pre-reduction output is < m*(1 + c1*c2*m/2^260), so nreduce=1
    suffices for c1*c2 <= 16. Shapes: (NLIMBS, *batch) uint32.
    """
    batch_shape = a.shape[1:]
    m = jnp.asarray(ctx.m_limbs).reshape((NLIMBS,) + (1,) * len(batch_shape))
    m0inv = jnp.uint32(ctx.m0inv)
    t0 = jnp.zeros((NLIMBS,) + batch_shape, dtype=jnp.uint32)

    def body(i, t):
        ai = lax.dynamic_index_in_dim(a, i, axis=0, keepdims=True)  # (1, *batch)
        u = t + ai * b + (((t[0] + ai[0] * b[0]) & LIMB_MASK) * m0inv & LIMB_MASK) * m
        # u[0] is divisible by 2^13 by construction; shift down one limb.
        carry0 = u[0] >> LIMB_BITS
        shifted = jnp.concatenate(
            [
                (u[1] + carry0)[None],
                u[2:],
                jnp.zeros((1,) + batch_shape, dtype=jnp.uint32),
            ],
            axis=0,
        )
        return shifted

    t = lax.fori_loop(0, NLIMBS, body, t0, unroll=CIOS_UNROLL)
    limbs, c = carry_u32(t)
    del c  # value < 2m for canonical inputs; carry-out is provably zero
    return reduce_canonical(limbs, ctx, nreduce)


def add_raw(a: jax.Array, b: jax.Array) -> jax.Array:
    """Limb-canonical addition WITHOUT modular reduction (value = a+b)."""
    limbs, c = carry_u32(a + b)
    return limbs  # caller guarantees value < 2^260 (c == 0)


def sub_mod(ctx: MontCtx, a: jax.Array, b: jax.Array, b_bound: int, nreduce: int) -> jax.Array:
    """a - b + b_bound*m, carried in int32 (no borrow underflow), then
    reduced with `nreduce` conditional subtracts."""
    kp = ctx.km_limbs_i32[b_bound].reshape((NLIMBS,) + (1,) * (a.ndim - 1))
    t = a.astype(jnp.int32) + kp - b.astype(jnp.int32)
    limbs, c = carry_i32(t)
    return reduce_canonical(limbs.astype(jnp.uint32), ctx, nreduce)


def to_mont(ctx: MontCtx, x: jax.Array, nreduce: int = 1) -> jax.Array:
    return mont_mul(ctx, x, _bc(ctx.r2_limbs, x), nreduce=nreduce)


def from_mont(ctx: MontCtx, x: jax.Array) -> jax.Array:
    return mont_mul(ctx, x, _bc(ctx.one, x))


def _bc(const_limbs: np.ndarray, like: jax.Array) -> jax.Array:
    """Broadcast a (NLIMBS,) numpy constant against like's batch dims."""
    return jnp.broadcast_to(
        jnp.asarray(const_limbs).reshape((NLIMBS,) + (1,) * (like.ndim - 1)),
        like.shape,
    )


def mont_pow(ctx: MontCtx, x: jax.Array, exponent: int) -> jax.Array:
    """x^exponent in the Montgomery domain, square-and-multiply over the
    (static) exponent bits via lax.scan — the trace stays small and the
    schedule is branch-free (select instead of branch on each bit)."""
    nbits = exponent.bit_length()
    bits = np.array(
        [(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)], dtype=np.bool_
    )
    acc0 = _bc(ctx.one_mont, x)

    def body(acc, bit):
        acc = mont_mul(ctx, acc, acc)
        acc_x = mont_mul(ctx, acc, x)
        return jnp.where(bit, acc_x, acc), None

    acc, _ = lax.scan(body, acc0, jnp.asarray(bits))
    return acc


def eq_limbs(a: jax.Array, b: jax.Array) -> jax.Array:
    """Limbwise equality reduced over axis 0 -> bool (*batch)."""
    return jnp.all(a == b, axis=0)


def is_zero(a: jax.Array) -> jax.Array:
    return jnp.all(a == 0, axis=0)
